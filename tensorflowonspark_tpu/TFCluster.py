"""Driver-side cluster lifecycle API.

Capability-parity with /root/reference/tensorflowonspark/TFCluster.py: validate
the cluster template, start the reservation server, launch one node per
executor through the execution backend, block until the cluster assembles, and
expose ``train`` / ``inference`` / ``shutdown``.

TPU-native differences (SURVEY.md §7):

* the assembled reservations define a **jax.distributed world** (coordinator
  address + process ids) instead of a TF ClusterSpec/TF_CONFIG;
* ``ps`` nodes are accepted for API compatibility but do no training work —
  sync data parallelism over ICI replaces both MultiWorkerMirroredStrategy and
  ParameterServerStrategy (SURVEY.md §2.6);
* works against a real ``pyspark.SparkContext`` or the bundled local
  multi-process backend (:mod:`tensorflowonspark_tpu.backends.local`).
"""

import logging
import os
import random
import secrets
import threading
import time as _time

from tensorflowonspark_tpu import TFSparkNode, TFManager, chaos, reservation, resilience
from tensorflowonspark_tpu import registry as membership
from tensorflowonspark_tpu.obs import aggregate as obs_aggregate
from tensorflowonspark_tpu.obs import flight as obs_flight
from tensorflowonspark_tpu.obs import registry as obs_registry
from tensorflowonspark_tpu.obs import tracing as obs_tracing

logger = logging.getLogger(__name__)


class InputMode:
    """How the training program ingests data (reference TFCluster.py:43-49)."""

    TENSORFLOW = 0  #: user code reads its own data (GCS/HDFS/tfds) — perf path
    SPARK = 1  #: Spark partitions stream through the executor feed queues


def _worker_rows(cluster_info):
    """Training-participant rows with a reachable channel; the single
    definition of "which nodes count as workers" shared by shutdown,
    completion-wait, and abort (ps/evaluator are driver-managed separately)."""
    return [
        r for r in cluster_info or []
        if r["job_name"] in ("chief", "master", "worker") and r.get("manager_addr")
    ]


def _abort_nodes(cluster_info, authkey, reason):
    """Best-effort abort broadcast to every reachable node channel: posts the
    ``"abort"`` reason (the executor-side watcher kills the jax child) and
    releases parked ps/evaluator control loops. Returns
    {executor_id: (row, mgr)} for the nodes that acknowledged the post."""
    reached = {}
    for row in cluster_info or []:
        if not row.get("manager_addr"):
            continue
        try:
            mgr = TFManager.connect(tuple(row["manager_addr"]), authkey)
            mgr.set("abort", str(reason))
            if row["job_name"] in ("ps", "evaluator"):
                mgr.get_queue("control").put(None, block=False)
            reached[row["executor_id"]] = (row, mgr)
        except Exception as e:
            logger.warning(
                "abort: could not reach %s:%s: %s", row["job_name"], row["task_index"], e
            )
    return reached


class TFCluster:
    """Handle to a running cluster; constructed by :func:`run`."""

    def __init__(self, sc, cluster_info, cluster_meta, input_mode, server, launch_thread, tf_status, num_workers, worker_executor_ids, registry=None):
        self.sc = sc
        self.cluster_info = cluster_info
        self.cluster_meta = cluster_meta
        self.input_mode = input_mode
        self.server = server
        self.launch_thread = launch_thread
        self.tf_status = tf_status
        self.num_workers = num_workers
        self.worker_executor_ids = worker_executor_ids
        self.queues = cluster_meta["queues"]
        # membership truth: constructed by run() (journal-backed when a
        # registry_dir was given); direct constructions get an in-memory one
        if registry is None:
            registry = membership.MembershipRegistry()
            registry.begin_generation(
                {r["executor_id"]: (r["job_name"], r["task_index"]) for r in cluster_info or []}
            )
        self.registry = registry
        for row in cluster_info or []:
            # idempotent: the reservation server already joined registered
            # rows; this covers directly-constructed clusters
            self.registry.join(
                row["executor_id"], job_name=row["job_name"], task_index=row["task_index"]
            )
        self._monitor_stop = None
        self._start_monitor()

    # -- failure watchdog ------------------------------------------------------

    def _start_monitor(self, interval=None, stale_secs=None):
        """Driver-side watchdog, registry-driven: every liveness signal is a
        lease transition on :attr:`registry`, and failure is lease *expiry*
        (VERDICT r2 item 7; the reference only polled error queues from feed
        tasks and at teardown, TFCluster.py:136-144,178-183).

        Signals, in priority order per node: (a) the error queue (peeked
        non-destructively — a posted traceback stays visible to the shutdown
        path), (b) a final ``child_status`` → ``registry.leave`` (clean
        release), (c) the child heartbeat counter → ``registry.renew`` —
        renewal happens only when the counter *advances*, so a SIGKILLed
        child's frozen counter stops renewing and its lease expires after
        the TTL (``TOS_HEARTBEAT_STALE``). Beat delivery is tiered: nodes
        covered by a live heartbeat-aggregation window
        (:func:`registry.plan_aggregation_tree`) are renewed from the
        aggregator's summary — O(sqrt N) driver sockets — and fall back to
        direct channel polls when their aggregator goes quiet. Expiries land
        in ``tf_status`` (checked by feeders, the shutdown join loop, and
        :meth:`check_errors`) with the executor id in the message, which is
        what ``elastic.classify_failure`` attributes ``lease_expired``
        events from.

        The ``control.driver_crash`` chaos site is consulted here: firing it
        discards the in-memory registry without a parting commit and
        recovers a fresh one from the journal, exactly as a restarted driver
        process would (:meth:`_simulate_driver_restart`).
        """
        interval = interval or float(os.environ.get("TOS_MONITOR_INTERVAL", "3"))
        stale_secs = stale_secs or float(os.environ.get("TOS_HEARTBEAT_STALE", "30"))
        self.registry.ttl = float(stale_secs)
        stop = threading.Event()
        self._monitor_stop = stop
        channels = {}
        rows_by_eid = {
            r["executor_id"]: r for r in self.cluster_info or [] if r.get("manager_addr")
        }
        tree = (
            membership.plan_aggregation_tree(rows_by_eid.values())
            if membership.aggregation_enabled(len(rows_by_eid))
            else {}
        )
        window_secs = membership.WINDOW_SECS
        # a window is live while its counter keeps changing; after this long
        # without a change the aggregator is presumed dead and its members
        # fall back to direct polls
        window_horizon = 3.0 * window_secs + interval
        window_state = {}  # aggregator eid -> (window counter, monotonic seen)

        def _connect(eid):
            import socket as _socket

            mgr = channels.get(eid)
            if mgr is None:
                # cheap bounded reachability probe first: BaseManager.connect
                # has no timeout, and one unreachable (NAT'd) node must not
                # stall the single monitor thread for the OS connect timeout
                # every cycle
                addr = tuple(rows_by_eid[eid]["manager_addr"])
                with _socket.create_connection(addr, timeout=2):
                    pass
                mgr = TFManager.connect(addr, self.cluster_meta["authkey"])
                channels[eid] = mgr
            return mgr

        def _node_error(eid):
            """Fetch a posted traceback from one node (non-destructive)."""
            row = rows_by_eid[eid]
            tb = TFSparkNode.peek_error(_connect(eid))
            if tb is not None:
                return "node {}:{} failed:\n{}".format(row["job_name"], row["task_index"], tb)
            return None

        def _preempted_problem(eid):
            """A child committed a ``preempted`` parting status: its durable
            ``leave`` above IS the lease handoff; the message wording (the
            word "preempted" + "(executor N)") is what
            ``elastic.classify_failure`` attributes ``preemption`` events
            from — first-class, never blacklisted, never budget-charged."""
            row = rows_by_eid.get(eid)
            job, task = (
                (row["job_name"], row["task_index"]) if row else ("worker", "?")
            )
            obs_tracing.event(
                "node_preempted", executor=eid, job=job, task_index=task
            )
            return "node {}:{} preempted (executor {})".format(job, task, eid)

        def _poll_direct(eid):
            """Direct channel poll: error → status(leave) → beat(renew)."""
            problem = _node_error(eid)
            if problem is not None:
                return problem
            mgr = _connect(eid)
            status = mgr.get("child_status")
            if status is not None:
                self.registry.leave(eid, reason=str(status))
                if str(status) == "preempted":
                    return _preempted_problem(eid)
                return None
            self.registry.renew(eid, beat=mgr.get("heartbeat"))
            return None

        def _apply_window(agg_eid):
            """Read one aggregator's window summary; returns the set of
            member eids it covered (empty → stale, members poll directly)."""
            import json as _json

            raw = _connect(agg_eid).get(membership.WINDOW_KEY)
            if not raw:
                return set(), {}
            summary = _json.loads(raw)
            now = _time.monotonic()
            prev = window_state.get(agg_eid)
            if prev is None or prev[0] != summary.get("window"):
                window_state[agg_eid] = (summary.get("window"), now)
            elif now - prev[1] > window_horizon:
                return set(), {}  # aggregator stopped publishing
            # members the summary carries nothing for are NOT covered: the
            # aggregator could not reach their channel (or the child has not
            # beaten yet), and renewing here would keep a dead executor's
            # lease alive forever. They fall through to the direct-poll
            # path, where an unreachable channel stops renewals and the
            # lease expires after the TTL.
            statuses, beats, flagged = membership.window_coverage(
                summary, [e for e in tree[agg_eid] if e in rows_by_eid]
            )
            covered, problems = set(), {}
            for eid in flagged:
                try:
                    problem = _node_error(eid)
                except Exception:
                    continue
                if problem is not None:
                    problems[eid] = problem
            for eid, status in statuses.items():
                if eid in problems:
                    continue
                covered.add(eid)
                self.registry.leave(eid, reason=str(status))
                if str(status) == "preempted":
                    problems[eid] = _preempted_problem(eid)
            for eid, beat in beats.items():
                if eid in problems:
                    continue
                covered.add(eid)
                self.registry.renew(eid, beat=beat)
            return covered, problems

        registry_errors = obs_registry.counter(
            "watchdog_registry_errors_total",
            help="watchdog registry operations that raised (journal I/O, fencing)",
        )

        def _monitor():
            reported = set()
            poll_errors_logged = set()  # log an unreachable channel once per node
            registry_error_logged = [False]  # log a registry I/O failure once

            def _registry_failed(e, what):
                """A registry operation raised inside the watchdog loop: count
                it, log once, and keep the thread alive — an unwritable journal
                dir must not silently end all failure detection."""
                registry_errors.inc()
                if not registry_error_logged[0]:
                    registry_error_logged[0] = True
                    logger.warning("watchdog: %s failed: %s", what, e)

            while not stop.wait(interval):
                if chaos.active and chaos.fire("control.driver_crash"):
                    try:
                        self._simulate_driver_restart()
                    except Exception as e:
                        _registry_failed(e, "driver-restart recovery")
                covered, problems = set(), {}
                for agg_eid in tree:
                    try:
                        got, agg_problems = _apply_window(agg_eid)
                    except Exception:
                        continue  # aggregator unreachable: members poll directly
                    covered |= got
                    problems.update(agg_problems)
                for eid in rows_by_eid:
                    if eid in covered or eid in reported or eid in problems:
                        continue
                    try:
                        problem = _poll_direct(eid)
                    except Exception as e:
                        # channel unreachable: shutdown's concern — but count
                        # it, so a node the watchdog can never see is visible
                        obs_registry.counter(
                            "watchdog_poll_errors_total",
                            help="watchdog node polls that raised (channel unreachable)",
                        ).inc()
                        if eid not in poll_errors_logged:
                            poll_errors_logged.add(eid)
                            row = rows_by_eid[eid]
                            logger.debug(
                                "watchdog: cannot poll node %s:%s: %s",
                                row["job_name"], row["task_index"], e,
                            )
                        continue
                    poll_errors_logged.discard(eid)
                    if problem:
                        problems[eid] = problem
                try:
                    expired = self.registry.expire_stale()
                except membership.StaleEpochError as e:
                    # a newer driver generation fenced this registry: every
                    # further durable write will refuse, so surface the
                    # takeover to the job instead of dying silently
                    expired = []
                    _registry_failed(e, "lease expiry")
                    self.tf_status.setdefault(
                        "error", "watchdog registry fenced: {}".format(e)
                    )
                except Exception as e:
                    expired = []
                    _registry_failed(e, "lease expiry")
                for eid, age in expired:
                    if eid in reported or eid in problems:
                        continue
                    row = rows_by_eid.get(eid)
                    job, task = (
                        (row["job_name"], row["task_index"]) if row else ("worker", "?")
                    )
                    # wording carries three contracts: "stopped heartbeating"
                    # (historical operator-facing phrasing), "lease expired"
                    # (elastic's lease_expired classification), and
                    # "(executor N)" (elastic's id attribution)
                    problems[eid] = (
                        "node {}:{} stopped heartbeating: lease expired after "
                        "{:.0f}s without renewal (executor {})".format(job, task, age, eid)
                    )
                    # the watchdog verdict is a black-box moment: stamp it on
                    # the trace (the merged timeline shows the kill -> expiry
                    # -> relaunch chain) and flush the driver's flight shard
                    obs_tracing.event(
                        "lease_expired", executor=eid, job=job, task_index=task,
                        age_s=round(age, 3),
                    )
                    obs_flight.dump("lease_expired:executor{}".format(eid))
                for eid in sorted(p for p in problems if p not in reported):
                    reported.add(eid)
                    logger.error("watchdog: %s", problems[eid])
                    self.tf_status.setdefault("error", problems[eid])

        threading.Thread(target=_monitor, name="tos-watchdog", daemon=True).start()

    def _simulate_driver_restart(self):
        """``control.driver_crash``: drop the registry with no parting commit
        (a crash does not say goodbye) and bring up a replacement the way a
        restarted driver process would — journal replay, live-lease
        re-adoption, epoch bump (fencing any stale writer). Executors are
        untouched: their children keep training, their leases keep renewing
        against the recovered registry. Rows the journal had not yet
        captured (or with no journal at all) are re-adopted from the
        assembly snapshot — their in-flight REG already proved them alive."""
        old = self.registry
        logger.warning(
            "chaos: control.driver_crash — dropping registry (epoch %d) and "
            "recovering from journal %s", old.epoch, old.journal_dir,
        )
        old.crash()
        self.registry = membership.MembershipRegistry.recover(
            old.journal_dir, ttl=old.ttl, fallback_epoch=old.epoch
        )
        for row in self.cluster_info or []:
            if row["executor_id"] not in self.registry.members():
                self.registry.join(
                    row["executor_id"],
                    job_name=row["job_name"],
                    task_index=row["task_index"],
                )
        obs_registry.counter(
            "registry_driver_restarts_total",
            help="driver registry crash/recover cycles (chaos or real)",
        ).inc()

    def _current_rows(self):
        """Freshest node rows. Real Spark retries a failed launch task, and
        the retry re-registers with a NEW channel address (idempotent REG
        replaces the row server-side, reservation.Reservations.add) — so for
        teardown/abort purposes the reservation server's live view supersedes
        the assembly-time ``cluster_info`` snapshot; otherwise an abort posted
        to a crashed node's OLD channel would miss the retry's fresh child."""
        try:
            rows = self.server.reservations.get()
            if rows:
                return rows
        except Exception:
            pass
        return self.cluster_info

    def check_errors(self):
        """Raise if the watchdog (or the launch path) recorded a node
        failure; cheap enough to call between training epochs."""
        if self.tf_status.get("error"):
            raise RuntimeError("cluster failed: {}".format(self.tf_status["error"]))

    # -- data plane -----------------------------------------------------------

    def train(self, dataRDD, num_epochs=0, feed_timeout=600, qname="input"):
        """Feed data to the cluster for training (InputMode.SPARK only).

        ``dataRDD`` may be (reference TFCluster.py:63-94):

        * an RDD — fed for ``num_epochs`` epochs; blocks until consumed or
          training requests a stop;
        * a DStream (anything with ``foreachRDD``) — every micro-batch is fed
          as it arrives; returns immediately (the streaming context drives
          the feeding; stop via ``shutdown(ssc)`` or a STOP on the control
          plane, reference TFCluster.py:83-85);
        * an iterable/generator of RDDs — micro-batches fed sequentially
          until exhausted or :attr:`stop_requested`.
        """
        assert self.input_mode == InputMode.SPARK, "train() requires InputMode.SPARK"
        assert dataRDD is not None, "dataRDD is required"
        task = TFSparkNode.train(
            self.cluster_info, self.cluster_meta, feed_timeout=feed_timeout, qname=qname
        )

        if hasattr(dataRDD, "foreachRDD"):  # DStream-equivalent
            logger.info("feeding training data from a stream (micro-batches)")

            # exactly ONE positional arg: pyspark's foreachRDD inspects
            # co_argcount and passes (batch_time, rdd) to 2-arg functions —
            # and defaulted params count, so `task` must be a closure
            def _feed_micro_batch(rdd):
                if not self.stop_requested:
                    rdd.foreachPartition(task)

            dataRDD.foreachRDD(_feed_micro_batch)
            return

        if not hasattr(dataRDD, "foreachPartition"):  # iterable of RDDs
            logger.info("feeding training data from an RDD iterator")
            for rdd in dataRDD:
                if self.stop_requested:
                    logger.info("stop requested; ending stream feed")
                    break
                rdd.foreachPartition(task)
            return

        logger.info("feeding training data (epochs=%s)", num_epochs)
        assert num_epochs is None or num_epochs >= 0, "num_epochs cannot be negative"
        if not num_epochs:
            # unspecified: feed "many" epochs and rely on the training loop to
            # terminate the feed at its target step count (reference
            # TFCluster.py:88-92 picks the same arbitrary 10)
            num_epochs = 10
        rdd = dataRDD
        if num_epochs > 1:
            rdd = self.sc.union([dataRDD] * num_epochs)
        rdd.foreachPartition(task)

    def inference(self, dataRDD, feed_timeout=600, qname="input", qname_out="output"):
        """Feed an RDD for inference; returns a (lazy) RDD of results with a
        1:1 input:output contract (reference TFCluster.py:96-115)."""
        assert self.input_mode == InputMode.SPARK, "inference() requires InputMode.SPARK"
        assert dataRDD is not None, "dataRDD is required"
        return dataRDD.mapPartitions(
            TFSparkNode.inference(
                self.cluster_info, self.cluster_meta, feed_timeout=feed_timeout,
                qname=qname, qname_out=qname_out,
            )
        )

    # -- teardown -------------------------------------------------------------

    @property
    def stop_requested(self):
        """True once any node (or an external tool like utils/stop_cluster)
        sent STOP on the control plane — streaming feeds poll this."""
        return self.server.stop_requested

    def shutdown(self, ssc=None, grace_secs=0, timeout=259200):
        """Stop the cluster: end-of-feed to every worker, wait for the launch
        job, stop driver-managed roles, surface any node error
        (reference TFCluster.py:117-202; the 3-day default timeout mirrors
        its SIGALRM watchdog, TFCluster.py:136-144).

        ``ssc``: a streaming context feeding this cluster — stopped
        gracefully first so queued micro-batches drain before the end-of-feed
        markers go out (reference streaming-aware shutdown,
        mnist_spark_streaming.py:141-144).
        """
        logger.info("shutting down cluster")
        if ssc is not None:
            try:
                ssc.stop(stopSparkContext=False, stopGraceFully=True)
            except TypeError:  # non-pyspark signature
                ssc.stop()

        role_errors = []
        try:
            if self.input_mode == InputMode.SPARK:
                self._shutdown_workers(grace_secs)
        finally:
            # even when a worker surfaced an error, stop driver-managed roles,
            # reap the launch job, and release the reservation server — a
            # long-lived driver must be able to retry cluster.run without
            # leaking server threads/sockets. ps/evaluator error queues are
            # peeked here: nothing else ever reads them (workers surface
            # their errors through the feed tasks / _shutdown_workers).
            for row in self.cluster_info:
                if row.get("manager_addr"):
                    try:
                        mgr = TFManager.connect(tuple(row["manager_addr"]), self.cluster_meta["authkey"])
                        if row["job_name"] in ("ps", "evaluator"):
                            tb = TFSparkNode.peek_error(mgr)
                            if tb is not None:
                                role_errors.append(
                                    "node {}:{}:\n{}".format(row["job_name"], row["task_index"], tb)
                                )
                        mgr.get_queue("control").put(None, block=True)
                    except Exception as e:
                        logger.warning(
                            "could not stop %s:%s at %s: %s",
                            row["job_name"], row["task_index"], row["manager_addr"], e,
                        )
            # poll-join so a watchdog-detected node failure cuts the wait
            # short instead of riding out the full timeout

            deadline = _time.time() + timeout
            while self.launch_thread.is_alive() and _time.time() < deadline:
                self.launch_thread.join(timeout=1.0)
                if self.tf_status.get("error"):
                    break
            self.server.stop()
            if self._monitor_stop is not None:
                self._monitor_stop.set()
        if self.launch_thread.is_alive() and not self.tf_status.get("error"):
            raise RuntimeError("cluster did not shut down within {}s".format(timeout))
        if self.tf_status.get("error"):
            raise RuntimeError(
                "cluster failed: {}{}".format(
                    self.tf_status["error"],
                    "\nadditionally, driver-managed role error(s):\n" + "\n".join(role_errors)
                    if role_errors
                    else "",
                )
            )
        if role_errors:
            raise RuntimeError("error(s) in driver-managed roles:\n" + "\n".join(role_errors))
        logger.info("cluster shut down cleanly")

    def _shutdown_workers(self, grace_secs):
        """Post end-of-feed directly to every worker's queues over its TCP
        channel and wait for each jax child to wind down.

        Deterministic replacement for the reference's shutdown-by-Spark-tasks
        (TFCluster.py:174-176 + TFSparkNode.py:534-588), which relied on the
        scheduler spreading exactly one quick task per executor; here every
        worker is addressed explicitly, so no node can miss (or double-get)
        its end-of-feed marker.

        When a worker's channel is NOT reachable from the driver (NAT'd real
        clusters: executor TCP ports are often driver-opaque), shutdown falls
        back to the reference's design — one
        :class:`~tensorflowonspark_tpu.TFSparkNode._ShutdownPartitionTask`
        scattered per executor, each posting end-of-feed over its own
        executor-local channel.
        """
        workers = _worker_rows(self.cluster_info)
        channels = []
        unreachable = []
        for row in workers:
            try:
                mgr = TFManager.connect(tuple(row["manager_addr"]), self.cluster_meta["authkey"])
                mgr.get_queue("input").put(None, block=True)
                channels.append((row, mgr))
            except Exception as e:
                logger.warning(
                    "could not reach %s:%s for shutdown: %s", row["job_name"], row["task_index"], e
                )
                unreachable.append(row)
        if unreachable:
            self._shutdown_by_spark_tasks(grace_secs, unreachable)
        errors = []
        # one absolute budget shared across every channel wait
        deadline = resilience.Deadline(max(grace_secs, 60))
        tick = resilience.Backoff(base=0.1, factor=1.0, max_delay=0.1, jitter=0.0)
        for row, mgr in channels:
            for _ in tick.attempts(deadline=deadline):
                if mgr.get("child_status") is not None:
                    break
            try:
                eq = mgr.get_queue("error")
                if not eq.empty():
                    tb = eq.get(block=False)
                    eq.put(tb)  # keep visible (reference peek-and-requeue,
                    eq.task_done()  # TFSparkNode.py:576-582)
                    errors.append("node {}:{}:\n{}".format(row["job_name"], row["task_index"], tb))
            except Exception:
                pass
            # drain whatever the child never consumed: shared-memory chunks
            # in an abandoned queue would otherwise pin /dev/shm RAM until
            # the day-scale janitor (a dead child can't unlink its segments)
            try:
                TFSparkNode.drain_queue(mgr, "input")
            except Exception:
                pass
            mgr.set("state", "stopped")
        if errors:
            raise RuntimeError("error(s) in cluster nodes:\n" + "\n".join(errors))

    def _shutdown_by_spark_tasks(self, grace_secs, rows):
        """Reference-style shutdown scatter (TFCluster.py:174-176): one Spark
        task per executor posts end-of-feed over the executor-LOCAL channel —
        the path that still works when executor TCP is unreachable from the
        driver. Tasks landing on already-stopped nodes are no-ops (an extra
        end-of-feed marker in a drained queue)."""
        logger.warning(
            "falling back to Spark-task shutdown for %d unreachable worker(s): %s",
            len(rows),
            ", ".join("{}:{}".format(r["job_name"], r["task_index"]) for r in rows),
        )
        n = max(self.num_workers, len(rows))
        try:
            # local backend: pin task i to executor i so every node gets its
            # marker; pyspark lacks the kwarg and relies on the scheduler
            # spreading quick tasks (the reference's assumption)
            shutdown_rdd = self.sc.parallelize(range(n), n, pin_to_executors=True)
        except TypeError:
            shutdown_rdd = self.sc.parallelize(range(n), n)
        shutdown_rdd.foreachPartition(
            TFSparkNode.shutdown(self.cluster_info, self.cluster_meta, grace_secs=grace_secs)
        )

    def abort(self, reason="aborted by driver", wait_secs=60):
        """Forcibly tear the cluster down so the same SparkContext can
        relaunch: post an abort reason on every node channel (the
        executor-side abort watcher kills the jax child, freeing the executor
        slot), release parked ps/evaluator tasks, then wait for the nodes to
        report stopped.

        Unlike :meth:`shutdown` this never raises on node errors — it is the
        teardown half of :func:`run_with_recovery`, called when a failure has
        already been detected. The reference stopped at detection (SystemExit
        on the feed path, reference TFCluster.py:178-183); deterministic
        reclaim + relaunch is the TPU-native recovery story.
        """

        self.tf_status.setdefault("error", str(reason))
        reached = _abort_nodes(self._current_rows(), self.cluster_meta["authkey"], reason)
        pending = dict(reached)
        tick = resilience.Backoff(base=0.5, factor=1.0, max_delay=0.5, jitter=0.0)
        for _ in tick.attempts(deadline=resilience.Deadline(wait_secs)):
            for eid in list(pending):
                row, mgr = pending[eid]
                try:
                    if mgr.get("state") == "stopped":
                        pending.pop(eid)
                except Exception:
                    pending.pop(eid)  # channel gone: the node is down
            if not pending:
                break
        for eid, (row, _) in pending.items():
            logger.warning(
                "abort: node %s:%s did not confirm stop within %ss",
                row["job_name"], row["task_index"], wait_secs,
            )
        self.launch_thread.join(timeout=wait_secs)
        self.server.stop()
        if self._monitor_stop is not None:
            self._monitor_stop.set()
        logger.info("cluster aborted: %s", reason)

    def preempt(self, reason="preempted by driver", workers=None):
        """Post a preemption *warning* on worker channels — the
        driver-initiated sibling of a platform SIGTERM grace window.

        Each jax child's heartbeat notices the ``preempt`` key within one
        beat and runs its warned-shutdown path: drain in-flight async
        checkpoints, flush metrics, commit a ``preempted`` parting status
        (which the watchdog turns into a durable registry ``leave``), and
        exit clean. Unlike :meth:`abort` this is a *handoff*, not a
        teardown: the recovery ladder classifies the resulting loss as a
        first-class ``preemption`` (no blacklist, no restart-budget charge)
        and relaunches — the regrow path uses exactly this to restart onto
        a larger mesh without losing the step in flight.

        ``workers`` restricts the warning to specific executor ids.
        Returns the executor ids the warning reached.
        """
        posted = []
        for row in _worker_rows(self._current_rows()):
            if workers is not None and row["executor_id"] not in workers:
                continue
            try:
                mgr = TFManager.connect(
                    tuple(row["manager_addr"]), self.cluster_meta["authkey"]
                )
                mgr.set("preempt", str(reason))
                posted.append(row["executor_id"])
            except Exception as e:
                logger.warning(
                    "preempt: could not reach %s:%s: %s",
                    row["job_name"], row["task_index"], e,
                )
        if posted:
            logger.info(
                "preemption warning posted to executors %s: %s", posted, reason
            )
        return posted

    def wait_for_completion(self, poll_secs=1.0, timeout=None):
        """Block until every worker node retires (channel state ``"stopped"``)
        or a failure is recorded in ``tf_status`` (InputMode.TENSORFLOW).
        Returns True on completion/failure, False on timeout.

        Waiting on the *launch thread* instead would hang any cluster with
        ps/evaluator roles: those tasks park on their control queues until
        :meth:`shutdown` posts the release, so the launch job outlives
        training by design (reference ps wait loop, TFSparkNode.py:373-390).
        Worker channel state is the true completion signal; launch-thread
        exit also ends the wait. On a NAT'd cluster whose worker channels
        the driver cannot reach AND with a parked ps/evaluator role, neither
        signal can fire — pass ``timeout`` to bound the wait there.
        """

        mgrs = {}  # keyed by channel address: a task retry re-registers anew
        tick = resilience.Backoff(base=poll_secs, factor=1.0, max_delay=poll_secs, jitter=0.0)
        for _ in tick.attempts(deadline=resilience.Deadline(timeout)):
            if self.tf_status.get("error"):
                return True
            if not self.launch_thread.is_alive():
                return True
            done = True
            # rows re-read each cycle: a Spark task retry may have replaced a
            # node's channel address server-side mid-wait
            for row in _worker_rows(self._current_rows()):
                addr = tuple(row["manager_addr"])
                try:
                    mgr = mgrs.get(addr)
                    if mgr is None:
                        mgr = mgrs[addr] = TFManager.connect(
                            addr, self.cluster_meta["authkey"]
                        )
                    if mgr.get("state") != "stopped":
                        done = False
                except Exception:
                    mgrs.pop(addr, None)
                    done = False  # unreachable: rely on launch-thread exit
            if done:
                return True
        return False

    # -- observability --------------------------------------------------------

    def tensorboard_url(self):
        """URL of the profiler/TensorBoard server on the chief, if one was
        launched (reference TFCluster.py:204-209)."""
        for row in self.cluster_info:
            if row.get("tb_port"):
                return "http://{}:{}".format(row["host"], row["tb_port"])
        return None

    def metrics(self, include_driver=True):
        """One merged metrics snapshot for the whole cluster.

        Reads each reachable node channel's published snapshots (the jax
        child's ``obs_snapshot`` lane plus the feed tasks' accumulated
        ``obs_feeder`` lane), merges them with the driver's own registry
        (reservation timings, client retries), and returns the aggregation
        plane's snapshot dict with one extra key: ``"nodes"`` maps
        ``"job:index"`` to that node's own merged view, so per-node detail
        survives the cluster-level summing of counters/gauges.

        Unreachable channels (NAT'd executors) simply contribute nothing —
        same degradation story as :meth:`_shutdown_workers`. The result is
        JSON-able and feeds both exporters directly::

            obs.exporter.MetricsHTTPServer(cluster.metrics, port=9100).start()
        """
        snaps = []
        nodes = {}
        for row in self._current_rows() or []:
            if not row.get("manager_addr"):
                continue
            try:
                mgr = TFManager.connect(
                    tuple(row["manager_addr"]), self.cluster_meta["authkey"]
                )
                node_snaps = obs_aggregate.read_channel_snapshots(mgr)
            except Exception as e:
                logger.debug(
                    "metrics: channel %s:%s unreachable: %s",
                    row["job_name"], row["task_index"], e,
                )
                continue
            if node_snaps:
                merged_node = obs_aggregate.merge_snapshots(node_snaps)
                nodes["{}:{}".format(row["job_name"], row["task_index"])] = merged_node
                snaps.append(merged_node)
        if include_driver:
            snaps.append(obs_registry.snapshot())
        merged = obs_aggregate.merge_snapshots(snaps)
        merged["nodes"] = nodes
        return merged


def run_with_recovery(
    sc,
    map_fun,
    tf_args,
    num_executors,
    max_relaunches=2,
    poll_secs=1.0,
    shutdown_timeout=600,
    completion_timeout=None,
    feed_fn=None,
    **run_kwargs,
):
    """Train with automatic failure recovery: run → detect (watchdog / launch
    error / failed feed) → :meth:`TFCluster.abort` the survivors → relaunch →
    ``map_fun`` resumes from its latest checkpoint.

    The reference stopped at *detection* — on a node error the feed path
    raised and the docs told the operator to resubmit the job (reference
    TFCluster.py:178-183); the hard half (resuming the trajectory from the
    latest checkpoint) was delegated to TF's ``load_weights_on_restart``.
    Here the whole loop is driver-side: ``map_fun`` must pick up from
    ``checkpoint.restore_latest(model_dir)`` when one exists — the
    contract proven end-to-end in ``tests/test_resume.py`` — and this helper
    supplies detection, deterministic teardown, and relaunch around it.
    Resume prefers **manifest-verified** checkpoints: ``restore_latest``
    cheap-checks each candidate against its ``MANIFEST.json`` (written last
    and rename-published by the async engine,
    :mod:`tensorflowonspark_tpu.ckpt`), skipping torn or bitrotten newest
    checkpoints with a logged reason instead of attempting doomed restores;
    if the relaunched cluster has a different worker count,
    ``ckpt.reshard_restore`` maps the checkpoint onto the new mesh.

    Two input modes:

    * ``InputMode.TENSORFLOW`` (the perf path: nodes read their own data) —
      leave ``feed_fn`` unset; each attempt waits for worker completion.
    * ``InputMode.SPARK`` — pass ``feed_fn(cluster)``, the caller's feed
      loop (``cluster.train(...)`` calls). The feed RDD's lineage belongs to
      the caller, so only the caller can re-feed: on a node death mid-feed
      the feed task raises (feed timeout / watchdog), the attempt is
      aborted, and ``feed_fn`` is re-invoked FROM THE START against the
      relaunched cluster — ``map_fun`` resumes from its checkpoint and
      trains on the re-fed stream (use closure state inside ``feed_fn`` for
      partial re-feeds). After ``feed_fn`` returns, ``check_errors()``
      catches failures that raced the feed's completion.

    ``completion_timeout`` bounds each attempt's completion wait for the one
    topology where no completion signal can reach the driver (NAT'd worker
    channels + a parked ps/evaluator keeping the launch job alive — see
    :meth:`TFCluster.wait_for_completion`); on expiry the attempt proceeds
    straight to :meth:`TFCluster.shutdown`, whose Spark-task fallback can
    reach NAT'd nodes. Leave ``None`` for reachable clusters — a legitimate
    training run can take arbitrarily long.

    The attempt loop itself is the **recovery ladder**
    (:func:`tensorflowonspark_tpu.elastic.run_ladder`): failures are
    classified into a :class:`~tensorflowonspark_tpu.elastic.FailureLedger`,
    executors with repeated attributable losses are blacklisted (after a
    preflight health probe), and the relaunch shrinks to the surviving
    capacity — ``map_fun`` resharding onto the smaller mesh via
    ``ckpt.reshard_restore``. Ladder knobs (``min_workers``,
    ``blacklist_after``, ``window_secs``, ``preflight``, ``regrow``) pass
    through ``**run_kwargs``; the defaults reproduce the historical
    behaviour for single transient faults (one failure → full-size
    relaunch).

    Returns the number of relaunches performed (0 = clean first run).
    """
    mode = run_kwargs.get("input_mode", InputMode.SPARK)
    if mode != InputMode.TENSORFLOW and feed_fn is None:
        raise ValueError(
            "run_with_recovery in SPARK mode needs feed_fn=<your feed loop>; "
            "without a feed, use input_mode=InputMode.TENSORFLOW"
        )
    if mode == InputMode.TENSORFLOW and feed_fn is not None:
        raise ValueError("feed_fn requires input_mode=InputMode.SPARK")
    from tensorflowonspark_tpu import elastic

    result = elastic.run_ladder(
        sc,
        map_fun,
        tf_args,
        num_executors,
        max_relaunches=max_relaunches,
        poll_secs=poll_secs,
        shutdown_timeout=shutdown_timeout,
        completion_timeout=completion_timeout,
        feed_fn=feed_fn,
        **run_kwargs,
    )
    return result.relaunches


def build_cluster_template(num_executors, num_ps=0, master_node="chief", eval_node=False,
                           blacklist=None):
    """executor_id → (job_name, task_index), in the reference's role order
    ps → chief → evaluator → worker (TFCluster.py:252-267).

    ``blacklist`` (executor ids) excludes known-bad hosts from the
    assignment: roles are laid onto the first ``num_executors`` ids counting
    from 0 and *skipping* blacklisted ones, so a relaunch after the recovery
    ladder condemns an executor still gets ``num_executors`` healthy nodes
    (:mod:`~tensorflowonspark_tpu.elastic`).
    """
    if master_node is not None and master_node not in ("chief", "master"):
        # catches stringified-None and typos before they become silent
        # do-nothing roles in a live cluster
        raise ValueError(
            "master_node must be 'chief', 'master', or None; got {!r}".format(master_node)
        )
    roles = ["ps"] * num_ps
    if master_node:
        roles.append(master_node)
    if eval_node:
        roles.append("evaluator")
    num_workers = num_executors - len(roles)
    if num_workers < 0 or (num_workers == 0 and not master_node):
        raise ValueError(
            "num_executors={} too small for num_ps={}, master_node={!r}, eval_node={}".format(
                num_executors, num_ps, master_node, eval_node
            )
        )
    roles.extend(["worker"] * num_workers)
    banned = frozenset(blacklist or ())
    template, counters = {}, {}
    executor_id = 0
    for job in roles:
        while executor_id in banned:
            executor_id += 1
        task_index = counters.get(job, 0)
        counters[job] = task_index + 1
        template[executor_id] = (job, task_index)
        executor_id += 1
    return template


def resolve_default_fs(sc):
    """Default filesystem for the cluster: the local backend exposes
    ``defaultFS`` directly; real pyspark answers through the JVM Hadoop conf
    (reference TFCluster.py:271-274)."""
    default_fs = getattr(sc, "defaultFS", None)
    if default_fs is None:
        try:  # real pyspark: ask the Hadoop conf
            default_fs = sc._jsc.hadoopConfiguration().get("fs.defaultFS")
        except Exception:
            default_fs = "file://"
    return default_fs


def run(
    sc,
    map_fun,
    tf_args,
    num_executors,
    num_ps=0,
    tensorboard=False,
    input_mode=InputMode.SPARK,
    log_dir=None,
    driver_ps_nodes=False,
    master_node="chief",
    reservation_timeout=600,
    queues=None,
    eval_node=False,
    env=None,
    jax_distributed=None,
    obs=None,
    blacklist=None,
    registry=None,
    registry_dir=None,
):
    """Start a cluster: one node per executor (reference TFCluster.py:212-380).

    ``env`` is propagated into every jax child process (e.g.
    ``{"JAX_PLATFORMS": "cpu"}`` for CPU test runs). ``jax_distributed``
    controls whether children join a multi-process jax world; default: only
    when more than one training participant exists and no explicit override.
    ``obs`` toggles the observability plane cluster-wide (registry collection
    in children and feed tasks, snapshot publication, ``TFCluster.metrics()``
    content); default: the driver's ``TOS_OBS`` env setting (on unless
    ``TOS_OBS=0``).
    ``blacklist`` (executor ids) excludes known-bad executors: the template
    skips them, the launch RDD never pins a task to them, and the reservation
    server refuses a late registration from one — the recovery ladder's lever
    (:mod:`~tensorflowonspark_tpu.elastic`).
    ``registry`` is an existing
    :class:`~tensorflowonspark_tpu.registry.MembershipRegistry` to reuse
    (the recovery ladder passes one across attempts so the epoch and
    blacklist journal survive relaunches); ``registry_dir`` (env
    ``TOS_REGISTRY_DIR``) backs a fresh registry with an on-disk journal —
    the driver-restart survivability lever. With neither, membership is
    tracked in memory only.
    """
    if obs is None:
        obs = os.environ.get("TOS_OBS", "1") != "0"
    if driver_ps_nodes:
        raise NotImplementedError(
            "driver_ps_nodes: parameter servers have no TPU analogue; ps roles "
            "run on executors for API compatibility only (SURVEY.md §2.6)"
        )
    template = build_cluster_template(num_executors, num_ps, master_node, eval_node,
                                      blacklist=blacklist)
    executor_ids = sorted(template)
    num_workers = sum(1 for job, _ in template.values() if job in ("chief", "master", "worker"))
    worker_executor_ids = [
        eid for eid, (job, _) in template.items() if job in ("chief", "master", "worker")
    ]
    if jax_distributed is None:
        # default: any multi-worker cluster forms a jax.distributed world —
        # including CPU ones, where collectives ride gloo (the test analogue
        # of multi-host ICI/DCN; see TFNodeContext.initialize_distributed)
        jax_distributed = num_workers > 1
    logger.info("cluster template: %s", {e: "{}:{}".format(j, t) for e, (j, t) in template.items()})

    if registry is None:
        registry_dir = registry_dir or os.environ.get("TOS_REGISTRY_DIR") or None
        registry = membership.MembershipRegistry(
            ttl=float(os.environ.get("TOS_HEARTBEAT_STALE", "30")),
            journal_dir=registry_dir,
        )
    registry.begin_generation(template, target_size=num_executors)
    for eid in blacklist or ():
        # one membership truth: the caller's static blacklist is mirrored
        # into (and journaled by) the registry
        registry.blacklist(eid, reason="caller blacklist")

    server = reservation.Server(
        num_executors, expected_ids=executor_ids, blacklist=blacklist,
        registry=registry,
    )
    server_addr = server.start()

    default_fs = resolve_default_fs(sc)

    cluster_meta = {
        "id": random.getrandbits(64),
        "cluster_template": template,
        "num_executors": num_executors,
        "server_addr": server_addr,
        "default_fs": default_fs,
        "queues": list(queues or TFManager.CONTROL_QUEUES),
        "input_mode": "spark" if input_mode == InputMode.SPARK else "tensorflow",
        "authkey": secrets.token_bytes(16),
        "reservation_timeout": reservation_timeout,
        # a driver-installed chaos plan rides the env lane so executors /
        # jax children on OTHER hosts (no shared os.environ) inherit it;
        # an explicit user-provided TOS_CHAOS_PLAN in env wins. The trace
        # context (TOS_TRACE_ID / parent span / TOS_TRACE_DIR) rides the
        # same lane: mint() is idempotent, so a ladder relaunch reuses the
        # trace_id and the whole recovery stays one causal timeline.
        "env": {
            **obs_tracing.mint(proc="driver"),
            **({chaos.ENV_VAR: chaos.plan().to_json()} if chaos.active else {}),
            **dict(env or {}),
        },
        "jax_distributed": bool(jax_distributed),
        "tensorboard": bool(tensorboard),
        "log_dir": log_dir,
        # the driver's feed-lane choice, honored on BOTH halves of the plane
        # (feed tasks capture it at construction; DataFeed.batch_results
        # reads it from ctx.cluster_meta)
        "feed_shm": TFSparkNode.FEED_SHM,
        "obs": bool(obs),
    }

    tf_status = {}
    # partition data = the executor ids to launch (non-contiguous under a
    # blacklist); pinning sends task i to executor executor_ids[i], so a
    # blacklisted executor hosts nothing
    kwargs = (
        {"pin_to_executors": executor_ids} if getattr(sc, "PIN_SUPPORTED", False) else {}
    )
    node_rdd = sc.parallelize(executor_ids, num_executors, **kwargs)
    launch_task = TFSparkNode.run(
        map_fun, tf_args, cluster_meta, cluster_meta["input_mode"], log_dir, cluster_meta["queues"]
    )

    def _start():
        try:
            node_rdd.foreachPartition(launch_task)
        except Exception as e:
            logger.error("node launch failed: %s", e)
            # first error wins (the watchdog may already have recorded the
            # root cause; an abort() records its reason the same way)
            tf_status.setdefault("error", str(e))

    launch_thread = threading.Thread(target=_start, name="tos-cluster-launch", daemon=True)
    launch_thread.start()

    try:
        cluster_info = server.await_reservations(tf_status, timeout=reservation_timeout)

        # duplicate-node sanity check (reference TFCluster.py:352-367)
        eids = [r["executor_id"] for r in cluster_info]
        if sorted(eids) != sorted(template.keys()):
            raise RuntimeError(
                "cluster assembled with wrong executor set: got {} expected {}".format(
                    sorted(eids), sorted(template.keys())
                )
            )
    except BaseException as e:
        # nodes that DID register have already spawned jax children pinning
        # their executor slots — abort them, or a retry of run() on the same
        # SparkContext would starve against our own leak
        try:
            _abort_nodes(
                server.reservations.get(), cluster_meta["authkey"],
                "cluster assembly failed: {}".format(e),
            )
        except Exception:
            pass
        server.stop()  # don't leak the listener thread/socket on failed assembly
        raise
    for row in sorted(cluster_info, key=lambda r: r["executor_id"]):
        logger.info(
            "node: executor=%d %s:%d @ %s:%s chips=%s",
            row["executor_id"], row["job_name"], row["task_index"],
            row["host"], row["port"], (row.get("tpu") or {}).get("num_chips"),
        )
    return TFCluster(
        sc, cluster_info, cluster_meta, input_mode, server, launch_thread, tf_status,
        num_workers, worker_executor_ids, registry=registry,
    )
