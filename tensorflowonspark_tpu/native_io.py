"""ctypes binding for the native TFRecord reader/writer (``native/``).

The bulk-ingest hot path: one FFI call loads and CRC-verifies a whole shard
(``native/tfrecord_io.cc``), and records are sliced out of a single
contiguous buffer — no per-record Python framing work. Falls back silently
to the pure-Python codec in :mod:`tensorflowonspark_tpu.tfrecord` when the
shared library is missing and cannot be built (no compiler).

This replaces the native layer the reference borrowed from others: the
tensorflow-hadoop InputFormat jar (/root/reference/lib/) and TensorFlow's
C++ record_reader — here it is part of the framework itself.
"""

import ctypes
import logging
import os
import subprocess
import threading

from tensorflowonspark_tpu import chaos, resilience
from tensorflowonspark_tpu.store import framing

logger = logging.getLogger(__name__)

#: retry policy for shard reads: network filesystems (gcsfuse, NFS) fail
#: transiently under pressure, and a re-read is cheap next to losing the
#: whole ingest wave. Genuine corruption still surfaces after the budget.
READ_RETRY = resilience.RetryPolicy(
    max_attempts=3,
    backoff=resilience.Backoff(base=0.1, factor=2.0, max_delay=1.0, jitter=0.5),
    retry_on=(IOError,),
    name="native-io-read",
)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
#: TOS_NATIVE_LIB points at an alternative build of libtfrecord_io.so —
#: the sanitizer leg of run_tests.sh uses it to swap in an ASan/UBSan build
#: without disturbing the checked-in Makefile output
_LIB_PATH = os.environ.get(
    "TOS_NATIVE_LIB", os.path.join(_NATIVE_DIR, "libtfrecord_io.so")
)

_lib = None
_lib_lock = threading.Lock()
_load_attempted = False


def _bind(lib):
    lib.tfr_load.restype = ctypes.c_void_p
    lib.tfr_load.argtypes = [ctypes.c_char_p, ctypes.c_int]
    lib.tfr_free.restype = None
    lib.tfr_free.argtypes = [ctypes.c_void_p]
    lib.tfr_count.restype = ctypes.c_uint64
    lib.tfr_count.argtypes = [ctypes.c_void_p]
    lib.tfr_buffer.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.tfr_buffer.argtypes = [ctypes.c_void_p]
    lib.tfr_buffer_len.restype = ctypes.c_uint64
    lib.tfr_buffer_len.argtypes = [ctypes.c_void_p]
    lib.tfr_offsets.restype = ctypes.POINTER(ctypes.c_uint64)
    lib.tfr_offsets.argtypes = [ctypes.c_void_p]
    lib.tfr_lengths.restype = ctypes.POINTER(ctypes.c_uint64)
    lib.tfr_lengths.argtypes = [ctypes.c_void_p]
    lib.tfr_last_error.restype = ctypes.c_char_p
    lib.tfr_last_error.argtypes = []
    lib.tfr_write.restype = ctypes.c_int
    lib.tfr_write.argtypes = [
        ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_uint8),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_uint64,
    ]
    lib.tfr_masked_crc32c.restype = ctypes.c_uint32
    lib.tfr_masked_crc32c.argtypes = [ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64]
    # streaming entry points (the chunked input path); a stale prebuilt
    # library without them still serves the bulk API — callers check
    # stream_available() and fall back to the Python codec
    try:
        lib.tfr_stream_open.restype = ctypes.c_void_p
        lib.tfr_stream_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.tfr_stream_close.restype = None
        lib.tfr_stream_close.argtypes = [ctypes.c_void_p]
        lib.tfr_stream_next.restype = ctypes.c_void_p
        lib.tfr_stream_next.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.tfr_has_stream = True
    except AttributeError:
        logger.warning(
            "native tfrecord_io library predates the streaming API; "
            "chunked reads fall back to the Python codec (rebuild with "
            "`make -B` in native/)"
        )
        lib.tfr_has_stream = False
    # JPEG decode entry points (decode straight into a slab slot); a stale
    # prebuilt library without them still serves the record APIs — callers
    # check jpg_available() and fall back to PIL
    try:
        lib.tfr_build_info.restype = ctypes.c_char_p
        lib.tfr_build_info.argtypes = []
        lib.jpg_info.restype = ctypes.c_int32
        lib.jpg_info.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.jpg_decode_window.restype = ctypes.c_int32
        lib.jpg_decode_window.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.c_double,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_int64,
        ]
        lib.tfr_has_jpeg = True
    except AttributeError:
        logger.warning(
            "native tfrecord_io library predates the JPEG decode API; "
            "image decode falls back to PIL (rebuild with `make -B` in "
            "native/)"
        )
        lib.tfr_has_jpeg = False
    return lib


def _try_build():
    """Build the library with make/g++ if a toolchain is present."""
    src = os.path.join(_NATIVE_DIR, "tfrecord_io.cc")
    if not os.path.exists(src):
        return False
    try:
        subprocess.run(
            ["make", "-s", "libtfrecord_io.so"],
            cwd=_NATIVE_DIR,
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except Exception as e:
        logger.info("native tfrecord_io build unavailable (%s); using Python codec", e)
        return False


def load_library():
    """The bound ctypes library, or None when native IO is unavailable."""
    global _lib, _load_attempted
    with _lib_lock:
        if _lib is not None or _load_attempted:
            return _lib
        _load_attempted = True
        if not os.path.exists(_LIB_PATH) and not _try_build():
            return None
        try:
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
            logger.info("native tfrecord_io loaded from %s", _LIB_PATH)
        except OSError as e:
            logger.warning("could not load %s: %s", _LIB_PATH, e)
            _lib = None
        return _lib


def available():
    return load_library() is not None


def read_records(path, verify_crc=True):
    """All record payloads of one shard as a list of ``bytes``.

    Raises IOError on corruption/truncation (message carried up from C),
    after ``READ_RETRY`` exhausts its budget (transient filesystem errors
    heal on a re-read; corrupt bytes don't).
    """
    return READ_RETRY.call(_read_records_once, path, verify_crc)


def _slice_records(lib, handle):
    """Record payloads out of one loaded handle (bulk file or stream chunk):
    one copy per record straight out of the C buffer (a whole-buffer bytes
    intermediate would double peak memory on the ingest path)."""
    count = lib.tfr_count(handle)
    base = ctypes.cast(lib.tfr_buffer(handle), ctypes.c_void_p).value
    offsets = lib.tfr_offsets(handle)
    lengths = lib.tfr_lengths(handle)
    return [ctypes.string_at(base + offsets[i], lengths[i]) for i in range(count)]


def _read_records_once(path, verify_crc=True):
    lib = load_library()
    if lib is None:
        raise RuntimeError("native tfrecord_io not available")
    if chaos.active and chaos.fire("native_io.read_fail"):
        raise IOError("chaos: injected transient read failure for {}".format(path))
    handle = lib.tfr_load(path.encode(), 1 if verify_crc else 0)
    if not handle:
        raise IOError(lib.tfr_last_error().decode() or "tfr_load failed on {}".format(path))
    try:
        return _slice_records(lib, handle)
    finally:
        lib.tfr_free(handle)


def stream_available():
    """True when the loaded library exposes the chunked streaming API (a
    stale prebuilt ``libtfrecord_io.so`` may predate it)."""
    lib = load_library()
    return lib is not None and lib.tfr_has_stream


def _stream_open(lib, path, verify_crc):
    if chaos.active and chaos.fire("native_io.read_fail"):
        raise IOError("chaos: injected transient read failure for {}".format(path))
    handle = lib.tfr_stream_open(path.encode(), 1 if verify_crc else 0)
    if not handle:
        raise IOError(
            lib.tfr_last_error().decode() or "tfr_stream_open failed on {}".format(path)
        )
    return handle


class _StreamChunkReader(framing.ChunkReader):
    """The native stream behind the shared ``open → read_chunk → close``
    chunk contract (:mod:`tensorflowonspark_tpu.store.framing`): opening
    fires the ``native_io.read_fail`` chaos seam exactly as before, and
    ``read_chunk`` slices one ``tfr_stream_next`` buffer per call."""

    def __init__(self, lib, path, verify_crc):
        self._lib = lib
        self._handle = _stream_open(lib, path, verify_crc)

    def read_chunk(self, max_records):
        chunk = self._lib.tfr_stream_next(self._handle, int(max_records))
        if not chunk:
            err = self._lib.tfr_last_error().decode()
            if err:
                raise IOError(err)
            return []  # clean EOF
        try:
            return _slice_records(self._lib, chunk)
        finally:
            self._lib.tfr_free(chunk)

    def close(self):
        handle, self._handle = self._handle, None
        if handle:
            self._lib.tfr_stream_close(handle)


def open_chunk_reader(path, verify_crc=True):
    """A :class:`_StreamChunkReader` over one shard (the native fast path
    ``store.LocalStore.open`` hands to the loader). Raises ``RuntimeError``
    when the library lacks the streaming API — check
    :func:`stream_available` first."""
    lib = load_library()
    if lib is None or not lib.tfr_has_stream:
        raise RuntimeError("native tfrecord_io streaming not available")
    return _StreamChunkReader(lib, path, verify_crc)


def read_records_chunked(path, chunk_records=1024, verify_crc=True):
    """Yield lists of up to ``chunk_records`` record payloads, reading the
    shard incrementally (``tfr_stream_next``) instead of materializing it.

    The streaming half of the pipelined input path: peak memory is one chunk
    (plus the OS page cache), and the first record flows after one chunk's
    worth of IO instead of a whole shard's. The open is retried under
    ``READ_RETRY`` (transient filesystem errors); mid-stream corruption is
    NOT retried — the stream position is gone, and corrupt bytes don't heal.
    Both behaviors come from the shared chunk loop
    (:func:`tensorflowonspark_tpu.store.framing.iter_chunks`).
    """
    lib = load_library()
    if lib is None or not lib.tfr_has_stream:
        raise RuntimeError("native tfrecord_io streaming not available")
    return framing.iter_chunks(
        lambda: _StreamChunkReader(lib, path, verify_crc),
        chunk_records,
        retry=READ_RETRY,
    )


def write_records(path, records):
    """Write an iterable of payload ``bytes`` as one TFRecord shard."""
    lib = load_library()
    if lib is None:
        raise RuntimeError("native tfrecord_io not available")
    records = list(records)
    payloads = b"".join(records)
    n = len(records)
    offsets = (ctypes.c_uint64 * n)()
    lengths = (ctypes.c_uint64 * n)()
    pos = 0
    for i, rec in enumerate(records):
        offsets[i] = pos
        lengths[i] = len(rec)
        pos += len(rec)
    buf = (ctypes.c_uint8 * len(payloads)).from_buffer_copy(payloads) if payloads else (ctypes.c_uint8 * 1)()
    rc = lib.tfr_write(path.encode(), buf, offsets, lengths, n)
    if rc != 0:
        raise IOError(lib.tfr_last_error().decode() or "tfr_write failed on {}".format(path))
    return n


def masked_crc32c(data):
    """Masked crc32c via the native library (for cross-validation tests)."""
    lib = load_library()
    if lib is None:
        raise RuntimeError("native tfrecord_io not available")
    buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data else (ctypes.c_uint8 * 1)()
    return lib.tfr_masked_crc32c(buf, len(data))


#: env kill-switch: TOS_NATIVE_DECODE=0 forces the PIL decode path even when
#: the library carries the jpg_* entry points (bit-exactness A/B runs, and an
#: escape hatch if a platform's decode ever diverges)
DECODE_ENV_VAR = "TOS_NATIVE_DECODE"


def jpg_available():
    """True when native JPEG decode can be used: the loaded library carries
    the ``jpg_*`` entry points and :data:`DECODE_ENV_VAR` doesn't veto it."""
    if os.environ.get(DECODE_ENV_VAR, "1") == "0":
        return False
    lib = load_library()
    return lib is not None and lib.tfr_has_jpeg


def build_info():
    """The native build fingerprint string (``tfr_build_info()``), e.g.
    ``"tfrecord_io jpeg=libjpeg-turbo api=62"``, or None when the loaded
    library predates the JPEG API (or no library loaded at all)."""
    lib = load_library()
    if lib is None or not lib.tfr_has_jpeg:
        return None
    return lib.tfr_build_info().decode()


class JpegError(ValueError):
    """Native JPEG decode failed: corrupt/truncated stream or a coding the
    backend doesn't support. A ``ValueError`` so the loader's bad-record
    accounting treats it exactly like a PIL decode failure."""


def jpg_info(data):
    """``(width, height)`` from the JPEG header, without a full decode."""
    lib = load_library()
    if lib is None or not lib.tfr_has_jpeg:
        raise RuntimeError("native JPEG decode not available")
    w = ctypes.c_int32()
    h = ctypes.c_int32()
    if lib.jpg_info(data, len(data), ctypes.byref(w), ctypes.byref(h)) != 0:
        raise JpegError(lib.tfr_last_error().decode() or "jpg_info failed")
    return w.value, h.value


def jpg_decode_window(data, out, box, resize, window_origin=(0, 0), flip=False):
    """Decode ``data`` and write a resized window straight into ``out``.

    The single-call native hot path: decode, Pillow-exact bilinear resize of
    the source rect ``box`` (``(x0, y0, x1, y1)`` floats, PIL ``box=``
    semantics) to ``resize`` (``(width, height)``), then the window of that
    resize starting at ``window_origin`` with ``out``'s shape — horizontally
    mirrored when ``flip`` — lands in ``out``: a C-contiguous-rows uint8
    ``(H, W, 3)`` numpy view, typically a shared-memory slab slot. No PIL,
    no intermediate copy. Raises :class:`JpegError` on corrupt input or an
    unsupported coding (caller falls back to PIL).
    """
    lib = load_library()
    if lib is None or not lib.tfr_has_jpeg:
        raise RuntimeError("native JPEG decode not available")
    if out.dtype.str != "|u1" or out.ndim != 3 or out.shape[2] != 3:
        raise ValueError("out must be a uint8 (H, W, 3) array, got {} {}".format(
            out.dtype, out.shape))
    if out.strides[1] != 3 or out.strides[2] != 1:
        raise ValueError("out rows must be C-contiguous")
    oh, ow = out.shape[0], out.shape[1]
    ox, oy = window_origin
    rc = lib.jpg_decode_window(
        data, len(data),
        float(box[0]), float(box[1]), float(box[2]), float(box[3]),
        int(resize[0]), int(resize[1]),
        int(ox), int(oy), int(ow), int(oh),
        1 if flip else 0,
        out.ctypes.data_as(ctypes.c_void_p), out.strides[0],
    )
    if rc != 0:
        raise JpegError(lib.tfr_last_error().decode() or "jpg_decode_window failed")
