"""Survivable serving mesh: replica leases, routed failover, hot model swap.

One :class:`~tensorflowonspark_tpu.serving.InferenceServer` is a single
point of failure — one SIGKILL takes down all of serving while the training
plane shrugs off executor kills (ROADMAP item 2). This module grows serving
into a cluster-level plane built from the same substrate PR 11 gave the
control plane:

* :class:`ServingMesh` — runs N replicas (in-process threads for tests and
  single-host meshes, forked processes for crash isolation), each holding a
  TTL lease in a :class:`~tensorflowonspark_tpu.registry.MembershipRegistry`.
  A monitor thread pings every replica, renews its lease on each answered
  ping, lets silent replicas expire through the registry's lease machinery,
  and relaunches them on a fresh port — ``serving_replicas_active`` dips,
  then recovers.
* :class:`ReplicaRouter` — client-side load balancer over the live leases:
  round-robin across replicas whose per-replica
  :class:`~tensorflowonspark_tpu.resilience.CircuitBreaker` admits traffic,
  deadline-bounded failover (a request that hits a dead or shedding replica
  is replayed on another — prediction is stateless, so replay is safe), and
  request hedging (a primary that exceeds ``hedge_after`` seconds gets a
  duplicate sent to a second replica; first answer wins). When every live
  replica's circuit is open the router sheds with a distinct
  :class:`~tensorflowonspark_tpu.serving.Overloaded` reason instead of
  hanging — mesh-wide graceful degradation.
* :class:`ModelPointer` + the per-replica swap watcher — zero-downtime
  model hot-swap. ``publish()`` exports a new generation next to the old
  ones, stamps it with a :mod:`~tensorflowonspark_tpu.ckpt.manifest`
  (tmp + fsync + rename, manifest written last), then atomically flips a
  ``CURRENT`` pointer file. Each replica polls the pointer, cheap-verifies
  the new generation with ``manifest.verify()`` (a torn publish is rejected
  and counted, never a crash), loads and *warms* the new predictor off the
  request path, then swaps it in atomically while in-flight requests drain
  on the old bundle.
* :class:`MeshFrontend` — one TCP endpoint speaking the InferenceServer
  wire protocol, fanned out through a router: what
  ``python -m tensorflowonspark_tpu.serving mesh`` binds.

Chaos sites (see the site table in :mod:`tensorflowonspark_tpu.chaos`):
``serving.replica_kill`` SIGKILLs a live replica from the monitor loop,
``serving.router_partition`` drops the router's connection to the replica
chosen for a request, and ``serving.swap_torn`` tears the manifest of a
freshly published generation. Metrics: ``serving_replicas_active`` gauge,
``serving_failovers_total``, ``serving_hedges_total``,
``serving_swaps_total``, ``serving_swap_rejects_total``,
``serving_mesh_shed_total``, ``serving_circuit_open_total``,
``serving_replica_relaunches_total`` — all in the process-global registry,
so a driver-side mesh surfaces them through ``TFCluster.metrics()``.
"""

import logging
import os
import shutil
import signal
import socket
import threading
import time

from tensorflowonspark_tpu import chaos, obs, resilience, serving
from tensorflowonspark_tpu.ckpt import manifest
from tensorflowonspark_tpu.registry import MembershipRegistry
from tensorflowonspark_tpu.reservation import MessageSocket

logger = logging.getLogger(__name__)

#: generation directories are ``gen-000042``; the pointer file names one
GEN_PREFIX = "gen-"
CURRENT_NAME = "CURRENT"

_EID_PREFIX = "serving-"


def _eid(rid):
    return "{}{}".format(_EID_PREFIX, rid)


def _rid_of(eid):
    """Mesh replica id for a registry eid, or None for foreign members."""
    text = str(eid)
    if not text.startswith(_EID_PREFIX):
        return None
    try:
        return int(text[len(_EID_PREFIX):])
    except ValueError:
        return None


def is_pointer_dir(path):
    """True when ``path`` is a generation-pointer dir (has a CURRENT file)."""
    return os.path.isfile(os.path.join(path, CURRENT_NAME))


def _tear_manifest(path):
    """Truncate a just-written manifest half-way — the crash-between-write-
    and-fsync shape ``serving.swap_torn`` injects."""
    mpath = os.path.join(path, manifest.MANIFEST_NAME)
    try:
        with open(mpath, "rb") as f:
            data = f.read()
        with open(mpath, "wb") as f:
            f.write(data[: max(1, len(data) // 2)])
    except OSError:
        logger.warning("chaos: could not tear manifest under %s", path)


class ModelPointer:
    """A directory of model generations plus an atomically-updated pointer.

    Layout::

        root/
          gen-000000/   # a train.export bundle + MANIFEST.json
          gen-000001/
          CURRENT       # one line: the live generation's name

    ``publish`` follows the ckpt commit protocol: bundle files land in a
    staging dir, ``MANIFEST.json`` is written last, one ``os.rename``
    publishes the generation, and only then does ``CURRENT`` flip (its own
    tmp + fsync + rename). A crash at any point leaves either the old
    pointer or a fully-described new generation — replicas additionally
    cheap-verify before swapping, so even a torn manifest (the
    ``serving.swap_torn`` chaos shape) degrades to "keep serving the old
    model", never a crash."""

    def __init__(self, root):
        self.root = os.path.abspath(os.path.expanduser(root))
        os.makedirs(self.root, exist_ok=True)

    def generations(self):
        """Published generation names, oldest first."""
        return sorted(
            d for d in os.listdir(self.root)
            if d.startswith(GEN_PREFIX) and os.path.isdir(os.path.join(self.root, d))
        )

    def current(self):
        """``(generation_name, generation_dir)`` per the pointer, or None."""
        try:
            with open(os.path.join(self.root, CURRENT_NAME)) as f:
                name = f.read().strip()
        except OSError:
            return None
        if not name:
            return None
        return name, os.path.join(self.root, name)

    def publish(self, predict_builder, params, model_state=None, step=None):
        """Export a new generation and flip the pointer to it. Returns the
        generation dir. The ``serving.swap_torn`` chaos site tears the
        manifest *after* export but *before* the pointer flip — the torn
        generation is published and pointed at, and replicas must reject it."""
        from tensorflowonspark_tpu.train import export as train_export

        gens = self.generations()
        nxt = int(gens[-1][len(GEN_PREFIX):]) + 1 if gens else 0
        name = "{}{:06d}".format(GEN_PREFIX, nxt)
        staging = os.path.join(self.root, "tmp." + name)
        if os.path.exists(staging):
            shutil.rmtree(staging)
        train_export.export_model(
            staging, predict_builder, params, model_state=model_state
        )
        return self._commit(staging, name, step=step)

    def publish_bundle(self, export_dir, step=None):
        """Adopt an already-exported bundle dir as the next generation."""
        gens = self.generations()
        nxt = int(gens[-1][len(GEN_PREFIX):]) + 1 if gens else 0
        name = "{}{:06d}".format(GEN_PREFIX, nxt)
        staging = os.path.join(self.root, "tmp." + name)
        if os.path.exists(staging):
            shutil.rmtree(staging)
        shutil.copytree(export_dir, staging)
        # a copied bundle may carry the source's manifest; re-stamp below
        try:
            os.remove(os.path.join(staging, manifest.MANIFEST_NAME))
        except OSError:
            pass
        return self._commit(staging, name, step=step)

    def _commit(self, staging, name, step=None):
        manifest.write_manifest(staging, step=step, extra={"generation": name})
        if chaos.active and chaos.fire("serving.swap_torn"):
            _tear_manifest(staging)
        final = os.path.join(self.root, name)
        os.rename(staging, final)
        self._set_current(name)
        logger.info("model pointer %s -> %s", self.root, name)
        return final

    def _set_current(self, name):
        tmp = os.path.join(self.root, CURRENT_NAME + ".tmp")
        with open(tmp, "w") as f:
            f.write(name + "\n")
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, os.path.join(self.root, CURRENT_NAME))
        try:
            dirfd = os.open(self.root, os.O_RDONLY)
            try:
                os.fsync(dirfd)
            finally:
                os.close(dirfd)
        except OSError:
            pass  # pointer durability is best-effort; the rename is atomic


def _zeros_for(spec):
    """A 1-row all-zeros batch matching a recorded request signature."""
    import numpy as np

    return {
        name: np.zeros((1,) + tuple(shape), dtype=np.dtype(dtype))
        for name, dtype, shape in spec
    }


class ReplicaServer:
    """One mesh replica: an :class:`serving.InferenceServer` plus, when
    serving a :class:`ModelPointer` dir, a hot-swap watcher thread.

    The watcher polls ``CURRENT``; a new generation is cheap-verified
    (``manifest.verify`` — a torn publish increments
    ``serving_swap_rejects_total`` and the old model keeps serving), loaded
    and warmed off the request path (one zeros-batch predict shaped like the
    last real request, so the compile happens before the flip), then swapped
    in atomically. In-flight requests drain on the old predictor."""

    def __init__(self, model, host="127.0.0.1", port=0, poll_interval=None,
                 trusted_builder=None, max_threads=None):
        self.model = os.path.abspath(os.path.expanduser(model))
        self._trusted_builder = trusted_builder
        self._poll = poll_interval if poll_interval is not None else float(
            os.environ.get("TOS_SERVING_SWAP_POLL_SECS", "0.5")
        )
        self._pointer = None
        self._generation = None
        bundle = self.model
        if is_pointer_dir(self.model):
            self._pointer = ModelPointer(self.model)
            cur = self._pointer.current()
            if cur is None:
                raise FileNotFoundError(
                    "pointer dir {} has no published generation".format(self.model)
                )
            self._generation, bundle = cur
        self._server = serving.InferenceServer(
            bundle, host=host, port=port, max_threads=max_threads,
            trusted_builder=trusted_builder,
        )
        self._rejected = set()
        self._stop_evt = threading.Event()
        self._watcher = None
        self._lock = threading.Lock()
        self._swaps_c = obs.counter(
            "serving_swaps_total", help="zero-downtime model hot-swaps completed"
        )
        self._rejects_c = obs.counter(
            "serving_swap_rejects_total",
            help="published model generations rejected by manifest cheap-verify",
        )

    @property
    def address(self):
        return self._server.address

    def generation(self):
        """Name of the generation currently serving (None for plain bundles)."""
        with self._lock:
            return self._generation

    def start(self):
        addr = self._server.start()
        if self._pointer is not None:
            self._watcher = threading.Thread(
                target=self._watch, name="tos-swap-watch", daemon=True
            )
            self._watcher.start()
        return addr

    def stop(self):
        self._stop_evt.set()
        if self._watcher is not None:
            self._watcher.join(timeout=10)
        self._server.stop()

    def kill(self):
        """SIGKILL-shaped death for chaos: sockets close abruptly, nothing
        drains. :meth:`stop` can still be called later to reap threads."""
        self._stop_evt.set()
        self._server.kill()

    # -- hot swap ------------------------------------------------------------

    def _watch(self):
        ticker = resilience.Ticker(self._poll, jitter=0.25)
        for _ in ticker.ticks():
            if self._stop_evt.is_set():
                return
            try:
                self.check_swap()
            except Exception:
                # the watcher must never take the replica down with it
                logger.exception("swap watcher: poll failed; will retry")

    def check_swap(self):
        """One watcher poll step (public so tests can drive it
        deterministically). Returns True when a swap happened."""
        if self._pointer is None:
            return False
        cur = self._pointer.current()
        if cur is None:
            return False
        gen, gen_dir = cur
        with self._lock:
            if gen == self._generation or gen in self._rejected:
                return False
        ok, reason = manifest.verify(gen_dir)
        if not ok:
            with self._lock:
                self._rejected.add(gen)
            self._rejects_c.inc()
            logger.warning(
                "replica %s: rejected generation %s (%s); old model keeps serving",
                self.address, gen, reason,
            )
            return False
        from tensorflowonspark_tpu.train import export as train_export

        predict_fn, params, model_state = train_export.load_model(
            gen_dir, trusted_builder=self._trusted_builder
        )
        new_pred = serving._Predictor(predict_fn, params, model_state)
        warm = self._server.warm_spec()
        if warm:
            try:
                new_pred.submit(_zeros_for(warm))
            except Exception:
                logger.exception("swap warm-up predict failed; flipping anyway")
        old = self._server.swap_predictor(new_pred, export_dir=gen_dir)
        with self._lock:
            self._generation = gen
        self._swaps_c.inc()
        logger.info("replica %s: hot-swapped to %s", self.address, gen)
        # in-flight requests already dispatched keep draining on the old
        # predictor; stop() joins once they are done
        old.stop()
        return True


def _replica_child_main(model, host, conn, poll_interval, trusted_builder):
    """Forked-process replica entry point: serve, report the bound address
    through the pipe, then wait for SIGTERM."""
    # the replica inherits the mesh's trace context through os.environ at
    # spawn; adopt it under a replica proc label so its spans land in a
    # shard of their own (no-op when tracing is inert)
    from tensorflowonspark_tpu.obs import tracing as obs_tracing

    obs_tracing.install_from_env("serving-replica")
    stop_evt = threading.Event()

    def _on_term(_signum, _frame):
        stop_evt.set()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        pass  # non-main-thread start (tests): rely on SIGKILL cleanup
    try:
        replica = ReplicaServer(
            model, host=host or "127.0.0.1", port=0,
            poll_interval=poll_interval, trusted_builder=trusted_builder,
        )
        addr = replica.start()
    except Exception as e:
        try:
            conn.send(("error", "{}: {}".format(type(e).__name__, e)))
        finally:
            conn.close()
        return
    conn.send(("ok", list(addr)))
    conn.close()
    stop_evt.wait()
    replica.stop()


class _Replica:
    """Driver-side handle for one replica slot."""

    __slots__ = ("rid", "address", "server", "proc", "alive", "dead_seen", "misses")

    def __init__(self, rid):
        self.rid = rid
        self.address = None
        self.server = None   # thread mode: the in-process ReplicaServer
        self.proc = None     # process mode: the forked child
        self.alive = False
        self.dead_seen = None  # monitor tick that observed the death
        self.misses = 0        # consecutive failed pings


class ServingMesh:
    """N serving replicas held together by registry leases and a monitor.

    ``mode="thread"`` runs replicas in-process (fast, shares the obs
    registry — unit tests and single-host meshes); ``mode="process"`` forks
    one child per replica so a SIGKILL is a real process death. The monitor
    thread pings each replica every ``monitor_interval`` seconds; an
    answered ping renews the replica's lease (the ping counter is the beat,
    so renewals follow the registry's advancing-beat contract), a silent
    replica expires through ``expire_stale()`` and is relaunched on the
    next tick — ``serving_replicas_active`` dips, then recovers."""

    def __init__(self, model, replicas=3, mode="thread", registry=None,
                 lease_ttl=None, host="127.0.0.1", monitor_interval=None,
                 restart=True, swap_poll=None, trusted_builder=None,
                 spawn_timeout=60.0):
        if mode not in ("thread", "process"):
            raise ValueError("mode must be 'thread' or 'process'")
        if replicas < 1:
            raise ValueError("replicas must be >= 1")
        self.model = os.path.abspath(os.path.expanduser(model))
        self.replicas = replicas
        self.mode = mode
        self._host = host or "127.0.0.1"
        ttl = lease_ttl if lease_ttl is not None else float(
            os.environ.get("TOS_SERVING_LEASE_TTL", "10")
        )
        self.registry = registry if registry is not None else MembershipRegistry(ttl=ttl)
        self._interval = monitor_interval if monitor_interval is not None else float(
            os.environ.get("TOS_SERVING_MONITOR_SECS", "1.0")
        )
        self._ping_timeout = max(0.2, min(2.0, self._interval))
        self._restart = restart
        self._swap_poll = swap_poll
        self._trusted_builder = trusted_builder
        self._spawn_timeout = spawn_timeout
        self._lock = threading.Lock()
        self._replicas = {}
        self._beats = {}
        self._stop_evt = threading.Event()
        self._monitor = None
        self._started = False
        self._active_g = obs.gauge(
            "serving_replicas_active", help="serving replicas holding a live mesh lease"
        )
        self._relaunch_c = obs.counter(
            "serving_replica_relaunches_total",
            help="mesh replicas relaunched after their lease expired",
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        """Spawn every replica, grant leases, start the monitor. Returns
        ``{rid: (host, port)}``."""
        with self._lock:
            if self._started:
                raise RuntimeError("mesh already started")
            self._started = True
        for rid in range(self.replicas):
            rec = _Replica(rid)
            with self._lock:
                self._replicas[rid] = rec
            self._spawn_into(rec)
            self.registry.join(_eid(rid), job_name="serving", task_index=rid)
        self._publish_active()
        self._monitor = threading.Thread(
            target=self._run_monitor, name="tos-mesh-monitor", daemon=True
        )
        self._monitor.start()
        return self.endpoints()

    def stop(self):
        self._stop_evt.set()
        if self._monitor is not None:
            self._monitor.join(timeout=max(10.0, self._interval * 4))
        with self._lock:
            recs = list(self._replicas.values())
        for rec in recs:
            self._reap(rec)
            self.registry.leave(_eid(rec.rid), reason="mesh stopped")
        self._publish_active()

    def endpoints(self):
        """``{rid: (host, port)}`` for replicas believed alive — the feed
        for :class:`ReplicaRouter`; refreshed on every routed request."""
        with self._lock:
            return {
                rec.rid: rec.address
                for rec in self._replicas.values()
                if rec.alive and rec.address is not None
            }

    def router(self, **kwargs):
        """A :class:`ReplicaRouter` bound to this mesh's live-endpoint view."""
        return ReplicaRouter(self.endpoints, **kwargs)

    def kill_replica(self, rid=None):
        """Hard-kill one live replica (SIGKILL in process mode; abrupt
        socket death in thread mode). The death is *discovered* — failed
        pings, then lease expiry — exactly like an unplanned crash. Returns
        the victim rid, or None when nothing is alive."""
        with self._lock:
            live = sorted(r for r, rec in self._replicas.items() if rec.alive)
            if not live:
                return None
            victim = rid if rid in live else live[0]
            rec = self._replicas[victim]
            proc, server = rec.proc, rec.server
        if proc is not None:
            try:
                os.kill(proc.pid, signal.SIGKILL)
            except OSError:
                pass
        elif server is not None:
            server.kill()
        logger.warning("mesh: hard-killed replica %s", victim)
        return victim

    # -- internals -----------------------------------------------------------

    def _spawn_into(self, rec):
        if self.mode == "thread":
            server = ReplicaServer(
                self.model, host=self._host, port=0,
                poll_interval=self._swap_poll,
                trusted_builder=self._trusted_builder,
            )
            addr = server.start()
            with self._lock:
                rec.server = server
                rec.proc = None
                rec.address = (addr[0] or "127.0.0.1", addr[1])
                rec.alive = True
                rec.dead_seen = None
                rec.misses = 0
            return
        import multiprocessing

        ctx = multiprocessing.get_context("fork")
        parent, child = ctx.Pipe()
        proc = ctx.Process(
            target=_replica_child_main,
            args=(self.model, self._host, child, self._swap_poll, self._trusted_builder),
            name="tos-mesh-replica-{}".format(rec.rid),
            daemon=True,
        )
        proc.start()
        child.close()
        try:
            if not parent.poll(self._spawn_timeout):
                raise RuntimeError(
                    "replica {} did not report an address within {:.0f}s".format(
                        rec.rid, self._spawn_timeout
                    )
                )
            status, payload = parent.recv()
        except (EOFError, OSError) as e:
            proc.terminate()
            raise RuntimeError("replica {} died during spawn: {}".format(rec.rid, e))
        finally:
            parent.close()
        if status != "ok":
            proc.terminate()
            raise RuntimeError("replica {} failed to start: {}".format(rec.rid, payload))
        with self._lock:
            rec.server = None
            rec.proc = proc
            rec.address = (payload[0] or "127.0.0.1", int(payload[1]))
            rec.alive = True
            rec.dead_seen = None
            rec.misses = 0

    def _reap(self, rec):
        """Release a dead (or stopping) replica's resources; idempotent."""
        with self._lock:
            proc, server = rec.proc, rec.server
            rec.proc = None
            rec.server = None
            rec.alive = False
        if proc is not None:
            try:
                proc.terminate()
            except (OSError, ValueError):
                pass
            proc.join(timeout=5)
            if proc.is_alive():
                try:
                    proc.kill()
                except (OSError, ValueError):
                    pass
                proc.join(timeout=5)
        if server is not None:
            try:
                server.stop()
            except Exception:
                logger.exception("mesh: error reaping replica %s", rec.rid)

    def _run_monitor(self):
        ticker = resilience.Ticker(self._interval, jitter=0.1)
        for tick_no in ticker.ticks():
            if self._stop_evt.is_set():
                return
            try:
                self._tick(tick_no)
            except Exception:
                logger.exception("mesh monitor tick failed")

    def _tick(self, tick_no):
        if chaos.active:
            spec = chaos.fire("serving.replica_kill")
            if spec is not None:
                self.kill_replica(spec.get("victim"))
        # 1. relaunch replicas whose death was observed on an EARLIER tick —
        #    deferring one tick keeps the serving_replicas_active dip
        #    observable instead of folding expiry+relaunch into one instant
        if self._restart:
            with self._lock:
                to_respawn = [
                    rec for rec in self._replicas.values()
                    if not rec.alive and rec.dead_seen is not None
                    and tick_no > rec.dead_seen
                ]
            for rec in to_respawn:
                try:
                    self._respawn(rec)
                except Exception:
                    logger.exception("mesh: relaunch of replica %s failed", rec.rid)
        # 2. ping live replicas; every answered ping advances the beat and
        #    renews the lease
        with self._lock:
            live = [
                (rec.rid, rec.address) for rec in self._replicas.values()
                if rec.alive and rec.address is not None
            ]
        for rid, addr in live:
            if self._ping(addr):
                beat = self._beats.get(rid, 0) + 1
                self._beats[rid] = beat
                self.registry.renew(_eid(rid), beat=beat)
                with self._lock:
                    rec = self._replicas.get(rid)
                    if rec is not None:
                        rec.misses = 0
            else:
                with self._lock:
                    rec = self._replicas.get(rid)
                    if rec is None or not rec.alive:
                        continue
                    rec.misses += 1
                    # a replica that died before its FIRST beat has an
                    # expiry-exempt lease (beat is None): declare it after
                    # three straight misses so the slot still relaunches
                    declare = rec.misses >= 3 and self._beats.get(rid, 0) == 0
                    if declare:
                        rec.alive = False
                        rec.dead_seen = tick_no
                if declare:
                    logger.warning(
                        "mesh: replica %s never answered a ping; relaunching", rid
                    )
        # 3. leases that stopped renewing expire; their replicas are marked
        #    dead and relaunched on the next tick
        for eid, age in self.registry.expire_stale():
            rid = _rid_of(eid)
            if rid is None:
                continue  # foreign (training) member on a shared registry
            with self._lock:
                rec = self._replicas.get(rid)
                if rec is None or not rec.alive:
                    continue
                rec.alive = False
                rec.dead_seen = tick_no
            logger.warning(
                "mesh: replica %s lease expired after %.1fs without a ping", rid, age
            )
        self._publish_active()

    def _respawn(self, rec):
        self._reap(rec)
        self._spawn_into(rec)
        self.registry.join(_eid(rec.rid), job_name="serving", task_index=rec.rid)
        self._relaunch_c.inc()
        logger.info("mesh: relaunched replica %s at %s", rec.rid, rec.address)
        self._publish_active()

    def _ping(self, addr):
        try:
            with socket.create_connection(addr, timeout=self._ping_timeout) as sock:
                sock.settimeout(self._ping_timeout)
                msock = MessageSocket(sock)
                msock.send({"type": "ping"})
                reply = msock.recv()
                return bool(reply) and reply.get("type") == "pong"
        except (OSError, ValueError):
            return False

    def _publish_active(self):
        with self._lock:
            eids = {_eid(rid) for rid in self._replicas}
        members = self.registry.members()
        n = sum(
            1 for eid in eids if members.get(eid, {}).get("state") == "live"
        )
        self._active_g.set(n)


class ReplicaRouter:
    """Client-side load balancer over a mesh's live replicas.

    ``endpoints`` is a ``{rid: (host, port)}`` mapping or a callable
    returning one (a live view like :meth:`ServingMesh.endpoints`). Each
    replica gets its own :class:`~tensorflowonspark_tpu.resilience.
    CircuitBreaker` and a small connection pool; a replica whose address
    changes (relaunch) gets a fresh breaker and pool.

    Request path: round-robin over circuit-admitted replicas; a replica
    failure (``OSError`` / ``Overloaded``) records on its breaker, counts a
    failover, and re-routes — all attempts share one
    :class:`~tensorflowonspark_tpu.resilience.Deadline`. With
    ``hedge_after > 0`` a primary that has not answered within the budget
    gets a duplicate request on a second replica; first answer wins
    (prediction is stateless, so duplicates are safe). When every live
    replica's circuit is open, the request is shed *immediately* with a
    distinct ``Overloaded`` reason — graceful mesh-wide degradation instead
    of a hang."""

    def __init__(self, endpoints, deadline=None, hedge_after=None,
                 request_timeout=None, breaker_threshold=None,
                 breaker_reset=None, backoff=None, pool_size=8):
        self._endpoints_fn = endpoints if callable(endpoints) else (
            lambda snapshot=dict(endpoints): dict(snapshot)
        )
        self.deadline = deadline if deadline is not None else float(
            os.environ.get("TOS_SERVING_ROUTE_DEADLINE_SECS", "30")
        )
        self.hedge_after = hedge_after if hedge_after is not None else (
            float(os.environ.get("TOS_SERVING_HEDGE_MS", "0")) / 1000.0
        )
        self.request_timeout = request_timeout if request_timeout is not None else float(
            os.environ.get("TOS_SERVING_ROUTE_TIMEOUT_SECS", "30")
        )
        self._threshold = breaker_threshold or int(
            os.environ.get("TOS_SERVING_BREAKER_FAILURES", "3")
        )
        self._reset = breaker_reset if breaker_reset is not None else float(
            os.environ.get("TOS_SERVING_BREAKER_RESET_SECS", "5")
        )
        self._backoff = backoff if backoff is not None else resilience.Backoff(
            base=0.05, factor=2.0, max_delay=0.5, jitter=0.5
        )
        self._pool_size = pool_size
        self._lock = threading.Lock()
        self._rr = 0
        self._breakers = {}
        self._addrs = {}
        self._pools = {}
        self._executor = None
        self._failover_c = obs.counter(
            "serving_failovers_total",
            help="requests re-routed to another replica after a failure",
        )
        self._hedges_c = obs.counter(
            "serving_hedges_total",
            help="hedged duplicate requests sent to a second replica",
        )
        self._shed_c = obs.counter(
            "serving_mesh_shed_total",
            help="requests shed mesh-wide: no routable replica",
        )
        self._circuit_c = obs.counter(
            "serving_circuit_open_total",
            help="per-replica circuit-breaker trips observed by the mesh router",
        )

    # -- public request surface ----------------------------------------------

    def predict(self, **inputs):
        """JSON-lane predict with failover/hedging; returns dict of lists."""
        return self._request("json", inputs)

    def predict_binary(self, **inputs):
        """Binary-lane predict: numpy arrays in, numpy arrays out."""
        return self._request("binary", inputs)

    def close(self):
        with self._lock:
            clients = [c for pool in self._pools.values() for c in pool]
            self._pools = {}
            executor = self._executor
            self._executor = None
        for client in clients:
            try:
                client.close()
            except Exception:
                pass
        if executor is not None:
            executor.shutdown(wait=False)

    # -- routing core ----------------------------------------------------------

    def _request(self, kind, payload):
        # one span per routed client request: failovers/hedges happen inside
        # it, so a merged timeline shows routing latency per request with
        # the cluster trace_id the mesh process inherited at spawn
        with obs.span("serving_route", kind=kind):
            return self._route(kind, payload)

    def _route(self, kind, payload):
        deadline = resilience.Deadline(self.deadline)
        started = time.monotonic()
        last_err = None
        tried = set()
        routed_once = False
        for _ in self._backoff.attempts(deadline):
            eps = self._refresh()
            if not eps:
                self._shed_c.inc()
                raise serving.Overloaded(
                    "Overloaded: mesh has no live replicas; request shed"
                )
            cycle_tried = set()
            attempted_this_cycle = False
            while True:
                rid = self._pick(eps, exclude=cycle_tried)
                if rid is None:
                    break
                attempted_this_cycle = True
                cycle_tried.add(rid)
                tried.add(rid)
                if routed_once:
                    self._failover_c.inc()
                routed_once = True
                try:
                    if self.hedge_after and self.hedge_after > 0:
                        return self._hedged(rid, eps, cycle_tried, kind, payload, deadline)
                    return self._call_replica(rid, kind, payload)
                except (OSError, serving.Overloaded) as e:
                    last_err = e
                    if deadline.expired():
                        raise self._final_error(tried, started, last_err) from last_err
            if not attempted_this_cycle:
                # every live replica's circuit is open: shed, don't hang
                self._shed_c.inc()
                raise serving.Overloaded(
                    "Overloaded: all {} replica circuits open; mesh shedding "
                    "requests".format(len(eps))
                )
        raise self._final_error(tried, started, last_err) from last_err

    def _hedged(self, rid, eps, exclude, kind, payload, deadline):
        from concurrent.futures import FIRST_COMPLETED, wait

        pool = self._hedge_executor()
        pending = {pool.submit(self._call_replica, rid, kind, payload)}
        hedged = False
        last = None
        while pending:
            timeout = deadline.remaining() if hedged else deadline.clamp(self.hedge_after)
            done, pending = wait(pending, timeout=timeout, return_when=FIRST_COMPLETED)
            if not done:
                if not hedged:
                    hedged = True
                    alt = self._pick(eps, exclude=exclude)
                    if alt is not None:
                        exclude.add(alt)
                        self._hedges_c.inc()
                        pending = set(pending)
                        pending.add(pool.submit(self._call_replica, alt, kind, payload))
                    continue
                # deadline spent with calls still in flight: surface as a
                # transient so the outer loop raises the named final error
                raise ConnectionError(
                    "hedged request still in flight at the routing deadline"
                )
            for fut in done:
                err = fut.exception()
                if err is None:
                    # abandoned sibling attempts finish in the background;
                    # _call_replica already returned their clients/breakers
                    return fut.result()
                last = err
                if not isinstance(err, (OSError, serving.Overloaded)):
                    raise err
        raise last

    def _call_replica(self, rid, kind, payload):
        try:
            out = self._attempt(rid, kind, payload)
        except (OSError, serving.Overloaded):
            self._record_failure(rid)
            raise
        self._record_success(rid)
        return out

    def _attempt(self, rid, kind, payload):
        if chaos.active and chaos.fire("serving.router_partition"):
            self._drop_pool(rid)
            raise ConnectionResetError(
                "chaos: router partitioned from replica {}".format(rid)
            )
        client = self._borrow(rid)
        try:
            if kind == "binary":
                out = client.predict_binary(**payload)
            else:
                out = client.predict(**payload)
        except BaseException:
            try:
                client.close()
            except Exception:
                pass
            raise
        self._return(rid, client)
        return out

    def _pick(self, eps, exclude=()):
        order = sorted(eps)
        if not order:
            return None
        with self._lock:
            start = self._rr
            self._rr += 1
        n = len(order)
        for i in range(n):
            rid = order[(start + i) % n]
            if rid in exclude:
                continue
            with self._lock:
                breaker = self._breakers.get(rid)
            if breaker is None or breaker.allow():
                return rid
        return None

    def _refresh(self):
        """Sync breakers/pools with the current endpoint view; returns it."""
        eps = dict(self._endpoints_fn() or {})
        stale = []
        with self._lock:
            for rid, addr in eps.items():
                addr = (addr[0], int(addr[1]))
                if self._addrs.get(rid) != addr:
                    # new or relaunched replica: fresh breaker, fresh pool
                    self._addrs[rid] = addr
                    self._breakers[rid] = resilience.CircuitBreaker(
                        failure_threshold=self._threshold,
                        reset_timeout=self._reset,
                        name="serving-replica-{}".format(rid),
                    )
                    stale.extend(self._pools.pop(rid, []))
            view = {rid: self._addrs[rid] for rid in eps}
        for client in stale:
            try:
                client.close()
            except Exception:
                pass
        return view

    def _record_success(self, rid):
        with self._lock:
            breaker = self._breakers.get(rid)
        if breaker is not None:
            breaker.record_success()

    def _record_failure(self, rid):
        with self._lock:
            breaker = self._breakers.get(rid)
        if breaker is None:
            return
        before = breaker.state
        breaker.record_failure()
        if before != resilience.OPEN and breaker.state == resilience.OPEN:
            self._circuit_c.inc()

    def _borrow(self, rid):
        with self._lock:
            pool = self._pools.setdefault(rid, [])
            client = pool.pop() if pool else None
            addr = self._addrs.get(rid)
        if client is not None:
            return client
        if addr is None:
            raise ConnectionError("replica {} has no live endpoint".format(rid))
        return serving.InferenceClient(
            addr, timeout=self.request_timeout,
            retry=resilience.RetryPolicy(max_attempts=1),
        )

    def _return(self, rid, client):
        with self._lock:
            pool = self._pools.setdefault(rid, [])
            if len(pool) < self._pool_size:
                pool.append(client)
                return
        client.close()

    def _drop_pool(self, rid):
        with self._lock:
            clients = self._pools.pop(rid, [])
        for client in clients:
            try:
                client.close()
            except Exception:
                pass

    def _hedge_executor(self):
        with self._lock:
            if self._executor is None:
                from concurrent.futures import ThreadPoolExecutor

                self._executor = ThreadPoolExecutor(
                    max_workers=16, thread_name_prefix="tos-mesh-hedge"
                )
            return self._executor

    def _final_error(self, tried, started, last_err):
        elapsed = time.monotonic() - started
        return ConnectionError(
            "mesh: request failed across {} replica(s) {} after {:.1f}s of a "
            "{:.0f}s budget: {}".format(
                len(tried), sorted(tried), elapsed, self.deadline,
                last_err if last_err is not None else "no replica available",
            )
        )


class MeshFrontend(serving.ProtocolServer):
    """One TCP endpoint speaking the InferenceServer wire protocol, fanned
    out through a :class:`ReplicaRouter` — clients that only know
    ``HOST:PORT`` (the JVM client, ``infer --server``) get mesh failover
    without learning the registry. Requests cross to replicas on the binary
    tensor lane regardless of the lane the client used."""

    def __init__(self, router, host="", port=0, max_threads=None):
        self.router = router
        serving.ProtocolServer.__init__(
            self, host=host, port=port, max_threads=max_threads,
            name="tos-mesh-front",
        )

    def _submit(self, arrays):
        return self.router.predict_binary(**arrays)

    def _info(self):
        return {"type": "info", "mesh": True, "ready": True}
