"""``LocalStore`` — the filesystem shard source behind the store ABI.

Wraps today's local read path without changing it: ``open`` hands back the
native streaming reader (``native/tfrecord_io.cc`` ``tfr_stream_next``)
when the library carries the streaming API, and the shared Python framing
(:mod:`~tensorflowonspark_tpu.store.framing`) otherwise — the same
native-fast-path-with-portable-fallback split the loader always made,
now expressed once behind ``ShardStore``.
"""

import os
import shutil

from tensorflowonspark_tpu.store import base, framing


def strip_file_scheme(path):
    path = str(path)
    if path.startswith("file://"):
        return path[len("file://"):]
    return path


class LocalStore(base.ShardStore):
    """Shard source for executor-local (or mounted) filesystem paths."""

    #: opens are retried by the callers that always did (the loader's
    #: ``SHARD_READ_RETRY``, ``native_io.READ_RETRY``); the store itself
    #: adds no second retry layer on the local path
    retry = None

    def handles(self, path):
        path = str(path)
        return "://" not in path or path.startswith("file://")

    def list_shards(self, root):
        from tensorflowonspark_tpu import tfrecord

        root = strip_file_scheme(root)
        names = [n for n in os.listdir(root) if tfrecord._is_shard_name(n)]
        return sorted(
            (os.path.join(root, n) for n in names), key=base.shard_sort_key
        )

    def stat(self, path):
        st = os.stat(strip_file_scheme(path))
        return {"size": int(st.st_size), "mtime": float(st.st_mtime)}

    def open(self, path, verify_crc=True):
        from tensorflowonspark_tpu import native_io

        path = strip_file_scheme(path)
        if native_io.stream_available():
            return native_io.open_chunk_reader(path, verify_crc=verify_crc)
        return framing.FramedChunkReader(
            open(path, "rb"), path, verify_crc=verify_crc
        )

    def fetch(self, path, out_f):
        path = strip_file_scheme(path)
        with open(path, "rb") as src:
            shutil.copyfileobj(src, out_f)
        return os.path.getsize(path)

    def fingerprint(self):
        return "local"
