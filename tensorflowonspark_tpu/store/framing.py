"""The one TFRecord framing/chunk implementation behind every shard source.

Before the store subsystem existed the tree carried two copies of the
chunked-read loop — ``tfrecord.read_records_chunked`` (pure-Python framing,
accumulate-into-lists) and ``native_io.read_records_chunked`` (the C
``tfr_stream_open/next/close`` walk) — with the open-retry, clean-EOF and
close-on-teardown semantics duplicated in each. This module is the single
copy both now delegate to:

- :func:`masked_crc` / :func:`read_framed` — THE Python framing loop
  (length + masked-crc32c per record, tensorflow record_writer.h wire
  format), over any file-like object: a local file, an fsspec handle, or a
  remote store's ranged reader.
- :class:`ChunkReader` — the ``open → read_chunk → close`` contract every
  shard source speaks (the ABI :class:`~tensorflowonspark_tpu.store.base.
  ShardStore` exposes, mirroring what ``tfr_stream_next`` always did).
- :func:`iter_chunks` — THE chunk loop: retried open, ``read_chunk`` until
  an empty chunk (clean EOF), close on every exit path. Mid-stream errors
  are never retried — the stream position is gone and corrupt bytes don't
  heal — exactly the contract both former copies enforced separately.

Leaf module: imports nothing from the package, so ``tfrecord`` and
``native_io`` can build on it without an import cycle.
"""

import struct

import google_crc32c

_MASK_DELTA = 0xA282EAD8


def masked_crc(data):
    """Masked crc32c of ``data`` (tensorflow record_writer.h masking)."""
    crc = int.from_bytes(google_crc32c.Checksum(data).digest(), "big")
    return ((((crc >> 15) | (crc << 17)) + _MASK_DELTA) & 0xFFFFFFFF)


def read_framed(f, name, verify_crc=True):
    """Yield raw record payloads from an open TFRecord byte stream ``f``.

    ``name`` labels errors (the path or URL the bytes came from). Raises
    ``IOError`` on truncation or CRC mismatch — the caller decides whether
    that is retryable (an open is; a half-consumed stream is not).
    """
    while True:
        header = f.read(8)
        if not header:
            return
        if len(header) != 8:
            raise IOError("truncated TFRecord length header in {}".format(name))
        (length,) = struct.unpack("<Q", header)
        len_crc_b = f.read(4)
        if len(len_crc_b) != 4:
            raise IOError("truncated TFRecord length crc in {}".format(name))
        (len_crc,) = struct.unpack("<I", len_crc_b)
        if verify_crc and masked_crc(header) != len_crc:
            raise IOError("corrupt TFRecord length crc in {}".format(name))
        data = f.read(length)
        if len(data) != length:
            raise IOError("truncated TFRecord payload in {}".format(name))
        data_crc_b = f.read(4)
        if len(data_crc_b) != 4:
            raise IOError("truncated TFRecord payload crc in {}".format(name))
        (data_crc,) = struct.unpack("<I", data_crc_b)
        if verify_crc and masked_crc(data) != data_crc:
            raise IOError("corrupt TFRecord payload crc in {}".format(name))
        yield data


class ChunkReader:
    """The chunked-read contract of one open shard.

    ``read_chunk(max_records)`` returns up to ``max_records`` record
    payloads as a list — an empty list means clean EOF. ``close()``
    releases the underlying handle; it must be idempotent. Concrete
    readers: the native stream (``native_io``), :class:`FramedChunkReader`
    over any byte source, and the remote stores' ranged readers.
    """

    def read_chunk(self, max_records):
        raise NotImplementedError

    def close(self):
        raise NotImplementedError


class FramedChunkReader(ChunkReader):
    """Python-codec :class:`ChunkReader` over an open byte stream: the
    framing loop of :func:`read_framed` chunked into lists. Owns ``f`` —
    ``close()`` closes it."""

    def __init__(self, f, name, verify_crc=True):
        self._f = f
        self._records = read_framed(f, name, verify_crc=verify_crc)

    def read_chunk(self, max_records):
        chunk = []
        for rec in self._records:
            chunk.append(rec)
            if len(chunk) >= max_records:
                break
        return chunk

    def close(self):
        f, self._f = self._f, None
        if f is not None:
            f.close()


def iter_chunks(open_reader, chunk_records, retry=None):
    """Generator of record-chunk lists over the ``open → read_chunk →
    close`` contract.

    ``open_reader()`` returns a :class:`ChunkReader`; when ``retry`` (a
    ``resilience.RetryPolicy``) is given the *open* is retried under it —
    transient filesystem/network errors heal on a re-open. ``read_chunk``
    is never retried: past the open, the stream position is gone. The
    reader is closed on every exit path (clean EOF, error, or an abandoned
    generator torn down by GC).
    """
    reader = retry.call(open_reader) if retry is not None else open_reader()
    try:
        while True:
            chunk = reader.read_chunk(int(chunk_records))
            if not chunk:
                return
            yield chunk
    finally:
        reader.close()
