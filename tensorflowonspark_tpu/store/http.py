"""``HTTPStore`` — remote object-store shard source over HTTP range-GETs.

Shards are read in ``TOS_STORE_RANGE_BYTES``-sized ranged requests (the
object-store access pattern: no open handles, no server state), with the
shared Python framing (:mod:`~tensorflowonspark_tpu.store.framing`) sliced
on top — so a remote shard streams through the loader chunk-for-chunk
identically to a local one. Every request runs under
:data:`STORE_READ_RETRY` (transient network errors heal on a re-request;
a mid-record CRC mismatch does not and is surfaced, exactly the local
contract).

GCS and S3 ride the same code path via **endpoint adapters**: an adapter
maps ``gs://bucket/key`` / ``s3://bucket/key`` names onto plain HTTP
object URLs against a configurable endpoint and knows that service's
listing API (GCS JSON API, S3 ListObjectsV2 XML). The default
:class:`IndexHtmlAdapter` speaks directory-index HTML (``http.server``,
nginx autoindex) — which is also what the in-process test fixture serves,
so the whole store is exercised without cloud credentials.

Chaos seams: ``store.read_error`` makes one request raise ``IOError``
(absorbed by the retry budget, visible in ``resilience_retries_total`` and
the per-site fault counter); ``store.remote_stall`` sleeps inside the
request — the latency lands in shard-read time, so ``classify_stalls``
calls the run io_bound and the prefetch autotuner must deepen.
"""

import html.parser
import json
import os
import threading
import urllib.error
import urllib.parse
import urllib.request

from tensorflowonspark_tpu import chaos, obs, resilience
from tensorflowonspark_tpu.store import base, framing

#: bytes per range-GET — large enough to amortize request latency, small
#: enough that a chunked read never buffers more than a few MiB per shard
DEFAULT_RANGE_BYTES = 4 * 1024 * 1024
RANGE_ENV = "TOS_STORE_RANGE_BYTES"

#: per-request timeout, seconds
_REQUEST_TIMEOUT = 30.0

#: retry policy for remote object reads: one budget for every HTTP request
#: the store issues (stat, list, ranged read) — object stores throw
#: transient 5xx/conn-reset under load and a re-request is cheap next to
#: losing the shard
STORE_READ_RETRY = resilience.RetryPolicy(
    max_attempts=4,
    backoff=resilience.Backoff(base=0.05, factor=2.0, max_delay=1.0, jitter=0.5),
    retry_on=(OSError,),
    name="store-read",
)


def resolve_range_bytes(range_bytes=None):
    if range_bytes is None:
        range_bytes = int(os.environ.get(RANGE_ENV, str(DEFAULT_RANGE_BYTES)))
    return max(1, int(range_bytes))


class _HrefParser(html.parser.HTMLParser):
    def __init__(self):
        super().__init__()
        self.hrefs = []

    def handle_starttag(self, tag, attrs):
        if tag == "a":
            for name, value in attrs:
                if name == "href" and value:
                    self.hrefs.append(value)


class IndexHtmlAdapter:
    """Plain-HTTP endpoint adapter: object names ARE URLs, and listings
    come from the server's directory-index page (``http.server``, nginx
    autoindex — and the in-process test fixture)."""

    def handles(self, path):
        return str(path).startswith(("http://", "https://"))

    def object_url(self, path):
        return str(path)

    def list_names(self, store, root):
        root = str(root).rstrip("/") + "/"
        body = store.request(root).decode("utf-8", "replace")
        parser = _HrefParser()
        parser.feed(body)
        names = []
        for href in parser.hrefs:
            name = urllib.parse.unquote(href.rstrip("/").rsplit("/", 1)[-1])
            if name and not href.endswith("/"):
                names.append(name)
        return root, names


class GCSAdapter:
    """``gs://bucket/key`` → the GCS XML/JSON endpoints (public or
    emulated; ``endpoint`` points tests at a local fixture). No auth —
    credentialed access belongs to a fronting proxy, not this reader."""

    scheme = "gs://"

    def __init__(self, endpoint="https://storage.googleapis.com"):
        self.endpoint = endpoint.rstrip("/")

    def handles(self, path):
        return str(path).startswith(self.scheme)

    def _split(self, path):
        bucket, _, key = str(path)[len(self.scheme):].partition("/")
        return bucket, key

    def object_url(self, path):
        bucket, key = self._split(path)
        return "{}/{}/{}".format(self.endpoint, bucket, urllib.parse.quote(key))

    def list_names(self, store, root):
        bucket, prefix = self._split(root)
        url = "{}/storage/v1/b/{}/o?prefix={}".format(
            self.endpoint, bucket, urllib.parse.quote(prefix)
        )
        items = json.loads(store.request(url).decode()).get("items", [])
        base_root = str(root).rstrip("/") + "/"
        names = []
        for item in items:
            key = item.get("name", "")
            tail = key[len(prefix):].lstrip("/")
            if tail and "/" not in tail:
                names.append(tail)
        return base_root, names


class S3Adapter:
    """``s3://bucket/key`` → path-style S3 endpoints via ListObjectsV2
    (public or emulated; ``endpoint`` points tests at a local fixture)."""

    scheme = "s3://"

    def __init__(self, endpoint="https://s3.amazonaws.com"):
        self.endpoint = endpoint.rstrip("/")

    def handles(self, path):
        return str(path).startswith(self.scheme)

    def _split(self, path):
        bucket, _, key = str(path)[len(self.scheme):].partition("/")
        return bucket, key

    def object_url(self, path):
        bucket, key = self._split(path)
        return "{}/{}/{}".format(self.endpoint, bucket, urllib.parse.quote(key))

    def list_names(self, store, root):
        import re

        bucket, prefix = self._split(root)
        url = "{}/{}?list-type=2&prefix={}".format(
            self.endpoint, bucket, urllib.parse.quote(prefix)
        )
        body = store.request(url).decode("utf-8", "replace")
        base_root = str(root).rstrip("/") + "/"
        names = []
        for key in re.findall(r"<Key>([^<]+)</Key>", body):
            tail = key[len(prefix):].lstrip("/")
            if tail and "/" not in tail:
                names.append(tail)
        return base_root, names


class _RangedFile:
    """Sequential file-like view of one remote object, reading ahead in
    ``range_bytes``-sized range-GETs so the per-record framing reads never
    hit the wire individually."""

    def __init__(self, store, url, size):
        self._store = store
        self._url = url
        self._size = int(size)
        self._pos = 0
        self._buf = b""
        self._buf_pos = 0

    def read(self, n):
        out = []
        need = int(n)
        while need > 0:
            avail = len(self._buf) - self._buf_pos
            if avail <= 0:
                if self._pos >= self._size:
                    break
                span = max(need, self._store.range_bytes)
                end = min(self._pos + span, self._size) - 1
                self._buf = self._store.read_range(self._url, self._pos, end)
                self._buf_pos = 0
                self._pos += len(self._buf)
                if not self._buf:
                    break
                continue
            take = min(avail, need)
            out.append(self._buf[self._buf_pos : self._buf_pos + take])
            self._buf_pos += take
            need -= take
        return b"".join(out)

    def close(self):
        self._buf = b""


class HTTPStore(base.ShardStore):
    """Remote shard source speaking HTTP range-GETs through an endpoint
    adapter (:class:`IndexHtmlAdapter` default; :class:`GCSAdapter` /
    :class:`S3Adapter` for ``gs://`` / ``s3://`` names)."""

    def __init__(self, adapter=None, range_bytes=None, retry=None):
        self.adapter = adapter or IndexHtmlAdapter()
        self.range_bytes = resolve_range_bytes(range_bytes)
        self.retry = retry or STORE_READ_RETRY
        self._lock = threading.Lock()
        self._sizes = {}  # object url -> size (stat cache for open())
        self._reads_c = obs.counter(
            "store_remote_reads_total",
            help="HTTP requests issued to remote shard stores",
        )
        self._bytes_c = obs.counter(
            "store_remote_bytes_total",
            help="bytes fetched from remote shard stores",
        )

    def handles(self, path):
        return self.adapter.handles(path)

    # -- HTTP primitives (every request funnels through here) ------------------

    def _request_once(self, url, headers=None, method="GET"):
        if chaos.active:
            if chaos.fire("store.read_error"):
                raise IOError("chaos: injected remote store read failure for {}".format(url))
            chaos.delay("store.remote_stall")
        req = urllib.request.Request(url, headers=headers or {}, method=method)
        try:
            with urllib.request.urlopen(req, timeout=_REQUEST_TIMEOUT) as resp:
                body = resp.read()
                self._reads_c.inc()
                self._bytes_c.inc(len(body))
                return resp.status, dict(resp.headers), body
        except urllib.error.HTTPError as e:
            if e.code == 416:  # past EOF: an empty range, not a failure
                return 416, dict(e.headers or {}), b""
            raise IOError("HTTP {} for {}".format(e.code, url))

    def request(self, url, headers=None, method="GET"):
        """One retried request; returns the body bytes."""
        status, _headers, body = self.retry.call(
            self._request_once, url, headers, method
        )
        return body

    def read_range(self, url, start, end):
        """Bytes ``[start, end]`` of the object (inclusive range). Servers
        that ignore the Range header (plain ``http.server``) answer 200
        with the whole body — sliced here so the framing above never
        notices the difference."""
        status, _headers, body = self.retry.call(
            self._request_once, url, {"Range": "bytes={}-{}".format(start, end)}
        )
        if status == 206:
            return body
        if status == 416:
            return b""
        return body[start : end + 1]

    # -- ShardStore ABI ---------------------------------------------------------

    def stat(self, path):
        url = self.adapter.object_url(path)
        status, headers, body = self.retry.call(self._request_once, url, None, "HEAD")
        length = headers.get("Content-Length")
        if length is None:
            # HEAD-less servers: fall back to a full GET for the size
            status, headers, body = self.retry.call(self._request_once, url)
            length = headers.get("Content-Length", len(body))
        size = int(length)
        with self._lock:
            self._sizes[url] = size
        return {"size": size}

    def open(self, path, verify_crc=True):
        url = self.adapter.object_url(path)
        with self._lock:
            size = self._sizes.get(url)
        if size is None:
            size = self.stat(path)["size"]
        return framing.FramedChunkReader(
            _RangedFile(self, url, size), url, verify_crc=verify_crc
        )

    def list_shards(self, root):
        from tensorflowonspark_tpu import tfrecord

        base_root, names = self.adapter.list_names(self, root)
        shards = [base_root + n for n in names if tfrecord._is_shard_name(n)]
        return sorted(shards, key=base.shard_sort_key)

    def fetch(self, path, out_f):
        url = self.adapter.object_url(path)
        size = self.stat(path)["size"]
        pos = 0
        while pos < size:
            end = min(pos + self.range_bytes, size) - 1
            block = self.read_range(url, pos, end)
            if not block:
                raise IOError("short remote object: {} ended at {}/{}".format(url, pos, size))
            out_f.write(block)
            pos += len(block)
        return pos

    def fingerprint(self):
        return "http adapter={} range_bytes={}".format(
            type(self.adapter).__name__, self.range_bytes
        )


def resolve_store(paths):
    """The store implied by a file list: ``http(s)://`` names get an
    :class:`HTTPStore`, ``gs://`` / ``s3://`` get one with the matching
    endpoint adapter, local paths get None (the loader's classic path).
    Mixed lists are rejected — one pipeline, one byte source."""
    schemes = set()
    for p in paths:
        p = str(p)
        if p.startswith(("http://", "https://")):
            schemes.add("http")
        elif p.startswith("gs://"):
            schemes.add("gs")
        elif p.startswith("s3://"):
            schemes.add("s3")
        else:
            schemes.add("local")
    if len(schemes) > 1:
        raise ValueError(
            "mixed shard sources {} — one pipeline reads one store".format(sorted(schemes))
        )
    scheme = schemes.pop() if schemes else "local"
    if scheme == "http":
        return HTTPStore()
    if scheme == "gs":
        return HTTPStore(adapter=GCSAdapter())
    if scheme == "s3":
        return HTTPStore(adapter=S3Adapter())
    return None
