"""``ShardStore`` — the ABI every shard source implements.

The contract mirrors what ``native_io.read_records_chunked`` has always
exposed to the loader, generalized off the local filesystem:

- ``list_shards(root)`` — the shard files under a corpus root, in the
  **deterministic order the sharding contract depends on** (sorted by
  shard basename, then full path — identical for a local directory and a
  remote listing of the same corpus, so ``shard_files`` assigns the same
  shards to the same workers either way).
- ``stat(path)`` — ``{"size": bytes, ...}`` without reading the object.
- ``open(path) → ChunkReader`` — ``read_chunk(n)`` / ``close()``, the
  chunked-read contract of :mod:`~tensorflowonspark_tpu.store.framing`.
- ``read_records_chunked(path)`` — the loader-facing generator built from
  the three primitives via :func:`framing.iter_chunks` (retried open,
  never-retried stream, close on every exit).
- ``fingerprint()`` — a short backend id (recorded by bench runs so a
  measured number names the store it was measured against).

Concrete stores: :class:`~tensorflowonspark_tpu.store.local.LocalStore`
(today's filesystem path, native fast path preserved) and
:class:`~tensorflowonspark_tpu.store.http.HTTPStore` (range-GET chunked
reads; GCS/S3 ride the same code path via endpoint adapters).
"""

import threading

from tensorflowonspark_tpu.store import framing


def shard_sort_key(path):
    """Order shards by basename first, full path second: a local glob and
    a remote URL listing of the same corpus sort identically, so worker
    shard assignment cannot depend on where the corpus lives."""
    p = str(path).rstrip("/")
    return (p.rsplit("/", 1)[-1], p)


class ShardStore:
    """ABI; see the module docstring. Subclasses set :attr:`retry` to the
    ``resilience.RetryPolicy`` their ``open`` is retried under."""

    retry = None

    def handles(self, path):
        """True when ``path`` names an object in this store."""
        raise NotImplementedError

    def list_shards(self, root):
        raise NotImplementedError

    def stat(self, path):
        raise NotImplementedError

    def open(self, path, verify_crc=True):
        raise NotImplementedError

    def fingerprint(self):
        raise NotImplementedError

    def fetch(self, path, out_f):
        """Copy the raw object bytes to the open binary file ``out_f`` (the
        staging tier's download primitive). Returns the byte count."""
        raise NotImplementedError

    def read_records_chunked(self, path, chunk_records=1024, verify_crc=True):
        """Generator of record-chunk lists — the loader's streaming ABI."""
        note_backend(self.fingerprint())
        return framing.iter_chunks(
            lambda: self.open(path, verify_crc=verify_crc),
            chunk_records,
            retry=self.retry,
        )

    def read_records(self, path, verify_crc=True):
        """All record payloads of one shard as a single list (bulk path)."""
        out = []
        for chunk in self.read_records_chunked(path, 4096, verify_crc=verify_crc):
            out.extend(chunk)
        return out


# -- backend fingerprint (for bench provenance) --------------------------------

_fingerprint_lock = threading.Lock()
_active_fingerprint = "local"


def note_backend(fingerprint):
    """Record the most recently used store backend; bench runs embed it in
    their stalls block so a measured rate names its byte source."""
    global _active_fingerprint
    with _fingerprint_lock:
        _active_fingerprint = str(fingerprint)


def active_fingerprint():
    with _fingerprint_lock:
        return _active_fingerprint
