"""``store/`` — shard sources behind the read-ahead plane.

The loader's byte source, generalized off the local filesystem:

- :mod:`~tensorflowonspark_tpu.store.framing` — the one TFRecord
  framing/chunk implementation (``tfrecord`` and ``native_io`` both
  delegate here).
- :mod:`~tensorflowonspark_tpu.store.base` — the :class:`ShardStore` ABI
  (``list_shards`` / ``stat`` / ``open → read_chunk → close``).
- :mod:`~tensorflowonspark_tpu.store.local` — today's filesystem path,
  native fast path preserved.
- :mod:`~tensorflowonspark_tpu.store.http` — range-GET remote reads
  (plain HTTP, GCS/S3 via endpoint adapters) under a retry policy.
- :mod:`~tensorflowonspark_tpu.store.staging` — prefetch-to-local-disk
  tier steered by the read-ahead autotuner (imported lazily by consumers:
  it pulls in ``data.autotune``, which the leaf modules here must not).
"""

from tensorflowonspark_tpu.store import base, framing, http, local
from tensorflowonspark_tpu.store.base import ShardStore, active_fingerprint, shard_sort_key
from tensorflowonspark_tpu.store.http import GCSAdapter, HTTPStore, IndexHtmlAdapter, S3Adapter, resolve_store
from tensorflowonspark_tpu.store.local import LocalStore

__all__ = [
    "ShardStore",
    "LocalStore",
    "HTTPStore",
    "IndexHtmlAdapter",
    "GCSAdapter",
    "S3Adapter",
    "active_fingerprint",
    "resolve_store",
    "shard_sort_key",
    "base",
    "framing",
    "http",
    "local",
]
