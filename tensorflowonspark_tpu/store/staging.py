"""Prefetch-to-local-disk staging tier for remote shard stores.

A remote shard read has two costs the local path never paid: per-range
request latency and wide-area bandwidth. The stager hides both by
downloading whole shards to executor-local disk *ahead* of the reader —
the same stall-driven discipline the shard read-ahead plane already uses,
steered by the same controller: a
:class:`~tensorflowonspark_tpu.data.autotune.ReadaheadAutotuner` watches
the producer/consumer stall counters and deepens the prefetch window when
the classification says io_bound (consumer starved while shard reads
dominate parse), shallows it when the pipeline demonstrably keeps up.
The depth it chooses is published on the ``store_prefetch_depth`` gauge.

Staged shards commit with the tree-wide durable-publish idiom
(:mod:`tensorflowonspark_tpu.durable`, the commit-discipline rule of
``python -m tosa``): bytes download into a ``tmp.obj-*`` staging
directory, the data file is fsynced, ``MANIFEST.json`` is written last,
one atomic rename publishes, the parent directory entry is fsynced, and
the shard is *adopted* only after ``manifest.verify`` passes on the
published name. Verify runs again on first use of any staged shard this
process did not verify itself (warm reopen after a crash), so a torn
publish — a power cut mid-commit, or the ``store.prefetch_tear`` chaos
site — is rejected, deleted, and the shard is simply re-fetched: the
staging tier can serve cold or serve verified bytes, never garbage.

The staged tier is capacity-bounded (``TOS_PREFETCH_BYTES``): once the
resident bytes exceed the bound, least-recently-used shards are evicted
(``store_prefetch_evictions_total``) and fall back to the remote cold
store on next use — the bottom rung of the tier hierarchy documented in
docs/architecture.md.

Env lanes: ``TOS_PREFETCH_DIR`` (staging root, default
``$TMPDIR/tos-prefetch``), ``TOS_STORE_PREFETCH`` (window depth; ``auto``
default = autotuned, ``0`` disables staging so remote shards stream
cold), ``TOS_PREFETCH_BYTES`` (staged-tier capacity, 0/unset =
unbounded).
"""

import concurrent.futures
import logging
import os
import shutil
import tempfile
import threading
import uuid
import zlib

from tensorflowonspark_tpu import chaos, durable, obs
from tensorflowonspark_tpu.ckpt import manifest

logger = logging.getLogger(__name__)

DIR_ENV = "TOS_PREFETCH_DIR"
DEPTH_ENV = "TOS_STORE_PREFETCH"
BYTES_ENV = "TOS_PREFETCH_BYTES"

_DATA_NAME = "data.bin"
#: background download threads: enough to overlap fetch with consume,
#: few enough that the staging tier never competes with the reader pool
_FETCH_THREADS = 2


def default_root():
    return os.path.join(tempfile.gettempdir(), "tos-prefetch")


def _obj_dirname(path):
    """Filesystem-safe staged-directory name for one remote shard: the
    readable basename plus a crc of the full URL so distinct corpora whose
    shards share basenames cannot collide."""
    base = str(path).rstrip("/").rsplit("/", 1)[-1]
    safe = "".join(c if c.isalnum() or c in "._-" else "_" for c in base)
    return "obj-{}-{:08x}".format(safe[:80], zlib.crc32(str(path).encode()))


def resolve_stager(store, prefetch=None, root=None, capacity_bytes=None):
    """Build the staging tier implied by the knobs: ``prefetch`` (default
    ``$TOS_STORE_PREFETCH`` or ``auto``) of ``0``/``off`` means *no
    stager* — remote shards stream cold through range-GETs — otherwise a
    :class:`PrefetchStager` with a fixed or autotuned window."""
    if prefetch is None:
        prefetch = os.environ.get(DEPTH_ENV, "auto")
    mode = str(prefetch).strip().lower()
    if mode in ("0", "off", "cold", "none", "false"):
        return None
    depth = None if mode == "auto" else max(1, int(mode))
    if root is None:
        root = os.environ.get(DIR_ENV) or default_root()
    if capacity_bytes is None:
        capacity_bytes = int(os.environ.get(BYTES_ENV, "0")) or None
    return PrefetchStager(store, root=root, depth=depth, capacity_bytes=capacity_bytes)


class PrefetchStager:
    """Downloads remote shards to local disk ahead of the reader.

    ``plan(order)`` declares one epoch's shard visit order and warms the
    window; ``fetch(path)`` blocks until ``path`` is staged (foreground
    download on a miss) and returns the local data file the classic loader
    path then reads natively; ``close()`` drains the download pool. All
    shared state is guarded by one lock; downloads run on a small named
    thread pool.
    """

    def __init__(self, store, root=None, depth=None, capacity_bytes=None, clock=None):
        from tensorflowonspark_tpu.data import autotune

        self.store = store
        self.root = os.path.abspath(os.path.expanduser(root or default_root()))
        os.makedirs(self.root, exist_ok=True)
        self.capacity_bytes = capacity_bytes
        self._lock = threading.Lock()
        self._order = []  # current epoch's shard visit order
        self._cursor = 0  # index of the next shard fetch() will ask for
        self._futures = {}  # path -> in-flight download future
        self._verified = set()  # staged dirs verified by THIS process
        self._sizes = {}  # staged dir -> bytes (for the capacity bound)
        self._tick = 0  # monotonic use counter driving LRU eviction
        self._last_use = {}  # staged dir -> tick of last use
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=_FETCH_THREADS, thread_name_prefix="tos-store-prefetch"
        )
        self._hits_c = obs.counter(
            "store_prefetch_hits_total",
            help="shard reads served from the local staged tier",
        )
        self._misses_c = obs.counter(
            "store_prefetch_misses_total",
            help="shard reads that had to wait on (or run) a remote download",
        )
        self._commits_c = obs.counter(
            "store_prefetch_commits_total",
            help="staged shards published and adopted after verify",
        )
        self._rejects_c = obs.counter(
            "store_prefetch_rejects_total",
            help="staged shards rejected by verify-on-read and re-fetched",
        )
        self._evict_c = obs.counter(
            "store_prefetch_evictions_total",
            help="staged shards evicted by the capacity bound",
        )
        self._bytes_g = obs.gauge(
            "store_prefetch_bytes", help="bytes resident in the staged shard tier"
        )
        self._depth_g = obs.gauge(
            "store_prefetch_depth", help="remote shard prefetch window depth"
        )
        if depth is None:
            self._tuner = autotune.ReadaheadAutotuner(
                min_depth=1,
                max_depth=autotune.DEFAULT_MAX_READAHEAD,
                clock=clock,
                gauge=self._depth_g,
            )
            self.depth = 2  # starting window; the stall rule moves it
        else:
            self._tuner = None
            self.depth = max(1, int(depth))
        self._depth_g.set(int(self.depth))
        self._sweep_leftovers()

    # -- lifecycle --------------------------------------------------------------

    def _sweep_leftovers(self):
        """Adopt staged shards left by an earlier process (verify deferred
        to first use) and clear abandoned ``tmp.obj-*`` staging dirs."""
        total = 0
        for name in os.listdir(self.root):
            full = os.path.join(self.root, name)
            if name.startswith("tmp.obj-"):
                shutil.rmtree(full, ignore_errors=True)
            elif name.startswith("obj-") and os.path.isdir(full):
                try:
                    size = os.path.getsize(os.path.join(full, _DATA_NAME))
                except OSError:
                    size = 0
                self._sizes[full] = size
                self._last_use[full] = 0
                total += size
        self._bytes_g.set(float(total))
        # a reopened tier honors the (possibly tightened) capacity bound
        self._evict_over_capacity()

    def close(self):
        """Drain the download pool; staged shards stay on disk (they are
        the warm tier the next run reopens)."""
        with self._lock:
            futures = list(self._futures.values())
            self._futures.clear()
        for f in futures:
            f.cancel()
        self._pool.shutdown(wait=True)

    # -- epoch window -----------------------------------------------------------

    def plan(self, order):
        """Declare one epoch's shard visit order and warm the first
        ``depth`` shards in the background."""
        with self._lock:
            self._order = [str(p) for p in order]
            self._cursor = 0
        self._top_up()

    def _top_up(self):
        """Schedule background downloads for unstaged shards inside the
        window ``[cursor, cursor + depth)``."""
        with self._lock:
            window = self._order[self._cursor : self._cursor + int(self.depth)]
            for path in window:
                final = os.path.join(self.root, _obj_dirname(path))
                if final in self._sizes or path in self._futures:
                    continue
                self._futures[path] = self._pool.submit(self._stage_quiet, path)

    def _stage_quiet(self, path):
        try:
            return self._stage(path)
        except Exception as e:  # background lane: a failed prefetch is a
            # cold read later, never a crashed pipeline
            logger.warning("store prefetch of %s failed: %s", path, e)
            return None

    # -- serving ----------------------------------------------------------------

    def fetch(self, path):
        """Block until ``path`` is staged and verified; returns the local
        data file path, or None when staging failed (caller reads cold).
        Advances the window and ticks the depth autotuner."""
        path = str(path)
        final = os.path.join(self.root, _obj_dirname(path))
        with self._lock:
            try:
                self._cursor = self._order.index(path, self._cursor) + 1
            except ValueError:
                pass
            self._tick += 1
            self._last_use[final] = self._tick
            staged = final in self._sizes
            future = self._futures.get(path)
        if staged and future is None:
            data = self._verify_on_read(final, path)
            if data is not None:
                self._hits_c.inc()
                self._after_fetch()
                return data
            staged = False
        self._misses_c.inc()
        if future is not None:
            data = future.result()
            with self._lock:
                self._futures.pop(path, None)
        else:
            data = self._stage_quiet(path)
        self._after_fetch()
        return data

    def _after_fetch(self):
        if self._tuner is not None:
            target = self._tuner.tick(self.depth)
            if target is not None and target != self.depth:
                logger.info("store prefetch window: %d -> %d", self.depth, target)
                self.depth = target
        self._top_up()

    def _verify_on_read(self, final, path):
        """The staged data file, after the first-use integrity check for
        shards staged by an earlier process. A reject deletes the staged
        dir so the caller re-fetches."""
        with self._lock:
            seen = final in self._verified
        if not seen:
            ok, reason = manifest.verify(final)
            if not ok:
                logger.warning(
                    "store prefetch: rejecting staged %s (%s)", final, reason
                )
                self._rejects_c.inc()
                self._drop(final)
                return None
            with self._lock:
                self._verified.add(final)
        return os.path.join(final, _DATA_NAME)

    # -- staging commit ---------------------------------------------------------

    def _stage(self, path):
        """Download ``path`` and publish it into the staged tier with the
        durable commit idiom: fsync the data file, ``MANIFEST.json`` last,
        atomic rename, parent-directory fsync, adopt only after verify."""
        final = os.path.join(self.root, _obj_dirname(path))
        with self._lock:
            if final in self._sizes and final in self._verified:
                return os.path.join(final, _DATA_NAME)
        stage = os.path.join(self.root, "tmp.obj-{}".format(uuid.uuid4().hex[:8]))
        os.makedirs(stage)
        try:
            with open(os.path.join(stage, _DATA_NAME), "wb") as f:
                nbytes = self.store.fetch(path, f)
                f.flush()
                os.fsync(f.fileno())
            manifest.write_manifest(stage, extra={"source": str(path)})
            if chaos.active and chaos.fire("store.prefetch_tear"):
                # publish a *torn* manifest: the commit marker exists but
                # lies, exactly what a crash mid-publish leaves behind
                mpath = os.path.join(stage, manifest.MANIFEST_NAME)
                with open(mpath, "r+") as mf:
                    mf.truncate(os.path.getsize(mpath) // 2)
            if os.path.exists(final):  # lost a race or replacing a reject
                shutil.rmtree(final, ignore_errors=True)
            os.rename(stage, final)
        except Exception:
            shutil.rmtree(stage, ignore_errors=True)
            raise
        # the rename is only durable once the root's entry table is —
        # without this a power cut can replay the directory with the old
        # (deleted) entry and the verify-on-read contract does the rest
        durable.fsync_dir(self.root)
        ok, reason = manifest.verify(final)
        if not ok:
            logger.warning(
                "store prefetch: published shard failed verify (%s); dropping", reason
            )
            self._rejects_c.inc()
            shutil.rmtree(final, ignore_errors=True)
            return None
        with self._lock:
            self._sizes[final] = int(nbytes)
            self._last_use.setdefault(final, self._tick)
            self._verified.add(final)
            total = sum(self._sizes.values())
        self._commits_c.inc()
        self._bytes_g.set(float(total))
        self._evict_over_capacity()
        return os.path.join(final, _DATA_NAME)

    # -- capacity bound ---------------------------------------------------------

    def _drop(self, final):
        with self._lock:
            self._sizes.pop(final, None)
            self._verified.discard(final)
            self._last_use.pop(final, None)
            total = sum(self._sizes.values())
        shutil.rmtree(final, ignore_errors=True)
        self._bytes_g.set(float(total))

    def _evict_over_capacity(self):
        """Evict least-recently-used staged shards until resident bytes fit
        the capacity bound; evicted shards fall back to the remote cold
        store on next use."""
        if not self.capacity_bytes:
            return
        while True:
            with self._lock:
                total = sum(self._sizes.values())
                if total <= self.capacity_bytes or len(self._sizes) <= 1:
                    return
                victim = min(self._sizes, key=lambda d: self._last_use.get(d, 0))
            logger.info("store prefetch: evicting %s (tier over capacity)", victim)
            self._evict_c.inc()
            self._drop(victim)
