"""Small host-side utilities shared by driver and executors.

Capability-parity with /root/reference/tensorflowonspark/util.py (IP discovery,
PATH search, executor-id persistence, single-node env setup) but adapted for the
jax/TPU runtime: ``single_node_env`` prepares a jax process instead of a TF one,
and the executor-id file also records the local IPC manager address so later
Spark tasks landing on the same executor can reconnect to the running jax
process (reference: util.py:77-86 + TFSparkNode.py:97-123).
"""

import errno
import json
import logging
import multiprocessing
import os
import socket

from tensorflowonspark_tpu import durable

logger = logging.getLogger(__name__)

_mp_spawn = multiprocessing.get_context("spawn")

#: log format carrying process/thread names — the runtime spans a driver,
#: N executor processes and N jax child processes, so bare messages are
#: un-attributable (reference tensorflowonspark/__init__.py:3)
LOG_FORMAT = "%(asctime)s %(levelname)s (%(processName)s %(threadName)s) %(name)s: %(message)s"


def setup_logging(level=logging.INFO):
    """Configure root logging for an APPLICATION entry point (examples,
    bench.py, the jax child process). Libraries must never do this at import
    time — importing :mod:`tensorflowonspark_tpu` leaves the root logger's
    handlers untouched so embedding applications keep control of their own
    logging (enforced by the ``import-hygiene`` rule of ``python -m tosa``
    and a regression test). No-op if the root logger is already configured."""
    logging.basicConfig(level=level, format=LOG_FORMAT)


def _spawn_trampoline(blob):
    import cloudpickle

    cloudpickle.loads(blob)()


def spawn_process(fn, name=None):
    """A ``multiprocessing.Process`` running ``fn()`` in a **spawned** child.

    Spawn (not fork) everywhere: executors, IPC servers, and jax children are
    all started from processes that may carry threads (pytest, jax's own
    thread pools, queue feeders), and forking a threaded process deadlocks —
    python 3.12 warns about exactly this. ``fn`` may be any cloudpickle-able
    zero-arg callable (closures included); a spawned child only needs the
    module-level trampoline to be importable.
    """
    import cloudpickle

    return _mp_spawn.Process(target=_spawn_trampoline, args=(cloudpickle.dumps(fn),), name=name)

# Name of the per-executor state file written into the executor's CWD.
EXECUTOR_STATE_FILE = "tos_tpu_executor.json"


def get_ip_address():
    """Best-effort routable IP address of this host.

    Uses the UDP-connect trick (no packet is actually sent, so it works in
    zero-egress environments), falling back to hostname resolution and finally
    loopback. Reference: util.py:52.
    """
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 53))
            return s.getsockname()[0]
    except OSError:
        pass
    try:
        return socket.gethostbyname(socket.gethostname())
    except OSError:
        return "127.0.0.1"


def find_in_path(path, file_name):
    """Find a file within a ':'-separated search path (reference util.py:68)."""
    for p in path.split(os.pathsep):
        candidate = os.path.join(p, file_name)
        if os.path.exists(candidate) and os.path.isfile(candidate):
            return candidate
    return False


def write_executor_state(state, cwd=None):
    """Persist per-executor bootstrap state (executor id, IPC manager address,
    authkey) to a file in the executor's working directory.

    The reference persisted just the executor id (util.py:77-82); we persist the
    whole reconnect record because feeding tasks scheduled later onto this
    executor must find the already-running jax process's IPC manager.
    ``authkey`` bytes are hex-encoded.
    """
    record = dict(state)
    if isinstance(record.get("authkey"), bytes):
        record["authkey"] = record["authkey"].hex()
        record["authkey_hex"] = True
    path = os.path.join(cwd or os.getcwd(), EXECUTOR_STATE_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(record, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    # reconnect-after-crash reads this record; a torn or vanished file
    # strands later tasks without the running jax child's IPC address
    durable.fsync_dir(os.path.dirname(path))
    return path


def read_executor_state(cwd=None):
    """Read the record written by :func:`write_executor_state`, or None."""
    path = os.path.join(cwd or os.getcwd(), EXECUTOR_STATE_FILE)
    try:
        with open(path) as f:
            record = json.load(f)
    except OSError as e:
        if e.errno in (errno.ENOENT,):
            return None
        raise
    if record.pop("authkey_hex", False):
        record["authkey"] = bytes.fromhex(record["authkey"])
    return record


def force_platform(platform, num_cpu_devices=None):
    """Force the jax platform for THIS process, config-API-first.

    Env vars alone are not enough on hosts whose site setup pre-imports jax
    and pins a platform through ``jax.config`` (the config value wins over
    ``JAX_PLATFORMS``) — e.g. TPU pods whose runtime registers the PJRT
    plugin in every interpreter. Must run before the first jax backend use.
    ``num_cpu_devices`` forces that many virtual CPU devices (test worlds).
    """
    os.environ["JAX_PLATFORMS"] = platform
    if num_cpu_devices and platform == "cpu":
        import re

        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        )
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count={}".format(int(num_cpu_devices))
        ).strip()
    import jax

    jax.config.update("jax_platforms", platform)


def single_node_env(num_cpu_devices=None, platform=None):
    """Prepare the environment for a *single-node* jax process.

    The reference's version wired up the Hadoop classpath and CUDA_VISIBLE_DEVICES
    (util.py:21-49); the TPU-native analogue selects the jax platform and,
    for CPU-backed tests, a virtual device count — this must run before jax is
    imported in the process.
    """
    if platform:
        os.environ["JAX_PLATFORMS"] = platform
    if num_cpu_devices:
        flags = os.environ.get("XLA_FLAGS", "")
        opt = "--xla_force_host_platform_device_count={}".format(num_cpu_devices)
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (flags + " " + opt).strip()


def find_free_port(host=""):
    """Bind-and-release a TCP port; used for coordinator/profiler ports.

    The reference bound a free port for the TF grpc server
    (TFSparkNode.py:252-255); here ports are needed for the jax.distributed
    coordinator and the profiler server.
    """
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host, 0))
        return s.getsockname()[1]
