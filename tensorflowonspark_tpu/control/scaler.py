"""The cluster-level member of the controller family: target world size.

The recovery ladder (:func:`~tensorflowonspark_tpu.elastic.run_ladder`)
shrinks reactively — a failure costs capacity the moment the ledger
condemns it. Growing back is a *choice*, and a bad one is expensive: a
regrow restart drains and relaunches the whole cluster, so flapping on a
node that is about to die again costs more than training small for one
more interval. :class:`ClusterScaler` is that choice expressed through the
shared :class:`~tensorflowonspark_tpu.control.core.Controller` discipline,
inverted from the per-process tuners: **down immediately** (the capacity
is already gone; refusing to acknowledge it helps nobody) and **up only
after ``grow_patience`` consecutive healthy verdicts** (a returning
executor must stay probe-healthy across intervals before the ladder pays
for a restart).

The grow gate also consults the same stall/throughput classification the
per-process tuners reason from
(:func:`~tensorflowonspark_tpu.control.core.classify_stalls`): when the
last interval was input-bound (``io_bound`` / ``decode_bound``), more
workers on the same starved input path buy nothing — regrow is deferred
until the input path recovers or the verdict ages out. ``device_bound``
(or no stall data at all, the common case between intervals) means compute
is the gate, and more compute helps.

Publishes the ``target_world_size`` gauge on every verdict so the merged
metrics always show where the scaler is steering, not just where the
cluster currently is.
"""

import logging

from tensorflowonspark_tpu import obs
from tensorflowonspark_tpu.control.core import Controller

logger = logging.getLogger(__name__)

#: stall verdicts under which adding workers cannot raise throughput: the
#: input path, not compute, is the gate
INPUT_BOUND = frozenset({"io_bound", "decode_bound"})


class ClusterScaler:
    """Choose the target executor count for the recovery ladder.

    ``full_size`` is the job's requested world; ``min_size`` the floor the
    ladder enforces anyway. :meth:`decide` is called from the ladder's
    regrow poll with the *current* size, the size the re-probed capacity
    argues for (``desired``, usually ``plan_size`` after forgiveness), and
    the latest stall classification; it returns the size the discipline
    allows right now. One rung per verdict: the gate decides *whether* to
    pay for a restart — the relaunch itself regrows to the full re-probed
    plan.
    """

    def __init__(self, full_size, min_size=1, grow_patience=2, name="cluster"):
        self.full_size = int(full_size)
        self.min_size = max(1, int(min_size))
        self._ctl = Controller(
            lo=self.min_size, hi=self.full_size,
            up_patience=grow_patience, down_patience=1, name=name,
        )
        self._target_g = obs.gauge(
            "target_world_size",
            help="executor count the cluster scaler is currently steering toward",
        )

    @property
    def grow_patience(self):
        return self._ctl.up_patience

    def decide(self, current, desired, classification=None):
        """One scaling verdict; returns the allowed next world size."""
        if desired > current and classification in INPUT_BOUND:
            # more mouths on a starved input path help nothing: hold, and
            # clear any accumulated grow credit — the cluster must be
            # healthy AND compute-bound across the whole patience window
            self._ctl.reset()
            target = current
        else:
            want = (desired > current) - (desired < current)
            target = self._ctl.step(current, want)
        if target != current:
            logger.info(
                "cluster scaler: %d -> %d executor(s) (desired %d, %s)",
                current, target, desired, classification or "no stall data",
            )
        self._target_g.set(target)
        return target

    def observe(self, actual):
        """Snap to a size the ladder imposed outside a verdict (a failure
        shrink): clear the streaks — the regime changed — and republish the
        gauge so the metrics never show a stale target."""
        self._ctl.reset()
        self._target_g.set(int(actual))
