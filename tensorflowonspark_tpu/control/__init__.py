"""One audited control core for every estimate→decide→patience→apply loop.

Three subsystems grew the same controller shape independently:
:class:`~tensorflowonspark_tpu.data.autotune.FeedAutotuner` (packed-window
size K), :class:`~tensorflowonspark_tpu.data.autotune.ReadaheadAutotuner`
(shard read-ahead depth) and
:class:`~tensorflowonspark_tpu.data.decode_plane.DecodeAutotuner` (decode
worker count). Each one estimates a signal, argues for a direction, applies
**up-fast / down-slow hysteresis** (a stall is expensive *now*; releasing
capacity can wait for proof), and moves its knob one rung at a time inside
bounds. Hand-rolling that loop three times meant three slightly different
streak bugs waiting to happen and zero shared observability.

This package extracts the loop once:

* :class:`~tensorflowonspark_tpu.control.core.EwmaEstimator` — the
  seed-on-first-observation EWMA every estimator here builds on.
* :class:`~tensorflowonspark_tpu.control.core.Controller` — the audited
  move engine: an ordered ladder of values (explicit levels or an integer
  range), ``up_patience``/``down_patience`` streaks, bound clamping, and a
  ``control_decisions_total`` counter plus a ``control_decision`` span on
  every applied move — so *why the knob moved* is visible in
  ``TFCluster.metrics()`` and on the merged timeline.
* :class:`~tensorflowonspark_tpu.control.core.DeltaTicker` — the clocked
  counter-delta gate (``check_every`` seconds between reads) the interval
  tuners share.
* :func:`~tensorflowonspark_tpu.control.core.classify_stalls` — the
  stall/throughput classification (previously ``bench.classify_stalls``,
  which now re-exports it) shared by the per-process tuners and the
  cluster scaler.
* :class:`~tensorflowonspark_tpu.control.scaler.ClusterScaler` — the
  cluster-level member of the family: chooses the target world size for
  the recovery ladder (:func:`~tensorflowonspark_tpu.elastic.run_ladder`)
  from capacity health plus the same stall classification, gating regrow
  restarts behind ``grow_patience`` and publishing ``target_world_size``.

All three per-process autotuners are rebased on this core with their
behavior pinned by their pre-existing test suites (tests/test_autotune.py,
tests/test_decode_plane.py) — the extraction is a refactor, not a policy
change.
"""

from tensorflowonspark_tpu.control.core import (  # noqa: F401
    Controller,
    DeltaTicker,
    EwmaEstimator,
    StallRule,
    classify_stalls,
)
from tensorflowonspark_tpu.control.scaler import ClusterScaler  # noqa: F401
