"""The shared estimate→decide→patience→apply machinery.

Every controller in the tree follows the same discipline (see the package
docstring): estimate from measurements, argue for a direction, move **up
immediately** (by default) because a stall is costing throughput right now,
move **down only after ``down_patience`` consecutive lower verdicts**
because flapping a knob (recompiles, fork storms, cluster restarts) costs
more than holding it one interval too long. :class:`Controller` is that
discipline, once, with decisions counted and traced.
"""

import time

from tensorflowonspark_tpu import obs


def classify_stalls(read_s, parse_s, emit_s, wait_s):
    """Name the bottleneck the stall counters point at: the producer
    blocking on a full prefetch queue at least as long as the consumer
    starved means the consumer (device) is the gate (``device_bound``);
    otherwise the input path is, split by which producer stage dominated —
    ``decode_bound`` when parse time beats shard IO, ``io_bound`` when
    reads do. Shared by ``bench.py`` (the BENCH JSON's ``classification``
    field), the per-process autotuners' rationale, and the cluster scaler's
    regrow gate."""
    if emit_s >= wait_s:
        return "device_bound"
    return "decode_bound" if parse_s >= read_s else "io_bound"


class EwmaEstimator:
    """Seed-on-first-observation exponential moving average.

    ``alpha`` weights the newest observation (0.3 default: responsive
    within a handful of samples, yet one freak sample cannot swing a
    decision by itself). ``value`` is None until the first observation —
    the one-shot seeding contract every estimator in the family relies on
    (:class:`~tensorflowonspark_tpu.data.autotune.LinkEstimator` seeds its
    fixed-cost and bandwidth terms exactly this way).
    """

    def __init__(self, alpha=0.3):
        if not 0.0 < alpha <= 1.0:
            raise ValueError("alpha must be in (0, 1]")
        self.alpha = alpha
        self.value = None

    def observe(self, sample):
        """Blend one sample in (first sample seeds directly); returns the
        updated value."""
        self.value = self.blend(self.value, sample)
        return self.value

    def blend(self, old, new):
        """The pure EWMA step on explicit operands — for estimators that
        keep several blended terms under one alpha."""
        return new if old is None else (1.0 - self.alpha) * old + self.alpha * new


class StallRule:
    """The starvation verdict shared by the interval tuners: is the
    consumer starving badly enough — for a cause this knob can fix — to
    grow, or idle enough to shrink?

    * wait share above ``starve_ratio`` AND the pressure this controller
      owns dominated the interval → **+1** (grow).
    * wait share below ``idle_ratio`` → **−1** (shrink candidate; the
      :class:`Controller`'s down-patience decides when it actually lands).
    * anything between → **0** (hold).
    """

    def __init__(self, starve_ratio=0.05, idle_ratio=0.01):
        self.starve_ratio = float(starve_ratio)
        self.idle_ratio = float(idle_ratio)

    def want(self, wait_share, pressure_dominates):
        if wait_share > self.starve_ratio and pressure_dominates:
            return 1
        if wait_share < self.idle_ratio:
            return -1
        return 0


class Controller:
    """The audited hysteresis move engine over an ordered value ladder.

    The ladder is either an explicit ``levels`` tuple (the feed tuner's
    power-of-two buckets) or the integer range ``[lo, hi]`` (worker
    counts, depths, world sizes). :meth:`step` takes the current value and
    a wanted direction (+1/0/−1) and returns the value the discipline
    allows:

    * **up**: after ``up_patience`` consecutive +1 verdicts (default 1 —
      immediate, the up-fast half), one rung up, clamped at the top.
    * **down**: after ``down_patience`` consecutive −1 verdicts
      (hysteresis against mood flicker), one rung down. A −1 at the
      bottom rung is a hold *and clears the streak* — pinned tuner
      behavior: idle intervals at the floor don't accumulate credit
      toward a move that can never happen.
    * **hold** (0): clears both streaks.

    Every applied move increments ``control_decisions_total`` and records
    a ``control_decision`` span carrying the controller ``name`` and the
    from/to values, so knob movement is auditable in the merged metrics
    and on the trace timeline. Streak state is per-instance; the counter
    is process-global like every obs metric.
    """

    def __init__(self, levels=None, lo=None, hi=None, up_patience=1,
                 down_patience=2, name="controller"):
        if levels is not None:
            self.levels = tuple(sorted(set(levels)))
            if not self.levels:
                raise ValueError("levels must be non-empty")
        else:
            if lo is None or hi is None:
                raise ValueError("give either levels or lo/hi bounds")
            if int(hi) < int(lo):
                raise ValueError("hi must be >= lo")
            self.levels = None
            self.lo, self.hi = int(lo), int(hi)
        self.up_patience = max(1, int(up_patience))
        self.down_patience = max(1, int(down_patience))
        self.name = str(name)
        self._up_streak = 0
        self._down_streak = 0
        self._decisions = obs.counter(
            "control_decisions_total",
            help="knob moves applied by control.Controller instances",
        )

    # -- ladder navigation ------------------------------------------------------

    def floor(self):
        return self.levels[0] if self.levels is not None else self.lo

    def ceiling(self):
        return self.levels[-1] if self.levels is not None else self.hi

    def _rung(self, value, direction):
        if self.levels is not None:
            i = self.levels.index(value) + direction
            return self.levels[max(0, min(len(self.levels) - 1, i))]
        return max(self.lo, min(self.hi, int(value) + direction))

    # -- the discipline ---------------------------------------------------------

    def reset(self):
        """Clear both patience streaks (a regime change — e.g. a cluster
        relaunch — invalidates accumulated evidence)."""
        self._up_streak = 0
        self._down_streak = 0

    def step(self, current, want):
        """Apply one verdict; returns the new value (``current`` when the
        discipline holds)."""
        if want > 0:
            self._down_streak = 0
            if current >= self.ceiling():
                self._up_streak = 0
                return current
            self._up_streak += 1
            if self._up_streak < self.up_patience:
                return current
            self._up_streak = 0
            return self._move(current, +1)
        if want < 0:
            self._up_streak = 0
            if current <= self.floor():
                self._down_streak = 0
                return current
            self._down_streak += 1
            if self._down_streak < self.down_patience:
                return current
            self._down_streak = 0
            return self._move(current, -1)
        self.reset()
        return current

    def toward(self, current, recommended):
        """Direction-from-target convenience: one :meth:`step` toward
        ``recommended`` (the feed tuner's decide shape — the model argues
        for a value, the discipline walks there one rung at a time)."""
        want = (recommended > current) - (recommended < current)
        return self.step(current, want)

    def _move(self, current, direction):
        new = self._rung(current, direction)
        if new != current:
            self._decisions.inc()
            with obs.span(
                "control_decision", controller=self.name,
                from_value=current, to_value=new,
            ):
                pass  # marker span: the wall-clock point the knob moved
        return new


class DeltaTicker:
    """The clocked counter-delta gate the interval tuners share.

    ``read`` returns a tuple of cumulative counters; :meth:`tick` returns
    ``(deltas, elapsed)`` at most every ``check_every`` seconds and None
    between intervals. The first call only seeds the baseline (no verdict
    from a window of unknown length), and ``read`` is not consulted at all
    on sub-interval calls — counter reads can be snapshot-priced.
    ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, check_every, read, clock=None):
        self.check_every = float(check_every)
        self._read = read
        self._clock = clock or time.monotonic
        self._last_t = None
        self._last = None

    def tick(self):
        now = self._clock()
        if self._last_t is None:
            self._last_t, self._last = now, self._read()
            return None
        elapsed = now - self._last_t
        if elapsed < self.check_every:
            return None
        values = self._read()
        deltas = tuple(v - p for v, p in zip(values, self._last))
        self._last_t, self._last = now, values
        return deltas, elapsed
