"""Ring attention: sequence/context parallelism over a mesh axis.

Long-context capability absent from the reference (SURVEY.md §5 "Long-context /
sequence parallelism: Absent") but first-class here: sequence length is sharded
over the ``sp`` mesh axis; each device holds one block of queries and one block
of keys/values, computes blockwise attention with a numerically-stable online
softmax (flash-attention style accumulation), and rotates the K/V blocks around
the ring with ``lax.ppermute`` so every query block eventually sees every K/V
block. Communication is neighbour-to-neighbour, so on TPU it rides single-hop
ICI links and overlaps with the matmuls of the previous block.

Memory per device is O(L_local²) per block pair instead of O(L²) for the full
sequence, so max context length scales linearly with the number of devices on
the ``sp`` axis.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

#: finite stand-in for -inf so fully-masked blocks produce exp(-BIG)=0 instead
#: of NaN via (-inf) - (-inf) in the running-max correction.
_NEG_BIG = -0.7 * float(jnp.finfo(jnp.float32).max)


def _block_attn(q_scaled, k, v, o, m, l, q_pos, k_pos, causal, q_seg=None, k_seg=None):
    """One flash-style accumulation step: fold a K/V block into (o, m, l)."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q_scaled, k.astype(jnp.float32))
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None], scores, _NEG_BIG)
    if q_seg is not None:
        # packed-sequence fence: a query only sees keys of its own segment
        # (ids are global, so the mask is exact no matter which ring hop
        # this K/V block came from)
        seg_mask = q_seg[:, None, :, None] == k_seg[:, None, None, :]
        scores = jnp.where(seg_mask, scores, _NEG_BIG)
    m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new)
    l_new = l * corr + p.sum(axis=-1, keepdims=True)
    o_new = o * corr + jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o_new, m_new, l_new


def ring_attention(q, k, v, axis_name, causal=False, scale=None, segment_ids=None):
    """Blockwise ring attention; call inside ``shard_map`` over ``axis_name``.

    ``q``/``k``/``v``: the *local* sequence block, ``[batch, heads, seq_local,
    head_dim]``. Per ring step, attention against the currently-held K/V block
    is accumulated online, then K/V rotate one hop (member i → i+1). Global
    causal masking uses each block's origin index, so the result is exactly
    standard causal attention on the concatenated sequence.

    ``segment_ids`` (``int32 [batch, seq_local]``, 0 = padding) is this
    member's local block of packed-sequence ids; the key-side ids rotate
    around the ring alongside K/V, so cross-segment scores are masked on
    every hop and packed sequences never cross-attend.
    """
    from tensorflowonspark_tpu.parallel.collectives import axis_size

    n = axis_size(axis_name)
    my = lax.axis_index(axis_name)
    _, _, l_q, head_dim = q.shape
    l_k = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(head_dim)
    q_scaled = q.astype(jnp.float32) * scale
    q_pos = my * l_q + jnp.arange(l_q)

    # accumulators derive from q/v zeros so they inherit the inputs' full set
    # of varying mesh axes — keeps the scan carry type stable under
    # shard_map's varying-axes checks regardless of what else (dp/fsdp) the
    # inputs are sharded over
    zero_qv = q_scaled[..., :1] * 0 + v.astype(jnp.float32)[..., :1].sum(2, keepdims=True) * 0
    o0 = jnp.zeros(q.shape[:3] + (v.shape[3],), jnp.float32) + zero_qv
    m0 = jnp.full(q.shape[:3] + (1,), _NEG_BIG, jnp.float32) + zero_qv
    l0 = jnp.zeros(q.shape[:3] + (1,), jnp.float32) + zero_qv

    perm = [(i, (i + 1) % n) for i in range(n)]

    if segment_ids is None:

        def step(carry, s):
            o, m, l, k_cur, v_cur = carry
            src = (my - s) % n  # whose block we hold after s rotations
            k_pos = src * l_k + jnp.arange(l_k)
            o, m, l = _block_attn(q_scaled, k_cur, v_cur, o, m, l, q_pos, k_pos, causal)
            k_cur = lax.ppermute(k_cur, axis_name, perm=perm)
            v_cur = lax.ppermute(v_cur, axis_name, perm=perm)
            return (o, m, l, k_cur, v_cur), None

        (o, _, l, _, _), _ = lax.scan(step, (o0, m0, l0, k, v), jnp.arange(n))
        return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)

    q_seg = segment_ids.astype(jnp.int32)

    def seg_step(carry, s):
        o, m, l, k_cur, v_cur, k_seg_cur = carry
        src = (my - s) % n  # whose block we hold after s rotations
        k_pos = src * l_k + jnp.arange(l_k)
        o, m, l = _block_attn(
            q_scaled, k_cur, v_cur, o, m, l, q_pos, k_pos, causal,
            q_seg=q_seg, k_seg=k_seg_cur,
        )
        k_cur = lax.ppermute(k_cur, axis_name, perm=perm)
        v_cur = lax.ppermute(v_cur, axis_name, perm=perm)
        k_seg_cur = lax.ppermute(k_seg_cur, axis_name, perm=perm)
        return (o, m, l, k_cur, v_cur, k_seg_cur), None

    (o, _, l, _, _, _), _ = lax.scan(seg_step, (o0, m0, l0, k, v, q_seg), jnp.arange(n))
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ring_attention_sharded(
    q, k, v, mesh, causal=False, scale=None, axis="sp", segment_ids=None
):
    """Apply ring attention to globally-shaped ``[B, H, L, D]`` arrays, with
    the sequence dim sharded over ``axis`` and batch over the data axes.

    Falls back to plain (single-block) attention when the mesh has no ``axis``
    axis — same math, no ring. ``segment_ids`` (``int32 [B, L]``, 0 =
    padding) fences packed sequences; it is sharded over ``axis`` like the
    sequence dim and rotated with K/V inside the ring.

    A sequence length that does not divide the ring size is padded up to
    the next multiple and the pad rows sliced off after — exact, because
    appended keys carry segment id 0, which never equals a real (>= 1)
    segment (causal-only inputs get an all-ones synthetic segment tensor
    for the same fence; under pure causal masking the appended tail is
    already unreachable). Real text slabs therefore run the ring at any
    ``[B, L]`` geometry; only probe batches whose *batch* dim cannot shard
    fall back.
    """
    from jax.sharding import PartitionSpec as P

    from tensorflowonspark_tpu.parallel.sharding import data_axes

    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    if axis not in sizes or sizes[axis] == 1:
        return plain_attention(q, k, v, causal=causal, scale=scale, segment_ids=segment_ids)

    batch = data_axes(mesh)
    batch_div = 1
    for a in batch:
        batch_div *= sizes[a]
    n_ring = sizes[axis]
    if q.shape[0] % batch_div or (
        k.shape[2] != q.shape[2]
        and (q.shape[2] % n_ring or k.shape[2] % n_ring)
    ):
        # batch dims that don't shard (e.g. module.init on a [1, small]
        # probe batch) — or non-self-attention geometry that doesn't divide
        # — fall back to the single-block path: same math
        return plain_attention(q, k, v, causal=causal, scale=scale, segment_ids=segment_ids)
    pad = (-q.shape[2]) % n_ring
    if pad and k.shape[2] == q.shape[2]:
        l_real = q.shape[2]
        seg = segment_ids
        if seg is None and not causal:
            # non-causal queries would see the appended keys; a synthetic
            # all-ones segment tensor fences them (pad columns get id 0)
            seg = jnp.ones((q.shape[0], l_real), jnp.int32)
        q, k, v = (
            jnp.pad(t, ((0, 0), (0, 0), (0, pad), (0, 0))) for t in (q, k, v)
        )
        if seg is not None:
            seg = jnp.pad(seg, ((0, 0), (0, pad)))
        out = ring_attention_sharded(
            q, k, v, mesh, causal=causal, scale=scale, axis=axis,
            segment_ids=seg,
        )
        return out[:, :, :l_real]
    bspec = batch if len(batch) > 1 else (batch[0] if batch else None)
    spec = P(bspec, None, axis, None)
    from tensorflowonspark_tpu.parallel.collectives import shard_map

    if segment_ids is None:
        fn = shard_map(
            functools.partial(ring_attention, axis_name=axis, causal=causal, scale=scale),
            mesh=mesh,
            in_specs=(spec, spec, spec),
            out_specs=spec,
        )
        return fn(q, k, v)

    seg_spec = P(bspec, axis)

    def _seg_ring(q_l, k_l, v_l, seg_l):
        return ring_attention(
            q_l, k_l, v_l, axis_name=axis, causal=causal, scale=scale,
            segment_ids=seg_l,
        )

    fn = shard_map(
        _seg_ring,
        mesh=mesh,
        in_specs=(spec, spec, spec, seg_spec),
        out_specs=spec,
    )
    return fn(q, k, v, segment_ids.astype(jnp.int32))


def plain_attention(q, k, v, causal=False, scale=None, segment_ids=None):
    """Reference single-device attention (the L_local == L ring case).

    ``segment_ids`` (``int32 [B, L]``, 0 = padding) makes the mask
    block-diagonal over packed sequences — the unpacked-equivalence oracle
    the flash and ring variants are tested against.
    """
    head_dim = q.shape[-1]
    if scale is None:
        scale = 1.0 / math.sqrt(head_dim)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * scale, k.astype(jnp.float32))
    if causal:
        l_q, l_k = q.shape[2], k.shape[2]
        mask = jnp.arange(l_q)[:, None] >= jnp.arange(l_k)[None, :]
        scores = jnp.where(mask[None, None], scores, _NEG_BIG)
    if segment_ids is not None:
        seg = segment_ids.astype(jnp.int32)
        seg_mask = seg[:, None, :, None] == seg[:, None, None, :]
        scores = jnp.where(seg_mask, scores, _NEG_BIG)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(q.dtype)
