"""Device-mesh construction over ICI/DCN.

The mesh is the foundation of every parallelism strategy (SURVEY.md §2.7): the
reference's sync data parallelism (``MultiWorkerMirroredStrategy`` + NCCL ring)
becomes a 1-D ``dp`` mesh; its async PS path has no TPU analogue and is served
by the same sync mesh; TP/PP/SP/EP — absent from the reference — are additional
axes on the same mesh, so adding them is a sharding change, not a rewrite
(SURVEY.md §7 hard part 6).
"""

import logging
import math

logger = logging.getLogger(__name__)

#: canonical axis order; meshes are always built with axes in this order so
#: collectives ride ICI for the innermost (fastest-varying) axes.
AXIS_ORDER = ("dp", "fsdp", "tp", "sp", "ep", "pp")


def _normalize_axes(axes, num_devices):
    """Resolve an axes spec into an ordered {name: size} with product == num_devices.

    ``axes`` may be None (pure dp), a dict (one size may be -1 = "fill"), or a
    sequence of (name, size) pairs. Unknown axis names are allowed (appended
    after the canonical ones, in given order) so user code can define custom
    axes (e.g. a "stage" axis for pipeline parallelism).
    """
    if axes is None:
        axes = {"dp": -1}
    if not isinstance(axes, dict):
        axes = dict(axes)
    known = [a for a in AXIS_ORDER if a in axes]
    extra = [a for a in axes if a not in AXIS_ORDER]
    ordered = known + extra

    fills = [a for a in ordered if axes[a] == -1]
    if len(fills) > 1:
        raise ValueError("at most one axis may have size -1 (got {})".format(fills))
    fixed = math.prod(axes[a] for a in ordered if axes[a] != -1)
    if fills:
        if num_devices % fixed != 0:
            raise ValueError(
                "cannot fill axis {!r}: {} devices not divisible by {}".format(
                    fills[0], num_devices, fixed
                )
            )
        axes = dict(axes)
        axes[fills[0]] = num_devices // fixed
        fixed = num_devices
    if fixed != num_devices:
        raise ValueError(
            "mesh axes {} use {} devices but {} are available".format(
                {a: axes[a] for a in ordered}, fixed, num_devices
            )
        )
    return {a: axes[a] for a in ordered}


def _warn_if_multi_slice(devices):
    """Warn when a flat reshape would span distinct TPU slices.

    Multi-slice worlds (TPU v4+ megascale / multi-pod DCN) expose a
    ``slice_index`` on each device; a plain reshape interleaves slices, so
    mesh-neighbour collectives cross the slow DCN boundary instead of riding
    ICI. Returns the set of distinct slice indices (empty when the attribute
    is absent) so tests can probe the detection with fake device objects.
    """
    slices = {
        getattr(d, "slice_index") for d in devices if getattr(d, "slice_index", None) is not None
    }
    if len(slices) > 1:
        logger.warning(
            "devices span %d distinct slices (slice_index %s) but the mesh is "
            "a flat reshape — inner-axis collectives will cross the DCN "
            "boundary. Build the mesh with "
            "jax.experimental.mesh_utils.create_hybrid_device_mesh (ICI axes "
            "inner, DCN axes outer) instead.",
            len(slices),
            sorted(slices),
        )
    return slices


def build_mesh(axes=None, devices=None, drop_trivial=False):
    """Build a :class:`jax.sharding.Mesh` with named axes over the devices.

    On real TPU hardware the physical layout comes from
    ``mesh_utils.create_device_mesh`` so that neighbouring mesh coordinates are
    ICI neighbours and XLA collectives ride the torus; on CPU/virtual devices a
    plain reshape is used.

    ``axes``: dict of axis name → size; one size may be -1 ("use remaining
    devices"); default ``{"dp": -1}``. ``drop_trivial`` removes size-1 axes.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    shape = _normalize_axes(axes, len(devices))
    if drop_trivial:
        shape = {a: s for a, s in shape.items() if s > 1} or {"dp": 1}

    dims = tuple(shape.values())
    # multi-slice worlds need a hybrid (ICI-inner / DCN-outer) layout that
    # neither create_device_mesh nor a flat reshape provides — surface it
    _warn_if_multi_slice(devices)
    platform = devices[0].platform if devices else "cpu"
    if platform == "tpu":
        try:
            from jax.experimental import mesh_utils

            mesh_devices = mesh_utils.create_device_mesh(dims, devices=devices)
        except Exception as e:  # pragma: no cover - depends on physical topology
            logger.warning("create_device_mesh failed (%s); using device order", e)
            import numpy as np

            mesh_devices = np.asarray(devices).reshape(dims)
    else:
        import numpy as np

        mesh_devices = np.asarray(devices).reshape(dims)
    logger.info("mesh: %s over %d %s device(s)", shape, len(devices), platform)
    return Mesh(mesh_devices, tuple(shape.keys()))


def local_mesh(axes=None):
    """Mesh over this process's addressable devices only (single-host)."""
    import jax

    return build_mesh(axes, devices=jax.local_devices())


def mesh_shape(mesh):
    """{axis: size} for a mesh."""
    return dict(zip(mesh.axis_names, mesh.devices.shape))
