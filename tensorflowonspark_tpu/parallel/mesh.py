"""Device-mesh construction over ICI/DCN.

The mesh is the foundation of every parallelism strategy (SURVEY.md §2.7): the
reference's sync data parallelism (``MultiWorkerMirroredStrategy`` + NCCL ring)
becomes a 1-D ``dp`` mesh; its async PS path has no TPU analogue and is served
by the same sync mesh; TP/PP/SP/EP — absent from the reference — are additional
axes on the same mesh, so adding them is a sharding change, not a rewrite
(SURVEY.md §7 hard part 6).
"""

import logging
import math

logger = logging.getLogger(__name__)

#: canonical axis order; meshes are always built with axes in this order so
#: collectives ride ICI for the innermost (fastest-varying) axes.
AXIS_ORDER = ("dp", "fsdp", "tp", "sp", "ep", "pp")


def _normalize_axes(axes, num_devices):
    """Resolve an axes spec into an ordered {name: size} with product == num_devices.

    ``axes`` may be None (pure dp), a dict (one size may be -1 = "fill"), or a
    sequence of (name, size) pairs. Unknown axis names are allowed (appended
    after the canonical ones, in given order) so user code can define custom
    axes (e.g. a "stage" axis for pipeline parallelism).
    """
    if axes is None:
        axes = {"dp": -1}
    if not isinstance(axes, dict):
        axes = dict(axes)
    known = [a for a in AXIS_ORDER if a in axes]
    extra = [a for a in axes if a not in AXIS_ORDER]
    ordered = known + extra

    fills = [a for a in ordered if axes[a] == -1]
    if len(fills) > 1:
        raise ValueError("at most one axis may have size -1 (got {})".format(fills))
    fixed = math.prod(axes[a] for a in ordered if axes[a] != -1)
    if fills:
        if num_devices % fixed != 0:
            raise ValueError(
                "cannot fill axis {!r}: {} devices not divisible by {}".format(
                    fills[0], num_devices, fixed
                )
            )
        axes = dict(axes)
        axes[fills[0]] = num_devices // fixed
        fixed = num_devices
    if fixed != num_devices:
        raise ValueError(
            "mesh axes {} use {} devices but {} are available".format(
                {a: axes[a] for a in ordered}, fixed, num_devices
            )
        )
    return {a: axes[a] for a in ordered}


def _warn_if_multi_slice(devices):
    """Detect when the device set spans distinct TPU slices.

    Multi-slice worlds (TPU v4+ megascale / multi-pod DCN) expose a
    ``slice_index`` on each device; a plain reshape interleaves slices, so
    mesh-neighbour collectives cross the slow DCN boundary instead of riding
    ICI. Returns the set of distinct slice indices (empty when the attribute
    is absent) so tests can probe the detection with fake device objects.
    :func:`build_mesh` delegates to :func:`build_hybrid_mesh` when more than
    one slice is present, so this only warns if that delegation failed and
    the flat reshape is about to happen anyway.
    """
    slices = {
        getattr(d, "slice_index") for d in devices if getattr(d, "slice_index", None) is not None
    }
    if len(slices) > 1:
        logger.warning(
            "devices span %d distinct slices (slice_index %s) but the mesh is "
            "a flat reshape — inner-axis collectives will cross the DCN "
            "boundary. Use build_hybrid_mesh (ICI axes inner, DCN axes outer) "
            "with axis sizes that factor over the slices instead.",
            len(slices),
            sorted(slices),
        )
    return slices


def _slice_groups(devices):
    """Group devices by ``slice_index``: {slice_index: [devices]} in slice
    order, devices keeping their given order within each slice. Devices with
    no ``slice_index`` attribute all land in one group keyed ``None``."""
    groups = {}
    for d in devices:
        groups.setdefault(getattr(d, "slice_index", None), []).append(d)
    return {k: groups[k] for k in sorted(groups, key=lambda s: (s is None, s))}


def _hybrid_factors(shape, n_slices, dcn_axes):
    """Split each mesh axis into (dcn_factor, ici_factor) with
    ``prod(dcn_factors) == n_slices``.

    ``dcn_axes`` may be a dict {axis: dcn_factor} (explicit split) or a
    sequence of axis names eligible to absorb the DCN dimension — the whole
    ``n_slices`` factor goes to the first eligible axis whose size it
    divides (``dp`` by default), so a flat ``{"dp": 8}`` over 2 slices
    becomes dp = 2 (DCN, outer) x 4 (ICI, inner).
    """
    if isinstance(dcn_axes, dict):
        factors = {a: int(dcn_axes.get(a, 1)) for a in shape}
        bad = [a for a in factors if shape[a] % factors[a] != 0]
        if bad:
            raise ValueError(
                "dcn factor does not divide axis size for {}".format(
                    {a: (factors[a], shape[a]) for a in bad}
                )
            )
        if math.prod(factors.values()) != n_slices:
            raise ValueError(
                "dcn factors {} must multiply to the slice count {}".format(
                    factors, n_slices
                )
            )
        return factors
    factors = {a: 1 for a in shape}
    for a in dcn_axes:
        if a in shape and shape[a] % n_slices == 0:
            factors[a] = n_slices
            return factors
    raise ValueError(
        "no axis in {} (sizes {}) can absorb the DCN dimension of {} slices".format(
            tuple(dcn_axes), dict(shape), n_slices
        )
    )


def _hybrid_device_grid(shape, dcn_factors, groups):
    """Device ndarray for a hybrid mesh: slice-major within every axis.

    Each axis of size ``s`` splits as ``d x i`` (``d`` = its DCN factor):
    the grid is built as ``[d0, d1, ..., i0, i1, ...]`` — per-slice blocks
    reshaped to the ICI dims, stacked over the DCN dims — then the paired
    dims are interleaved and merged, so walking any mesh axis visits all
    within-slice (ICI) neighbours before crossing a slice (DCN) boundary.
    Pure numpy over opaque device objects, so tests can drive it with fakes.
    """
    import numpy as np

    ordered = list(shape)
    dcn_dims = tuple(dcn_factors[a] for a in ordered)
    ici_dims = tuple(shape[a] // dcn_factors[a] for a in ordered)
    per_slice = math.prod(ici_dims)
    blocks = []
    for idx, devs in groups.items():
        if len(devs) != per_slice:
            raise ValueError(
                "slice {} has {} devices; hybrid mesh needs {} per slice".format(
                    idx, len(devs), per_slice
                )
            )
        block = np.empty(per_slice, dtype=object)
        block[:] = devs
        blocks.append(block.reshape(ici_dims))
    grid = np.stack(blocks).reshape(dcn_dims + ici_dims)
    n = len(ordered)
    perm = [k for pair in ((i, n + i) for i in range(n)) for k in pair]
    return grid.transpose(perm).reshape(tuple(shape.values()))


def build_hybrid_mesh(axes=None, devices=None, dcn_axes=("dp",), drop_trivial=False):
    """Build a slice-topology-aware mesh: DCN axes outer, ICI axes inner.

    The real placement behind the old multi-slice warning: on worlds whose
    devices carry distinct ``slice_index`` values (TPU multi-slice / multi-pod
    DCN), collectives along an axis that spans slices pay the slow DCN hop, so
    the data-parallel axis should cross slices while fsdp/tp/sp stay inside
    one slice on ICI. ``dcn_axes`` names the axes allowed to absorb the
    cross-slice dimension (first fit wins; pass a ``{axis: factor}`` dict to
    split explicitly). ``axes=None`` defaults to ``{"dp": n_slices,
    "fsdp": -1}`` — dp across slices, params fully sharded within each slice.

    Single-slice (or slice-unaware) device sets delegate straight to
    :func:`build_mesh`. On TPU the placement goes through
    ``mesh_utils.create_hybrid_device_mesh``; elsewhere (and as the TPU
    fallback) the grid is assembled slice-major by :func:`_hybrid_device_grid`.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    groups = _slice_groups(devices)
    if len(groups) <= 1:
        return build_mesh(axes, devices, drop_trivial)
    n_slices = len(groups)
    if axes is None:
        axes = {"dp": n_slices, "fsdp": -1}
    shape = _normalize_axes(axes, len(devices))
    factors = _hybrid_factors(shape, n_slices, dcn_axes)
    if drop_trivial:
        kept = {a: s for a, s in shape.items() if s > 1} or {"dp": 1}
        if any(factors[a] > 1 for a in shape if a not in kept):
            raise ValueError("cannot drop a trivial axis carrying a DCN factor")
        shape = kept
        factors = {a: factors[a] for a in shape}

    platform = getattr(devices[0], "platform", "cpu") if len(devices) else "cpu"
    mesh_devices = None
    if platform == "tpu":
        try:
            from jax.experimental import mesh_utils

            mesh_devices = mesh_utils.create_hybrid_device_mesh(
                tuple(shape[a] // factors[a] for a in shape),
                tuple(factors[a] for a in shape),
                devices=devices,
            )
        except Exception as e:  # pragma: no cover - depends on physical topology
            logger.warning(
                "create_hybrid_device_mesh failed (%s); using slice-major order", e
            )
    if mesh_devices is None:
        mesh_devices = _hybrid_device_grid(shape, factors, groups)
    logger.info(
        "hybrid mesh: %s over %d slice(s), dcn factors %s", shape, n_slices, factors
    )
    return Mesh(mesh_devices, tuple(shape.keys()))


def build_mesh(axes=None, devices=None, drop_trivial=False):
    """Build a :class:`jax.sharding.Mesh` with named axes over the devices.

    On real TPU hardware the physical layout comes from
    ``mesh_utils.create_device_mesh`` so that neighbouring mesh coordinates are
    ICI neighbours and XLA collectives ride the torus; on CPU/virtual devices a
    plain reshape is used.

    ``axes``: dict of axis name → size; one size may be -1 ("use remaining
    devices"); default ``{"dp": -1}``. ``drop_trivial`` removes size-1 axes.
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    # multi-slice worlds need a hybrid (ICI-inner / DCN-outer) layout that
    # neither create_device_mesh nor a flat reshape provides — delegate;
    # only if no axis can absorb the slice dimension fall through to the
    # flat reshape (with the old warning)
    if len(_slice_groups(devices)) > 1:
        try:
            return build_hybrid_mesh(axes, devices, drop_trivial=drop_trivial)
        except ValueError as e:
            logger.warning("hybrid mesh placement failed (%s); flat reshape", e)
            _warn_if_multi_slice(devices)
    shape = _normalize_axes(axes, len(devices))
    if drop_trivial:
        shape = {a: s for a, s in shape.items() if s > 1} or {"dp": 1}

    dims = tuple(shape.values())
    platform = devices[0].platform if devices else "cpu"
    if platform == "tpu":
        try:
            from jax.experimental import mesh_utils

            mesh_devices = mesh_utils.create_device_mesh(dims, devices=devices)
        except Exception as e:  # pragma: no cover - depends on physical topology
            logger.warning("create_device_mesh failed (%s); using device order", e)
            import numpy as np

            mesh_devices = np.asarray(devices).reshape(dims)
    else:
        import numpy as np

        mesh_devices = np.asarray(devices).reshape(dims)
    logger.info("mesh: %s over %d %s device(s)", shape, len(devices), platform)
    return Mesh(mesh_devices, tuple(shape.keys()))


def local_mesh(axes=None):
    """Mesh over this process's addressable devices only (single-host)."""
    import jax

    return build_mesh(axes, devices=jax.local_devices())


def mesh_shape(mesh):
    """{axis: size} for a mesh."""
    return dict(zip(mesh.axis_names, mesh.devices.shape))
