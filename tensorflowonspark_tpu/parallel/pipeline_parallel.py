"""Pipeline parallelism: GPipe and 1F1B schedules over pipeline stages.

Absent from the reference (SURVEY.md §2.7: model parallelism was
"claimed-but-user-managed" TF1 device scopes; no pipeline support) — this is
a beyond-parity capability, built the TPU way in two tiers:

* :func:`pipeline_apply` — GPipe-style forward pipeline over the ``pp`` mesh
  axis: microbatch activations rotate between neighbours with
  ``lax.ppermute`` (ICI neighbour links) and the whole schedule is a
  ``lax.scan`` inside ``shard_map`` — one compiled program, no host round
  trips, fully differentiable (gradients flow back through the permutes in
  reverse schedule order, which is exactly GPipe's backward). Schedule is
  the classic bubble pipeline: with P stages and M microbatches, step t has
  stage i working on microbatch t-i; M + P - 1 steps, bubble fraction
  (P-1)/(M+P-1).

* :class:`Pipeline1F1B` — a host-driven one-forward-one-backward schedule
  (Narayanan et al. 2019/Megatron's interleaved baseline): each stage owns
  a device and a worker thread, stage-boundary activation/cotangent hops
  run through the same dedicated comm-thread pattern as
  :class:`~tensorflowonspark_tpu.train.strategy.BucketedOverlap`, and the
  bubble is *measured* from per-op compute spans rather than assumed from
  the closed form (the ``pipeline_bubble_fraction`` gauge, with the same
  spans published as retroactive trace tracks for corroboration in the
  merged Perfetto timeline).
"""

import logging
import queue as queue_mod
import threading
import time

import jax
import jax.numpy as jnp
from jax import lax

from tensorflowonspark_tpu.parallel.mesh import mesh_shape

logger = logging.getLogger(__name__)


def stack_stage_params(params_list):
    """[per-stage pytrees] → one pytree with a leading stage dim (shard it
    with ``PartitionSpec('pp', ...)``)."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *params_list)


def pipeline_apply(stage_fn, stacked_params, microbatches, mesh, axis="pp", batch_axis=None):
    """Run ``stage_fn`` as a P-stage pipeline over the mesh's ``axis``.

    ``stage_fn(stage_params, x) -> y`` is ONE stage's computation; every
    stage must map the same activation shape to itself (classic homogeneous
    pipeline). ``stacked_params`` has a leading stage dim of size P
    (:func:`stack_stage_params`); ``microbatches`` is ``[M, ...]`` (split a
    global batch with :func:`split_microbatches`). Returns ``[M, ...]``
    outputs, replicated over ``axis``.

    ``batch_axis`` composes the pipeline with data parallelism on one mesh:
    the within-microbatch dim (dim 1) is sharded over that axis, so a
    ``{"pp": P, "dp": D}`` mesh runs D activation shards through P stages
    concurrently — each dp column owns its slice end to end, the ppermute
    stage hops stay within the column, and params are replicated over dp.
    """
    from jax.sharding import PartitionSpec as P

    from tensorflowonspark_tpu.parallel.collectives import shard_map

    n_stages = mesh_shape(mesh)[axis]
    del n_stages  # validated implicitly by the leading-dim split below

    def _worker(params, mb):
        params = jax.tree.map(lambda a: jnp.squeeze(a, 0), params)  # my stage
        from tensorflowonspark_tpu.parallel.collectives import axis_size

        n_pp = axis_size(axis)
        idx = lax.axis_index(axis)
        n_micro = mb.shape[0]

        def body(carry, t):
            buf, out = carry
            # stage 0 injects microbatch t (clipped; masked by validity of
            # the output slot below), later stages eat their neighbour's buf
            x_in = jnp.where(idx == 0, mb[jnp.clip(t, 0, n_micro - 1)], buf)
            y = stage_fn(params, x_in)
            # the LAST stage finishes microbatch t-(P-1) at step t
            slot = t - (n_pp - 1)
            clipped = jnp.clip(slot, 0, n_micro - 1)
            out = out.at[clipped].set(
                jnp.where((idx == n_pp - 1) & (slot >= 0), y, out[clipped])
            )
            # rotate activations to the next stage (ICI neighbour hop)
            perm = [(i, (i + 1) % n_pp) for i in range(n_pp)]
            buf = lax.ppermute(y, axis, perm=perm)
            return (buf, out), None

        init = (jnp.zeros_like(mb[0]), jnp.zeros_like(mb))
        (_, out), _ = lax.scan(body, init, jnp.arange(mb.shape[0] + n_pp - 1))
        # only the last stage holds real outputs; broadcast so the result is
        # replicated over the pp axis (cheap at microbatch scale)
        return lax.psum(jnp.where(idx == n_pp - 1, out, jnp.zeros_like(out)), axis)

    data_spec = P(None, batch_axis) if batch_axis else P()
    return shard_map(
        _worker,
        mesh=mesh,
        in_specs=(P(axis), data_spec),
        out_specs=data_spec,
        check_vma=False,
    )(stacked_params, microbatches)


def split_microbatches(x, n_micro):
    """[B, ...] → [n_micro, B//n_micro, ...] (static shapes for the scan)."""
    if x.shape[0] % n_micro:
        raise ValueError(
            "batch {} not divisible into {} microbatches".format(x.shape[0], n_micro)
        )
    return x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])


def merge_microbatches(y):
    """Inverse of :func:`split_microbatches`."""
    return y.reshape((-1,) + y.shape[2:])


def schedule_1f1b(stage, n_stages, n_micro):
    """The 1F1B op order for one stage: ``[("F", m) | ("B", m), ...]``.

    ``n_stages - 1 - stage`` warmup forwards, then alternating F/B in
    steady state, then cooldown backwards — each stage holds at most
    ``n_stages - stage`` activation stashes in flight, which is the whole
    point of 1F1B over GPipe's all-forwards-then-all-backwards. The last
    stage's pairs are fused by :class:`Pipeline1F1B` into single loss+vjp
    ops, but the order here is the canonical schedule for every stage.
    """
    warmup = min(n_stages - 1 - stage, n_micro)
    ops = [("F", m) for m in range(warmup)]
    f = warmup
    b = 0
    while f < n_micro:
        ops.append(("F", f))
        f += 1
        ops.append(("B", b))
        b += 1
    while b < n_micro:
        ops.append(("B", b))
        b += 1
    return ops


class Pipeline1F1B:
    """Host-driven 1F1B microbatch pipeline with measured bubble accounting.

    ``stage_fn(stage_params, x) -> y`` is one stage's computation (same
    homogeneous contract as :func:`pipeline_apply`); ``params_list`` holds P
    per-stage param pytrees, each pinned to its own device; ``loss_fn(y,
    target) -> scalar`` closes the last stage. One optimizer step::

        sched = Pipeline1F1B(stage_fn, params_list, loss_fn)
        loss, grads = sched.step(split_microbatches(x, M),
                                 split_microbatches(t, M))
        # grads[i] lives on stage i's device, scaled to d(mean loss)/dparams

    Execution: one worker thread per stage runs :func:`schedule_1f1b`;
    backward ops re-derive the stage forward through ``jax.vjp`` (activation
    rematerialization — only the stage *inputs* are stashed, at most
    ``P - stage`` of them, which is 1F1B's memory contract). Stage-boundary
    activation/cotangent hops go through a dedicated comm thread — the
    :class:`~tensorflowonspark_tpu.train.strategy.BucketedOverlap` pattern:
    the comm thread waits on the producing device stream *beside* the next
    op's compute, then lands the buffer on the neighbour device.
    ``overlap=False`` runs the identical transfers inline on the stage
    threads (same buffers, same order, host-side fencing only), which is
    the measured-off leg the bench compares against.

    Measurement: every op's dispatch-to-ready interval is recorded per
    stage. ``pipeline_bubble_fraction`` = 1 - busy/(P × window) over the
    step's wall window — the *measured* counterpart of GPipe's closed-form
    (P-1)/(M+P-1), visible per step in :attr:`last_stats` and the gauge.
    Transfer seconds that land inside some stage's compute span count as
    hidden; the ``pipeline_comm_overlap_fraction`` gauge reports the
    fraction. With tracing active both land as retroactive spans
    (``pipeline_stage`` / ``pipeline_transfer`` tracks) so the merged
    Perfetto timeline corroborates the gauges.

    Donation contract: no program donates anything — params feed every
    microbatch, stashed inputs feed the backward, and grads accumulate
    functionally on each stage's device.
    """

    def __init__(self, stage_fn, params_list, loss_fn, devices=None, overlap=True):
        if not params_list:
            raise ValueError("need at least one pipeline stage")
        self.stage_fn = stage_fn
        self.loss_fn = loss_fn
        self.overlap = overlap
        self.n_stages = len(params_list)
        if devices is None:
            devices = jax.local_devices()
        if len(devices) < self.n_stages:
            raise ValueError(
                "{} pipeline stages need {} devices; have {}".format(
                    self.n_stages, self.n_stages, len(devices)
                )
            )
        self.devices = list(devices[: self.n_stages])
        self.params = [
            jax.device_put(p, d) for p, d in zip(params_list, self.devices)
        ]
        self.last_stats = {}
        self._fwd = [None] * self.n_stages
        self._bwd = [None] * self.n_stages
        self._last_prog = None
        # bounded: a wedged comm thread should exert backpressure on the
        # stage workers instead of accumulating device buffers in the queue
        self._jobs = queue_mod.Queue(maxsize=max(8, 4 * self.n_stages))
        self._comm_worker = None
        self._comm_err = None

    # -- compiled programs -------------------------------------------------

    def _fwd_prog(self, i):
        if self._fwd[i] is None:
            self._fwd[i] = jax.jit(self.stage_fn, donate_argnums=())
        return self._fwd[i]

    def _bwd_prog(self, i):
        if self._bwd[i] is None:

            def bwd(params, x, g):
                _y, vjp = jax.vjp(self.stage_fn, params, x)
                return vjp(g)  # (dparams, dx)

            self._bwd[i] = jax.jit(bwd, donate_argnums=())
        return self._bwd[i]

    def _last(self):
        """Fused loss+vjp program for the final stage (its F/B pair)."""
        if self._last_prog is None:

            def last(params, x, target):
                def f(p, xx):
                    return self.loss_fn(self.stage_fn(p, xx), target)

                loss, (dp, dx) = jax.value_and_grad(f, argnums=(0, 1))(params, x)
                return loss, dp, dx

            self._last_prog = jax.jit(last, donate_argnums=())
        return self._last_prog

    # -- comm thread (BucketedOverlap pattern) -----------------------------

    def _transfer(self, payload, dest, out_q, tag, spans):
        t0 = time.perf_counter()
        jax.block_until_ready(payload)  # producing device stream, not comm
        t1 = time.perf_counter()
        moved = jax.device_put(payload, dest)
        jax.block_until_ready(moved)
        t2 = time.perf_counter()
        spans.append((t1, t2, tag))
        out_q.put((tag[1], moved))

    def _comm_loop(self):
        while True:
            job = self._jobs.get()
            if job is None:
                return
            try:
                self._transfer(*job)
            except BaseException as e:  # surfaces at the step join
                self._comm_err = e
                job[2].put((job[3][1], e))

    def _ensure_comm_worker(self):
        if self._comm_worker is None or not self._comm_worker.is_alive():
            self._comm_worker = threading.Thread(
                target=self._comm_loop, name="pipeline-comm", daemon=True
            )
            self._comm_worker.start()

    # -- the step ----------------------------------------------------------

    def step(self, microbatches, targets):
        """One step over ``microbatches`` (``[M, b, ...]`` from
        :func:`split_microbatches`) and matching per-microbatch ``targets``.
        Returns ``(loss, grads)``: the microbatch-mean loss and per-stage
        grad pytrees scaled to match ``grad(mean loss)``."""
        P = self.n_stages
        M = int(microbatches.shape[0])
        if M < 1:
            raise ValueError("step needs at least one microbatch")
        if self.overlap:
            self._ensure_comm_worker()
        # ingest: land inputs on the edge devices before the measured window
        mbs = [jax.device_put(microbatches[m], self.devices[0]) for m in range(M)]
        tgts = [jax.device_put(targets[m], self.devices[-1]) for m in range(M)]
        jax.block_until_ready((mbs, tgts))

        acts = [queue_mod.Queue() for _ in range(P)]
        grads_q = [queue_mod.Queue() for _ in range(P)]
        compute_spans = [[] for _ in range(P)]  # (t0, t1, op, m) per stage
        comm_spans = []  # (t0, t1, (kind, m, src)) — comm thread + inline
        losses = [None] * M
        grad_acc = [None] * P
        errs = [None] * P

        def _send(payload, dest_stage, out_q, tag):
            if self.overlap:
                self._jobs.put(
                    (payload, self.devices[dest_stage], out_q, tag, comm_spans)
                )
            else:
                self._transfer(
                    payload, self.devices[dest_stage], out_q, tag, comm_spans
                )

        def _recv(q, m):
            got_m, payload = q.get()
            if isinstance(payload, BaseException):
                raise RuntimeError("pipeline transfer failed") from payload
            if got_m != m:
                raise RuntimeError(
                    "pipeline schedule out of order: wanted microbatch "
                    "{}, got {}".format(m, got_m)
                )
            return payload

        def _run_stage(i):
            try:
                stash = {}
                for op, m in schedule_1f1b(i, P, M):
                    if i == P - 1:
                        if op == "B":
                            continue  # fused into the F slot's loss+vjp op
                        x = mbs[m] if P == 1 else _recv(acts[i], m)
                        t0 = time.perf_counter()
                        loss, dp, dx = self._last()(self.params[i], x, tgts[m])
                        grad_acc[i] = (
                            dp
                            if grad_acc[i] is None
                            else jax.tree.map(jnp.add, grad_acc[i], dp)
                        )
                        jax.block_until_ready((loss, grad_acc[i], dx))
                        t1 = time.perf_counter()
                        compute_spans[i].append((t0, t1, "fb", m))
                        losses[m] = loss
                        if P > 1:
                            _send(dx, i - 1, grads_q[i - 1], ("grad", m, i))
                    elif op == "F":
                        x = mbs[m] if i == 0 else _recv(acts[i], m)
                        stash[m] = x
                        t0 = time.perf_counter()
                        y = self._fwd_prog(i)(self.params[i], x)
                        jax.block_until_ready(y)
                        t1 = time.perf_counter()
                        compute_spans[i].append((t0, t1, "fwd", m))
                        _send(y, i + 1, acts[i + 1], ("act", m, i))
                    else:  # backward: vjp against the stashed input
                        g = _recv(grads_q[i], m)
                        x = stash.pop(m)
                        t0 = time.perf_counter()
                        dp, dx = self._bwd_prog(i)(self.params[i], x, g)
                        grad_acc[i] = (
                            dp
                            if grad_acc[i] is None
                            else jax.tree.map(jnp.add, grad_acc[i], dp)
                        )
                        jax.block_until_ready(grad_acc[i] if i == 0 else (grad_acc[i], dx))
                        t1 = time.perf_counter()
                        compute_spans[i].append((t0, t1, "bwd", m))
                        if i > 0:
                            _send(dx, i - 1, grads_q[i - 1], ("grad", m, i))
            except BaseException as e:
                errs[i] = e
                # unblock neighbours waiting on this stage's sends
                if i + 1 < P:
                    acts[i + 1].put((-1, e))
                if i > 0:
                    grads_q[i - 1].put((-1, e))

        workers = [
            threading.Thread(
                target=_run_stage, args=(i,), name="pipeline-stage-{}".format(i),
                daemon=True,  # a wedged XLA call must not pin interpreter exit
            )
            for i in range(P)
        ]
        for w in workers:
            w.start()
        for w in workers:
            # the error path unblocks neighbours, so every stage terminates;
            # bounded join slices keep a wedged device call diagnosable
            while w.is_alive():
                w.join(timeout=60.0)
        for i, e in enumerate(errs):
            if e is not None:
                raise RuntimeError("pipeline stage {} failed".format(i)) from e
        if self._comm_err is not None:
            err, self._comm_err = self._comm_err, None
            raise RuntimeError("pipeline comm thread failed") from err

        scale = jnp.float32(1.0 / M)
        grads = [
            jax.tree.map(lambda g: g * scale, acc) for acc in grad_acc
        ]
        loss = jnp.mean(jnp.stack([jax.device_put(l, self.devices[-1]) for l in losses]))
        self._publish(compute_spans, comm_spans, M)
        return loss, grads

    # -- measurement -------------------------------------------------------

    def _publish(self, compute_spans, comm_spans, n_micro):
        """Span accounting → last_stats + gauges + retroactive trace spans."""
        from tensorflowonspark_tpu import obs
        from tensorflowonspark_tpu.obs import tracing as obs_tracing

        P = self.n_stages
        all_spans = [s for spans in compute_spans for s in spans]
        t_first = min(s[0] for s in all_spans)
        t_last = max(s[1] for s in all_spans)
        window = max(t_last - t_first, 1e-9)
        busy = sum(t1 - t0 for t0, t1, _op, _m in all_spans)
        bubble = max(0.0, 1.0 - busy / (P * window))

        # merge compute spans into a busy-interval union; transfer seconds
        # inside it ran beside some stage's compute — hidden comm
        union = []
        for t0, t1, _op, _m in sorted(all_spans):
            if union and t0 <= union[-1][1]:
                union[-1] = (union[-1][0], max(union[-1][1], t1))
            else:
                union.append((t0, t1))
        comm_busy = sum(t1 - t0 for t0, t1, _tag in comm_spans)
        hidden = 0.0
        for t0, t1, _tag in comm_spans:
            for u0, u1 in union:
                hidden += max(0.0, min(t1, u1) - max(t0, u0))
        overlap_fraction = min(1.0, hidden / comm_busy) if comm_busy > 0 else 0.0

        self.last_stats = {
            "n_stages": P,
            "n_microbatches": n_micro,
            "window_s": window,
            "busy_s": busy,
            "bubble_fraction": bubble,
            "bubble_fraction_theory": (P - 1.0) / (2.0 * n_micro + P - 1.0),
            "comm_busy_s": comm_busy,
            "hidden_comm_s": hidden,
            "overlap_fraction": overlap_fraction,
        }
        obs.gauge(
            "pipeline_bubble_fraction",
            help="measured idle fraction of the 1F1B pipeline window "
            "(1 - stage busy seconds / (stages x window))",
        ).set(bubble)
        obs.gauge(
            "pipeline_comm_overlap_fraction",
            help="fraction of stage-boundary transfer time hidden behind "
            "pipeline stage compute",
        ).set(overlap_fraction)
        if obs_tracing.active():
            # publish the measured intervals as retroactive spans (one track
            # per plane, like BucketedOverlap's comm tracks) so tracemerge's
            # timeline corroborates the bubble/overlap gauges
            anchor = time.time() - time.perf_counter()
            for i, spans in enumerate(compute_spans):
                for t0, t1, op, m in spans:
                    obs_tracing.record_span(
                        "pipeline_stage", ts=anchor + t0, dur_s=t1 - t0,
                        track="pipeline", stage=i, op=op, microbatch=m,
                    )
            for t0, t1, (kind, m, src) in comm_spans:
                obs_tracing.record_span(
                    "pipeline_transfer", ts=anchor + t0, dur_s=t1 - t0,
                    track="pipeline_comm", kind=kind, microbatch=m, stage=src,
                )

    def close(self):
        """Stop the comm thread (idempotent)."""
        if self._comm_worker is not None and self._comm_worker.is_alive():
            self._jobs.put(None)
            self._comm_worker.join(timeout=10)
        self._comm_worker = None
