"""Pipeline parallelism: GPipe-style stage execution over the ``pp`` axis.

Absent from the reference (SURVEY.md §2.7: model parallelism was
"claimed-but-user-managed" TF1 device scopes; no pipeline support) — this is
a beyond-parity capability, built the TPU way: stages live on mesh members
along ``pp``, microbatch activations rotate between neighbours with
``lax.ppermute`` (ICI neighbour links), and the whole schedule is a
``lax.scan`` inside ``shard_map`` — one compiled program, no host round
trips, fully differentiable (gradients flow back through the permutes in
reverse schedule order, which is exactly GPipe's backward).

The schedule is the classic bubble pipeline: with P stages and M
microbatches, step t has stage i working on microbatch t-i; total
M + P - 1 steps, bubble fraction (P-1)/(M+P-1).
"""

import jax
import jax.numpy as jnp
from jax import lax

from tensorflowonspark_tpu.parallel.mesh import mesh_shape


def stack_stage_params(params_list):
    """[per-stage pytrees] → one pytree with a leading stage dim (shard it
    with ``PartitionSpec('pp', ...)``)."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *params_list)


def pipeline_apply(stage_fn, stacked_params, microbatches, mesh, axis="pp", batch_axis=None):
    """Run ``stage_fn`` as a P-stage pipeline over the mesh's ``axis``.

    ``stage_fn(stage_params, x) -> y`` is ONE stage's computation; every
    stage must map the same activation shape to itself (classic homogeneous
    pipeline). ``stacked_params`` has a leading stage dim of size P
    (:func:`stack_stage_params`); ``microbatches`` is ``[M, ...]`` (split a
    global batch with :func:`split_microbatches`). Returns ``[M, ...]``
    outputs, replicated over ``axis``.

    ``batch_axis`` composes the pipeline with data parallelism on one mesh:
    the within-microbatch dim (dim 1) is sharded over that axis, so a
    ``{"pp": P, "dp": D}`` mesh runs D activation shards through P stages
    concurrently — each dp column owns its slice end to end, the ppermute
    stage hops stay within the column, and params are replicated over dp.
    """
    from jax.sharding import PartitionSpec as P

    from tensorflowonspark_tpu.parallel.collectives import shard_map

    n_stages = mesh_shape(mesh)[axis]
    del n_stages  # validated implicitly by the leading-dim split below

    def _worker(params, mb):
        params = jax.tree.map(lambda a: jnp.squeeze(a, 0), params)  # my stage
        from tensorflowonspark_tpu.parallel.collectives import axis_size

        n_pp = axis_size(axis)
        idx = lax.axis_index(axis)
        n_micro = mb.shape[0]

        def body(carry, t):
            buf, out = carry
            # stage 0 injects microbatch t (clipped; masked by validity of
            # the output slot below), later stages eat their neighbour's buf
            x_in = jnp.where(idx == 0, mb[jnp.clip(t, 0, n_micro - 1)], buf)
            y = stage_fn(params, x_in)
            # the LAST stage finishes microbatch t-(P-1) at step t
            slot = t - (n_pp - 1)
            clipped = jnp.clip(slot, 0, n_micro - 1)
            out = out.at[clipped].set(
                jnp.where((idx == n_pp - 1) & (slot >= 0), y, out[clipped])
            )
            # rotate activations to the next stage (ICI neighbour hop)
            perm = [(i, (i + 1) % n_pp) for i in range(n_pp)]
            buf = lax.ppermute(y, axis, perm=perm)
            return (buf, out), None

        init = (jnp.zeros_like(mb[0]), jnp.zeros_like(mb))
        (_, out), _ = lax.scan(body, init, jnp.arange(mb.shape[0] + n_pp - 1))
        # only the last stage holds real outputs; broadcast so the result is
        # replicated over the pp axis (cheap at microbatch scale)
        return lax.psum(jnp.where(idx == n_pp - 1, out, jnp.zeros_like(out)), axis)

    data_spec = P(None, batch_axis) if batch_axis else P()
    return shard_map(
        _worker,
        mesh=mesh,
        in_specs=(P(axis), data_spec),
        out_specs=data_spec,
        check_vma=False,
    )(stacked_params, microbatches)


def split_microbatches(x, n_micro):
    """[B, ...] → [n_micro, B//n_micro, ...] (static shapes for the scan)."""
    if x.shape[0] % n_micro:
        raise ValueError(
            "batch {} not divisible into {} microbatches".format(x.shape[0], n_micro)
        )
    return x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])


def merge_microbatches(y):
    """Inverse of :func:`split_microbatches`."""
    return y.reshape((-1,) + y.shape[2:])
