"""Host-side bucketed all-reduce group — the comm-thread gradient-sync path.

Why host-side and not XLA collectives: an XLA-emitted collective executes
inside the device program stream, and the CPU PJRT client runs enqueued
programs strictly in order — a collective waiting on a straggler peer blocks
every later program, so collective/compute overlap at *program* granularity
is impossible device-side (measured on this runtime: a 0.5 s peer skew adds
the full 0.5 s to the fenced and unfenced schedules alike; docs/perf.md
"Multi-host scaling"). A gather-sum-broadcast over host TCP sockets, driven
from a dedicated comm thread, waits in ``epoll`` instead: the device stream
keeps executing the next microbatch's backprop while the socket wait and
bucket sum happen beside it (jit execution releases the GIL). This is the
reference's Horovod-lineage design — NCCL on a side stream next to the TF
compute stream — rebuilt at the host layer this repo owns.

Determinism contract: rank 0 receives every peer's buffer, sums **in rank
order**, divides by the world size, and broadcasts the result — so every
rank applies bitwise-identical reduced gradients, and two runs with the same
inputs reduce to the same bits regardless of socket arrival order.

Bootstrap: pass ``root_address`` explicitly ("host:port" that rank 0 binds),
or leave it ``None`` in an initialized ``jax.distributed`` world and rank 0
publishes an ephemeral port through the coordination-service key-value
store. ``world == 1`` degenerates to a local mean (no sockets at all).
"""

import logging
import socket
import struct
import threading
import time

from tensorflowonspark_tpu import chaos, obs, resilience

logger = logging.getLogger(__name__)

_LEN = struct.Struct("<q")

#: coordination-service key under which rank 0 publishes its listener
KV_KEY = "tos_hostreduce_root"


def _send_msg(sock, payload):
    sock.sendall(_LEN.pack(len(payload)))
    sock.sendall(payload)


def _recv_exact(sock, n):
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        r = sock.recv_into(view[got:], n - got)
        if r == 0:
            raise ConnectionResetError("hostreduce peer closed mid-message")
        got += r
    return bytes(buf)


def _recv_msg(sock):
    (n,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    return _recv_exact(sock, n)


def _kv_client():
    """The jax.distributed coordination-service client, or None."""
    try:
        from jax._src.distributed import global_state

        return global_state.client
    except Exception:  # pragma: no cover - jax internals moved
        return None


class HostAllReduceGroup:
    """A fixed group of ranks doing deterministic mean all-reduces over TCP.

    Every rank must call :meth:`allreduce_mean` the same number of times in
    the same order (the per-connection byte streams are the sequencing) —
    exactly the discipline gradient buckets already have. Calls are
    serialized by an internal lock, so a single comm thread (or careful
    callers) can share the group.
    """

    def __init__(self, rank, world, root_address=None, timeout=120.0):
        self.rank = int(rank)
        self.world = int(world)
        self.timeout = timeout
        self._lock = threading.Lock()
        self._peers = {}  # rank -> socket (rank 0 only)
        self._root = None  # socket to rank 0 (peers only)
        self._listener = None
        if self.world > 1:
            self._connect(root_address)

    # -- wiring ---------------------------------------------------------------

    def _connect(self, root_address):
        if self.rank == 0:
            host, port = self._parse(root_address) if root_address else ("", 0)
            self._listener = socket.create_server((host, port))
            self._listener.settimeout(self.timeout)
            if not root_address:
                addr = "127.0.0.1:{}".format(self._listener.getsockname()[1])
                kv = _kv_client()
                if kv is None:
                    raise RuntimeError(
                        "hostreduce needs root_address when jax.distributed "
                        "is not initialized"
                    )
                kv.key_value_set(KV_KEY, addr)
            deadline = time.monotonic() + self.timeout
            while len(self._peers) < self.world - 1:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        "hostreduce rank 0: only {}/{} peers joined".format(
                            len(self._peers), self.world - 1
                        )
                    )
                conn, _ = self._listener.accept()
                conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                (peer_rank,) = _LEN.unpack(_recv_exact(conn, _LEN.size))
                self._peers[int(peer_rank)] = conn
        else:
            if root_address is None:
                kv = _kv_client()
                if kv is None:
                    raise RuntimeError(
                        "hostreduce needs root_address when jax.distributed "
                        "is not initialized"
                    )
                root_address = kv.blocking_key_value_get(
                    KV_KEY, int(self.timeout * 1000)
                )
            host, port = self._parse(root_address)
            backoff = resilience.Backoff(base=0.05, factor=1.5, max_delay=0.5)
            last_err = None
            for _ in backoff.attempts(resilience.Deadline(self.timeout)):
                try:
                    self._root = socket.create_connection(
                        (host, port), timeout=self.timeout
                    )
                    break
                except OSError as exc:
                    last_err = exc
            else:
                raise TimeoutError(
                    "hostreduce rank {}: root {} unreachable after {}s".format(
                        self.rank, root_address, self.timeout
                    )
                ) from last_err
            self._root.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._root.sendall(_LEN.pack(self.rank))

    @staticmethod
    def _parse(address):
        host, _, port = address.rpartition(":")
        return host or "127.0.0.1", int(port)

    # -- the collective -------------------------------------------------------

    def allreduce_mean(self, buf):
        """Mean of ``buf`` (a 1-D float numpy array) across the group.

        Returns a new array carrying bitwise-identical contents on every
        rank. Timing lands in ``comm_allreduce_seconds_total`` and the
        payload size in the ``comm_bucket_bytes`` gauge, so the comm plane
        shows up in ``TFCluster.metrics()``.
        """
        import numpy as np

        # chaos: one straggler rank's collectives run late — gate on the
        # victim BEFORE rolling the site so healthy ranks consume no budget
        if chaos.active:
            p = chaos.plan()
            spec = p.sites.get("comm.link_delay") if p else None
            if spec is not None and spec.get("victim", self.rank) == self.rank:
                chaos.delay("comm.link_delay")

        t0 = time.perf_counter()
        obs.gauge(
            "comm_bucket_bytes",
            help="payload bytes of the last gradient all-reduce bucket",
        ).set(int(buf.nbytes))
        with self._lock:
            if self.world == 1:
                out = np.array(buf, copy=True)
            elif self.rank == 0:
                acc = np.array(buf, dtype=buf.dtype, copy=True)
                chunks = {}
                for r in self._peers:
                    chunks[r] = np.frombuffer(
                        _recv_msg(self._peers[r]), dtype=buf.dtype
                    )
                for r in sorted(chunks):  # rank order => deterministic sum
                    acc += chunks[r]
                acc /= self.world
                payload = acc.tobytes()
                for r in self._peers:
                    _send_msg(self._peers[r], payload)
                out = acc
            else:
                _send_msg(self._root, np.ascontiguousarray(buf).tobytes())
                out = np.frombuffer(_recv_msg(self._root), dtype=buf.dtype).copy()
        obs.counter(
            "comm_allreduce_seconds_total",
            help="host seconds spent inside gradient all-reduces",
        ).inc(time.perf_counter() - t0)
        return out

    def close(self):
        for s in list(self._peers.values()) + [self._root, self._listener]:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        self._peers.clear()
        self._root = self._listener = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
