"""XLA collective wrappers — the NCCL/RING replacement.

The reference's collective layer was TensorFlow's `CollectiveAllReduce` over
NCCL/gRPC, selected by `all_reduce_alg`/`num_packs` flags
(/root/reference/examples/resnet/resnet_cifar_dist.py:104-105). On TPU the
equivalents are XLA collectives over ICI, emitted either implicitly by `pjit`
from shardings or explicitly inside `shard_map` bodies via these wrappers.

These are deliberately thin: the value they add is (a) one place that
documents the NCCL→XLA mapping, (b) axis-name defaulting over the canonical
data axes, (c) a `shard_map`-friendly surface for the strategy layer and ring
attention.

NCCL / TF collective      → XLA / jax primitive
-------------------------   ------------------------------------
all_reduce (sum/mean)     → lax.psum / lax.pmean
all_gather                → lax.all_gather
reduce_scatter            → lax.psum_scatter
send/recv ring            → lax.ppermute
all_to_all (a2a SP/EP)    → lax.all_to_all
broadcast                 → implicit (replicated sharding)
"""

from jax import lax


def shard_map(f, mesh=None, in_specs=None, out_specs=None, **kwargs):
    """Version-portable ``shard_map``.

    ``jax.shard_map`` only exists as a top-level export on newer jax; on the
    0.4.x line it lives in ``jax.experimental.shard_map`` and spells the
    replication-check kwarg ``check_rep`` instead of ``check_vma``. Every
    shard_map in this package goes through here so the version probe (and the
    kwarg translation) happens in one place.
    """
    import inspect

    import jax

    impl = getattr(jax, "shard_map", None)
    if impl is None:
        from jax.experimental.shard_map import shard_map as impl
    try:
        accepted = inspect.signature(impl).parameters
    except (TypeError, ValueError):  # pragma: no cover - exotic wrappers
        accepted = None
    if accepted is not None:
        for old, new in (("check_vma", "check_rep"), ("check_rep", "check_vma")):
            if old in kwargs and old not in accepted and new in accepted:
                kwargs[new] = kwargs.pop(old)
    return impl(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs)


def psum(x, axis_name):
    """All-reduce sum over a mesh axis (NCCL allreduce equivalent)."""
    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    """All-reduce mean — gradient averaging for sync data parallelism."""
    return lax.pmean(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    """Gather shards from every member of the axis (NCCL allgather)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, scatter_dimension=0, tiled=True):
    """Reduce-then-scatter (NCCL reducescatter); the building block of ZeRO
    gradient sharding."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=tiled)


def ring_shift(x, axis_name, shift=1):
    """Rotate shards around the axis ring: member i's value goes to i+shift.

    The ppermute pattern behind ring attention and pipelined collectives; on
    TPU this maps onto neighbour ICI links.
    """
    n = axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm=perm)


def all_to_all(x, axis_name, split_axis, concat_axis, tiled=True):
    """All-to-all — the Ulysses-style sequence-parallel exchange and the MoE
    expert dispatch primitive."""
    return lax.all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled
    )


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    """Static size of a mesh axis from inside a collective body.

    ``lax.axis_size`` is a late addition to jax; ``psum`` of a python ``1``
    constant-folds to the same static int on every version in between.
    """
    if hasattr(lax, "axis_size"):
        return lax.axis_size(axis_name)
    return lax.psum(1, axis_name)
