"""Sharding rules: how batches and parameters map onto the mesh.

The replacement for the reference's implicit placement model (every worker
holds a full replica, NCCL all-reduces gradients): here placement is explicit
`jax.sharding.NamedSharding`s, and XLA derives the collectives. Batch tensors
shard their leading dimension across the data axes (``dp`` × ``fsdp``);
parameters are replicated for pure DP or sharded along ``fsdp`` (ZeRO-3 style)
with per-array axis selection.
"""

import logging

logger = logging.getLogger(__name__)


def data_axes(mesh):
    """The mesh axes a batch's leading dim is sharded over."""
    return tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names)


def batch_spec(mesh):
    """PartitionSpec for a batch: leading dim over the data axes."""
    from jax.sharding import PartitionSpec as P

    axes = data_axes(mesh)
    if not axes:
        return P()
    return P(axes if len(axes) > 1 else axes[0])


def batch_sharding(mesh):
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, batch_spec(mesh))


def replicated(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def _pick_fsdp_axis(shape, axis_size, min_weight_size):
    """Index of the dim to shard along fsdp: the largest dim divisible by the
    axis size, on arrays big enough to be worth sharding; None = replicate."""
    import math

    if math.prod(shape) < min_weight_size:
        return None
    best, best_dim = None, -1
    for i, d in enumerate(shape):
        if d % axis_size == 0 and d > best_dim:
            best, best_dim = i, d
    return best


def fsdp_param_specs(params, mesh, min_weight_size=2**14):
    """PartitionSpec pytree for params: fully-shard eligible arrays along the
    ``fsdp`` axis (ZeRO-3), replicate the rest (biases, norm scales, small
    embeddings). With no ``fsdp`` axis in the mesh, everything replicates."""
    import jax
    from jax.sharding import PartitionSpec as P

    if "fsdp" not in mesh.axis_names:
        return jax.tree.map(lambda _: P(), params)
    axis_size = mesh_axis_size(mesh, "fsdp")

    def spec_for(x):
        shape = getattr(x, "shape", ())
        dim = _pick_fsdp_axis(shape, axis_size, min_weight_size)
        if dim is None:
            return P()
        spec = [None] * len(shape)
        spec[dim] = "fsdp"
        return P(*spec)

    return jax.tree.map(spec_for, params)


def _spec_axes(spec):
    """Flat set of mesh-axis names a PartitionSpec already uses."""
    used = set()
    for entry in tuple(spec):
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


def overlay_fsdp_specs(params, specs, mesh, min_weight_size=2**14):
    """Overlay ZeRO-3 sharding onto an existing per-array spec tree.

    The composition rule for hybrid dp×fsdp(×tp) meshes: a model's own
    placement (e.g. :func:`tensorflowonspark_tpu.models.transformer.param_specs`
    claiming ``tp``/``fsdp`` dims) wins where it already touches the ``fsdp``
    axis; every other array big enough to be worth sharding gets its largest
    still-unclaimed dim sharded along ``fsdp``, so the optimizer state and
    per-step all-gather shrink even for arrays the model rules replicate.
    With no ``fsdp`` axis in the mesh this is the identity.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    if "fsdp" not in mesh.axis_names:
        return specs
    axis_size = mesh_axis_size(mesh, "fsdp")

    def overlay(x, s):
        import math

        if "fsdp" in _spec_axes(s):
            return s
        shape = getattr(x, "shape", ())
        if math.prod(shape) < min_weight_size:
            return s
        entries = list(tuple(s)) + [None] * (len(shape) - len(tuple(s)))
        best, best_dim = None, -1
        for i, d in enumerate(shape):
            if entries[i] is None and d % axis_size == 0 and d > best_dim:
                best, best_dim = i, d
        if best is None:
            return s
        entries[best] = "fsdp"
        return P(*entries)

    return jax.tree.map(
        overlay, params, specs, is_leaf=lambda n: isinstance(n, P)
    )


def mesh_axis_size(mesh, name):
    return dict(zip(mesh.axis_names, mesh.devices.shape)).get(name, 1)


def shard_params(params, mesh, specs=None):
    """Place a params pytree onto the mesh (replicated or per-array specs)."""
    import jax
    from jax.sharding import NamedSharding

    if specs is None:
        specs = fsdp_param_specs(params, mesh)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs
    )


def shard_batch(batch, mesh):
    """Place a host-local batch pytree onto the mesh, sharded over data axes.

    Single-process: a plain sharded ``device_put``. Multi-process (one process
    per TPU host, the TFSparkNode world): each process contributes its local
    shard via ``make_array_from_process_local_data`` — the device-side analogue
    of the reference's per-executor feed queues (each executor fed only its own
    partition; here each host's partition becomes its shard of the global
    batch).
    """
    import jax

    sharding = batch_sharding(mesh)
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, x), batch
    )
