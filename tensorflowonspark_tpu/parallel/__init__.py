"""Parallelism core: device meshes, shardings, collectives, ring attention.

This package is the TPU-native replacement for the reference's entire
"training plane" (SURVEY.md §2.8): where TensorFlowOnSpark delegated
distribution to TF's gRPC ClusterSpec + NCCL/RING collective all-reduce
(/root/reference/tensorflowonspark/TFNode.py:123-129, TFSparkNode.py:277-299),
here distribution is expressed as shardings over a named
:class:`jax.sharding.Mesh` and XLA inserts the collectives (all-reduce /
all-gather / reduce-scatter / ppermute) over ICI within a slice and DCN across
slices.

Canonical mesh axes (any subset may be present, always in this order):

=======  =====================================================================
``dp``   pure data parallelism (params replicated, batch sharded)
``fsdp`` data parallelism with fully-sharded params (batch AND params sharded)
``tp``   tensor (a.k.a. model) parallelism — activations/weights sharded
``sp``   sequence/context parallelism — ring attention over this axis
``ep``   expert parallelism for MoE layers
``pp``   pipeline parallelism — GPipe stages (pipeline_parallel module)
=======  =====================================================================
"""

# Lazy re-exports (PEP 562): importing this package must not import jax —
# executor/driver processes stay jax-free so the platform (TPU vs CPU) is
# decided by the jax child process, not by whoever imported the package first.
_EXPORTS = {
    "AXIS_ORDER": "mesh",
    "build_hybrid_mesh": "mesh",
    "build_mesh": "mesh",
    "local_mesh": "mesh",
    "mesh_shape": "mesh",
    "shard_map": "collectives",
    "batch_sharding": "sharding",
    "batch_spec": "sharding",
    "data_axes": "sharding",
    "fsdp_param_specs": "sharding",
    "overlay_fsdp_specs": "sharding",
    "replicated": "sharding",
    "shard_batch": "sharding",
    "shard_params": "sharding",
    "collectives": None,
    "HostAllReduceGroup": "hostreduce",
    "ring_attention": "ring_attention",
    "ring_attention_sharded": "ring_attention",
    "pipeline_apply": "pipeline_parallel",
    "Pipeline1F1B": "pipeline_parallel",
    "schedule_1f1b": "pipeline_parallel",
    "stack_stage_params": "pipeline_parallel",
    "split_microbatches": "pipeline_parallel",
    "merge_microbatches": "pipeline_parallel",
}


def __getattr__(name):
    import importlib

    if name not in _EXPORTS:
        raise AttributeError(name)
    submodule = _EXPORTS[name] or name
    mod = importlib.import_module("tensorflowonspark_tpu.parallel." + submodule)
    return mod if _EXPORTS[name] is None else getattr(mod, name)


def __dir__():
    return sorted(_EXPORTS)
