"""Parallelism core: device meshes, shardings, collectives, ring attention.

This package is the TPU-native replacement for the reference's entire
"training plane" (SURVEY.md §2.8): where TensorFlowOnSpark delegated
distribution to TF's gRPC ClusterSpec + NCCL/RING collective all-reduce
(/root/reference/tensorflowonspark/TFNode.py:123-129, TFSparkNode.py:277-299),
here distribution is expressed as shardings over a named
:class:`jax.sharding.Mesh` and XLA inserts the collectives (all-reduce /
all-gather / reduce-scatter / ppermute) over ICI within a slice and DCN across
slices.

Canonical mesh axes (any subset may be present, always in this order):

=======  =====================================================================
``dp``   pure data parallelism (params replicated, batch sharded)
``fsdp`` data parallelism with fully-sharded params (batch AND params sharded)
``tp``   tensor (a.k.a. model) parallelism — activations/weights sharded
``sp``   sequence/context parallelism — ring attention over this axis
``ep``   expert parallelism for MoE layers
=======  =====================================================================
"""

from tensorflowonspark_tpu.parallel.mesh import (  # noqa: F401
    AXIS_ORDER,
    build_mesh,
    local_mesh,
    mesh_shape,
)
from tensorflowonspark_tpu.parallel.sharding import (  # noqa: F401
    batch_sharding,
    batch_spec,
    data_axes,
    fsdp_param_specs,
    replicated,
    shard_batch,
    shard_params,
)
from tensorflowonspark_tpu.parallel import collectives  # noqa: F401
from tensorflowonspark_tpu.parallel.ring_attention import (  # noqa: F401
    ring_attention,
    ring_attention_sharded,
)
