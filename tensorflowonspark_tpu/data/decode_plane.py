"""Multiprocess decode plane: GIL-free record decode into shared-memory slabs.

The input path's parse stage (PIL decode + augmentation,
:mod:`~tensorflowonspark_tpu.data.imagenet`) ran on a GIL-bound
``ThreadPoolExecutor`` — every bench round since r03 showed training
input-path-limited with parse as the dominant stall. This module takes the
decode off the GIL the way production input stacks do (tf.data service's
parallel host pipelines, NVIDIA DALI's process-isolated decoders): a pool
of worker *processes* decode records and write the pixels **directly into
preallocated shared-memory batch slabs**
(:class:`~tensorflowonspark_tpu.shm.SlabSegment`), so the producer thread
in :class:`~tensorflowonspark_tpu.data.ImagePipeline` assembles
device-ready ``[B,H,W,C]`` batches as zero-copy views and the recycle pool
becomes a cross-process slab free list.

The pieces:

* :class:`DecodePlane` — worker lifecycle (fork-spawned before the
  pipeline's threads start, respawn-on-death, clean drain on teardown),
  the slab pool (:meth:`DecodePlane.new_slab` mints pooled segments; the
  loader's free queue circulates the views), and the slot lease protocol:
  one *round* leases ``(seq, slab, slot, record bytes)`` tasks to workers
  over dedicated duplex pipes and collects ``(seq, slot, label | error)``
  acks. Each worker owns its own pipe — there is no cross-worker queue
  lock a SIGKILL could strand — so a death surfaces as EOF on that pipe
  and exactly its un-acked slots are re-leased. Duplicate work is harmless:
  ``parse_fn`` is deterministic per record (the imagenet/cifar fns key
  their augmentation RNG to the record bytes), so a re-decoded slot is
  written with identical bytes, and acks are deduped by slot.
* :class:`DecodeAutotuner` — self-sizes the worker count from the same
  stall counters operators read (``data_producer_parse_seconds_total`` vs
  ``data_consumer_wait_seconds_total``), with the
  :class:`~tensorflowonspark_tpu.data.autotune.FeedAutotuner` hysteresis
  discipline: grow immediately when the consumer starves on a
  parse-dominated producer, shrink only after ``down_patience``
  consecutive idle intervals.
* :func:`available` / :func:`resolve_workers` — the fallback contract:
  ``decode_workers=0`` (or a platform without fork /
  ``multiprocessing.shared_memory``) keeps today's thread pool, and the
  delivered batch stream is byte-identical across thread and process
  modes (pinned by tests/test_loader_pipeline.py).

``parse_fn`` contract: workers are **forked**, so the function (and
anything its closure captures) must be fork-inheritable and must not
depend on parent-thread state — importable module-level factories like
:func:`~tensorflowonspark_tpu.data.imagenet.make_parse_fn` qualify. The
task/ack framing itself stays picklable (record bytes in, labels or error
strings out); decoded-cache writes flow back through the slab, never
through pickle.

Observability (merged into ``TFCluster.metrics()``):

==================================  =======================================
metric                              meaning
==================================  =======================================
``decode_workers``                  worker processes currently in the pool
``decode_worker_restarts_total``    workers respawned after dying mid-round
``decode_slab_bytes``               bytes resident in the slab pool
``decode_slab_wait_seconds_total``  producer waits on an empty slab free list
``decode_native_total``             records decoded by the native JPEG path
==================================  =======================================

The ``data.decode_kill`` chaos site SIGKILLs one worker mid-round
(parent-side roll, so the seeded schedule is thread-timing independent and
the fault counter lands in the process whose registry reaches the cluster
merge); the lease protocol must respawn and re-lease with no lost or
duplicated rows — exercised at cluster level by tests/test_chaos_cluster.py.
"""

import logging
import os
import signal
import time

import numpy as np

from tensorflowonspark_tpu import chaos, obs
from tensorflowonspark_tpu.control import Controller, DeltaTicker, StallRule
from tensorflowonspark_tpu.shm import SlabSegment

logger = logging.getLogger(__name__)

#: how long one ack wait may block before the round re-checks the stop flag
#: (worker deaths need no poll — they surface as EOF on the dead pipe)
ACK_POLL_SECONDS = 0.2


class Stopped(Exception):
    """The consumer departed mid-round; unwind the caller quietly (the
    loader translates this into its own teardown exception)."""


class DecodeWorkerError(RuntimeError):
    """A record failed to parse inside a worker process. Carries the
    worker-side exception as text — the original object cannot cross the
    process boundary reliably, but the budget/absorb semantics only need
    the message."""


def available():
    """True when the process decode plane can run here: a POSIX fork start
    method and a usable ``multiprocessing.shared_memory``."""
    try:
        from multiprocessing import shared_memory  # noqa: F401
    except ImportError:
        return False
    import multiprocessing

    return "fork" in multiprocessing.get_all_start_methods()


def resolve_workers(decode_workers):
    """Normalize the ``decode_workers`` knob: ``None`` reads
    ``TOS_DECODE_WORKERS`` (default 0 = thread pool), ``"auto"`` self-sizes
    (start at half the cores, let :class:`DecodeAutotuner` move it),
    anything else is a fixed count. Returns ``(workers, autotune)``."""
    if decode_workers is None:
        decode_workers = os.environ.get("TOS_DECODE_WORKERS", "0")
    if isinstance(decode_workers, str) and decode_workers.strip().lower() == "auto":
        return max(1, (os.cpu_count() or 1) // 2), True
    return max(0, int(decode_workers)), False


def _worker_main(conn, parse_fn):
    """Worker-process loop: lease tasks off the dedicated pipe, decode into
    slab slots, ack on the same pipe.

    Every failure mode acks — an unacked slot would stall the round until
    the parent re-leases it — so parse errors travel back as
    ``(seq, slot, False, text)`` and only a torn pipe (parent gone or
    retiring this worker) ends the loop.
    """
    # the parent's SIGINT belongs to the training process; workers die by
    # pipe EOF (retire/teardown) or SIGKILL (crash/chaos) only
    signal.signal(signal.SIGINT, signal.SIG_IGN)
    # fork carries the parent's trace context in os.environ: adopt it under
    # this worker's own proc label so the flight recorder opens a fresh
    # shard (never interleaving the parent's), and stamp the fork on the
    # timeline. No-ops entirely when no trace is active.
    from tensorflowonspark_tpu.obs import tracing as obs_tracing

    obs_tracing.install_from_env("decode-worker")
    obs_tracing.event("decode_worker_start", pid=os.getpid())
    into = getattr(parse_fn, "into", None)
    slabs = {}  # name -> SlabSegment kept attached across rounds
    while True:
        try:
            task = conn.recv()
        except (EOFError, OSError):
            break
        seq, slab_name, slot, geom, rec = task
        try:
            batch_size, shape, dtype = geom
            slab = slabs.get(slab_name)
            if slab is None:
                slab = slabs[slab_name] = SlabSegment.attach(slab_name)
            view = slab.ndarray((batch_size,) + tuple(shape), dtype)
            if into is not None:
                # native fast path: decode straight into the slab slot (no
                # PIL, no intermediate copy); falls back to PIL internally
                lbl, native = into(rec, view[slot])
            else:
                img, lbl = parse_fn(rec)
                view[slot] = img  # raises on shape/dtype mismatch vs slot 0
                native = False
            ack = (seq, slot, True, (int(lbl), bool(native)))
        except Exception as e:
            ack = (seq, slot, False, "{}: {}".format(type(e).__name__, e))
        try:
            conn.send(ack)
        except (BrokenPipeError, OSError):
            break
    for slab in slabs.values():
        slab.close()
    conn.close()


class _Worker:
    """Parent-side handle: the process plus its dedicated duplex pipe."""

    __slots__ = ("proc", "conn")

    def __init__(self, proc, conn):
        self.proc = proc
        self.conn = conn


class DecodePlane:
    """A pool of decode worker processes plus the slab pool they write into.

    Construct (and thereby fork the workers) BEFORE starting any pipeline
    threads — fork-with-threads is the one lifecycle hazard here, and the
    loader's ``__iter__`` spawns the plane first for exactly that reason.
    Respawns after a worker death do fork with threads running; the child
    immediately enters pipe/numpy-only code, the same envelope
    ``multiprocessing.Pool`` lives in.

    The round protocol (:meth:`run_round`) preserves the loader's
    byte-identical stream contract: the caller keeps its slot-assignment
    algorithm (records to the lowest free slots, failures leave holes) and
    the plane only changes *where* the decode runs.
    """

    def __init__(self, parse_fn, workers, autotuner=None):
        if workers < 1:
            raise ValueError("DecodePlane needs at least one worker")
        import multiprocessing

        self._ctx = multiprocessing.get_context("fork")
        self._parse_fn = parse_fn
        self._autotuner = autotuner
        self._workers = []
        self._retired = []  # closed-off workers still to be reaped
        self._slabs = {}  # slab name -> SlabSegment (creator side)
        self._names = {}  # id(image view) -> slab name
        self._geom = None
        self._seq = 0
        self._closed = False
        self._workers_g = obs.gauge(
            "decode_workers", help="decode worker processes currently pooled"
        )
        self._restarts_c = obs.counter(
            "decode_worker_restarts_total",
            help="decode workers respawned after dying mid-round",
        )
        self._slab_bytes_g = obs.gauge(
            "decode_slab_bytes", help="bytes resident in the decode slab pool"
        )
        self._slab_wait_c = obs.counter(
            "decode_slab_wait_seconds_total",
            help="seconds the producer waited on an empty slab free list",
        )
        self._native_c = obs.counter(
            "decode_native_total",
            help="records decoded by the native JPEG path (no PIL)",
        )
        for _ in range(int(workers)):
            self._spawn()

    # -- worker lifecycle -------------------------------------------------------

    def _spawn(self):
        parent_conn, child_conn = self._ctx.Pipe(duplex=True)
        proc = self._ctx.Process(
            target=_worker_main,
            args=(child_conn, self._parse_fn),
            name="tos-decode-worker",
            daemon=True,
        )
        proc.start()
        child_conn.close()  # the child's end lives in the child only
        self._workers.append(_Worker(proc, parent_conn))
        self._workers_g.set(len(self._workers))

    @property
    def workers(self):
        """Current pool size (retired workers excluded)."""
        return len(self._workers)

    def _on_death(self, worker, restart=True):
        """Remove a dead worker; respawn a replacement unless tearing
        down. Returns the replacement (or None)."""
        if worker in self._workers:
            self._workers.remove(worker)
        try:
            worker.conn.close()
        except OSError:
            pass
        worker.proc.join(timeout=0)
        self._workers_g.set(len(self._workers))
        if not restart or self._closed:
            return None
        self._restarts_c.inc()
        logger.warning("decode worker pid %s died; respawning", worker.proc.pid)
        self._spawn()
        return self._workers[-1]

    def resize(self, target):
        """Move the pool toward ``target`` workers: growth forks
        immediately, shrink retires the newest workers by closing their
        pipes (the worker sees EOF after finishing its current lease and
        exits — no round is ever interrupted)."""
        target = max(1, int(target))
        while len(self._workers) < target:
            self._spawn()
        while len(self._workers) > target:
            w = self._workers.pop()
            try:
                w.conn.close()
            except OSError:
                pass
            self._retired.append(w.proc)
        self._workers_g.set(len(self._workers))

    def autotune_tick(self):
        """Give the :class:`DecodeAutotuner` (when configured) a chance to
        resize from the measured stall counters; call between rounds."""
        if self._autotuner is None:
            return
        target = self._autotuner.tick(len(self._workers))
        if target is not None and target != len(self._workers):
            logger.info(
                "decode autotuner: %d -> %d workers", len(self._workers), target
            )
            self.resize(target)

    # -- slab pool --------------------------------------------------------------

    def new_slab(self, batch_size, shape, dtype):
        """Mint one pooled slab sized for a ``[B,H,W,C]`` batch and return
        its zero-copy image view plus a parent-side label buffer. The view
        circulates through the loader's free queue; the plane keeps the
        segment (and the view→name mapping the lease protocol needs)."""
        self._geom = (int(batch_size), tuple(shape), np.dtype(dtype).str)
        nbytes = int(batch_size) * int(np.prod(shape)) * np.dtype(dtype).itemsize
        slab = SlabSegment.create(nbytes)
        self._slabs[slab.name] = slab
        images = slab.ndarray((batch_size,) + tuple(shape), dtype)
        self._names[id(images)] = slab.name
        self._slab_bytes_g.set(float(sum(s.nbytes for s in self._slabs.values())))
        return images, np.empty((batch_size,), np.int32)

    # -- the slot lease protocol ------------------------------------------------

    def run_round(self, images, labels, tasks, should_stop=None):
        """Decode ``tasks`` — ``[(slot, record bytes), ...]`` — into the
        slab behind ``images``, filling ``labels`` parent-side from the
        acks. Returns ``[(slot, DecodeWorkerError), ...]`` for records that
        failed to parse (same contract as the thread pool's per-slot
        results; the caller absorbs within its ``max_bad_records`` budget).

        Liveness: a worker death surfaces as EOF on its own pipe (no
        shared lock a SIGKILL could strand); its un-acked slots are
        re-leased to the respawned pool. Stale acks (earlier ``seq``) and
        duplicate acks are dropped — slab writes are idempotent because
        ``parse_fn`` is deterministic per record.
        """
        from multiprocessing import connection

        if not tasks:
            return []
        if self._geom is None:
            raise RuntimeError("run_round before new_slab: no batch geometry")
        self._seq += 1
        seq = self._seq
        name = self._names[id(images)]
        by_slot = dict(tasks)
        pending = set(by_slot)
        needs = sorted(pending)  # slots awaiting (re-)lease
        owner = {}  # slot -> _Worker currently leasing it
        failures = []

        def _check_stop():
            if should_stop is not None and should_stop():
                raise Stopped()

        def _reap(worker):
            # a dead worker takes its in-flight leases with it
            replacement = self._on_death(worker)
            orphans = sorted(s for s, w in owner.items() if w is worker and s in pending)
            for s in orphans:
                del owner[s]
            needs.extend(orphans)
            return replacement

        def _drain(timeout):
            conns = {w.conn: w for w in self._workers}
            if not conns:
                return
            for conn in connection.wait(list(conns), timeout=timeout):
                worker = conns[conn]
                try:
                    ack_seq, slot, ok, payload = conn.recv()
                except (EOFError, OSError):
                    _reap(worker)
                    continue
                if ack_seq != seq or slot not in pending:
                    continue  # stale round, or a duplicate after a re-lease
                pending.discard(slot)
                owner.pop(slot, None)
                if ok:
                    labels[slot] = payload[0]
                    if payload[1]:
                        self._native_c.inc()
                else:
                    failures.append((slot, DecodeWorkerError(payload)))

        first_wave = True
        while pending:
            _check_stop()
            while needs:
                todo, needs[:] = list(needs), []
                for i, slot in enumerate(todo):
                    while not self._workers:
                        self._spawn()  # the whole pool died at once
                    worker = self._workers[i % len(self._workers)]
                    try:
                        worker.conn.send((seq, name, slot, self._geom, by_slot[slot]))
                        owner[slot] = worker
                    except (BrokenPipeError, OSError):
                        needs.append(slot)
                        _reap(worker)
                # keep the ack direction drained while leasing, so a big
                # round can never wedge on two full pipe buffers
                _drain(0)
            if first_wave:
                first_wave = False
                self._maybe_chaos_kill()
            if pending:
                _drain(ACK_POLL_SECONDS)
        return failures

    def _maybe_chaos_kill(self):
        """``data.decode_kill``: SIGKILL one live worker mid-round. Rolled
        parent-side so the seeded schedule is independent of worker timing
        and the fault counter lands in the registry that reaches the
        cluster merge."""
        if not (chaos.active and chaos.fire("data.decode_kill")):
            return
        victim = next((w for w in self._workers if w.proc.is_alive()), None)
        if victim is not None:
            logger.warning("chaos: SIGKILL decode worker pid %d", victim.proc.pid)
            os.kill(victim.proc.pid, signal.SIGKILL)

    # -- teardown ---------------------------------------------------------------

    def close(self, timeout=5.0):
        """Clean drain: close every lease pipe (workers exit at EOF after
        their current task), join with a deadline, SIGKILL stragglers,
        then unlink the slab pool. Idempotent — both the producer's
        teardown and the consumer's ``finally`` may land here."""
        if self._closed:
            return
        self._closed = True
        procs = [w.proc for w in self._workers] + self._retired
        for w in self._workers:
            try:
                w.conn.close()
            except OSError:
                pass
        self._workers = []
        self._retired = []
        deadline = time.monotonic() + timeout
        for p in procs:
            p.join(timeout=max(0.0, deadline - time.monotonic()))
            if p.is_alive():
                p.kill()
                p.join(timeout=1.0)
        self._workers_g.set(0)
        for slab in self._slabs.values():
            # release, not close: emitted batch views may outlive the plane
            # (the consumer's last batch) — the mapping follows the views
            slab.release()
        self._slabs = {}
        self._names = {}
        self._slab_bytes_g.set(0)

    def note_slab_wait(self, seconds):
        """Wait-accounting hook: the loader calls this when its buffer
        acquire blocked on the slab free list."""
        self._slab_wait_c.inc(seconds)


class DecodeAutotuner:
    """Self-sizing controller for the decode worker count.

    Mirrors :class:`~tensorflowonspark_tpu.data.autotune.FeedAutotuner`'s
    discipline on a different pair of measurements: the deltas of
    ``data_producer_parse_seconds_total`` (is the parse stage busy?) and
    ``data_consumer_wait_seconds_total`` (is the training loop starving?)
    over each observation interval.

    Decision rule per interval of ``check_every`` seconds:

    * consumer starved for more than ``starve_ratio`` of the interval AND
      parse dominated the wait → the decode plane is the bottleneck:
      **grow one worker immediately** (starvation is expensive *now*).
    * consumer essentially never starved (wait share below ``idle_ratio``)
      → the input path is ahead of the consumer: **shrink one worker after
      ``down_patience`` consecutive idle intervals** (hysteresis against
      mood flicker — flapping thrashes the fork rate for nothing).

    Bounds: ``[min_workers, max_workers]`` (default 1 .. ``os.cpu_count()``).
    The counter reads are injectable (``read_counters``), so the decision
    core is a pure function of its inputs in tests, like the feed
    autotuner's injectable clock.
    """

    def __init__(
        self,
        min_workers=1,
        max_workers=None,
        starve_ratio=0.05,
        idle_ratio=0.01,
        down_patience=2,
        check_every=2.0,
        clock=None,
        read_counters=None,
    ):
        self.min_workers = max(1, int(min_workers))
        self.max_workers = int(max_workers or (os.cpu_count() or 1))
        if self.max_workers < self.min_workers:
            raise ValueError("max_workers must be >= min_workers")
        self.starve_ratio = float(starve_ratio)
        self.idle_ratio = float(idle_ratio)
        self.down_patience = max(1, int(down_patience))
        self.check_every = float(check_every)
        # the shared control core: starvation verdict, up-fast/down-slow
        # hysteresis inside the worker bounds, and the clocked delta gate
        self._rule = StallRule(
            starve_ratio=self.starve_ratio, idle_ratio=self.idle_ratio
        )
        self._ctl = Controller(
            lo=self.min_workers, hi=self.max_workers,
            down_patience=self.down_patience, name="decode_workers",
        )
        self._ticker = DeltaTicker(
            self.check_every, read_counters or self._read_obs, clock=clock
        )

    @staticmethod
    def _read_obs():
        counters = obs.snapshot()["counters"]

        def _c(counter_name):
            return counters.get(counter_name, {}).get("value", 0.0)

        return (
            _c("data_producer_parse_seconds_total"),
            _c("data_consumer_wait_seconds_total"),
        )

    def decide(self, workers, parse_delta, wait_delta, elapsed):
        """Pure decision: the worker count argued for by one interval's
        counter deltas (no clock, no obs — the unit-testable core)."""
        if elapsed <= 0:
            return workers
        want = self._rule.want(wait_delta / elapsed, parse_delta >= wait_delta)
        return self._ctl.step(workers, want)

    def tick(self, workers):
        """Clocked wrapper for :meth:`decide`: reads the counters at most
        every ``check_every`` seconds; returns the new target count, or
        None when the interval has not elapsed yet."""
        out = self._ticker.tick()
        if out is None:
            return None
        (parse_delta, wait_delta), elapsed = out
        return self.decide(workers, parse_delta, wait_delta, elapsed)
