"""CIFAR-style record parsing and augmentation.

Capability-parity with /root/reference/examples/resnet/cifar_preprocessing.py
(:42-90 parse, :93-123 preprocess: pad-4 + random crop + flip for training,
per-image standardization always), numpy host-side.

Record schema (what :func:`encode_example` / dfutil write): ``image`` raw
uint8 HWC bytes (32x32x3), ``label`` int64 — a TFRecord-native layout rather
than the reference's legacy depth-major CIFAR binary.
"""

import numpy as np

from tensorflowonspark_tpu import tfrecord

HEIGHT = 32
WIDTH = 32
NUM_CHANNELS = 3
NUM_CLASSES = 10
NUM_IMAGES = {"train": 50000, "validation": 10000}
_PAD = 4


def preprocess_train(image, rng):
    """uint8 HWC → float32: pad+random crop, random flip, standardize."""
    padded = np.pad(image, ((_PAD, _PAD), (_PAD, _PAD), (0, 0)), mode="constant")
    y = rng.integers(0, 2 * _PAD + 1)
    x = rng.integers(0, 2 * _PAD + 1)
    out = padded[y : y + HEIGHT, x : x + WIDTH]
    if rng.random() < 0.5:
        out = out[:, ::-1]
    return _standardize(out)


def preprocess_eval(image):
    return _standardize(image)


def _standardize(image):
    """Per-image standardization (the reference applies
    tf.image.per_image_standardization, cifar_preprocessing.py:121)."""
    img = np.asarray(image, np.float32)
    mean = img.mean()
    # stddev floored at 1/sqrt(N) like TF's adjusted_stddev
    adj = max(img.std(), 1.0 / np.sqrt(img.size))
    return (img - mean) / adj


def make_parse_fn(is_training, seed=0):
    """record bytes → (image f32 32x32x3, label int32). Augmentation rng is
    keyed to (seed, crc32 of the record) — deterministic under thread-pooled
    parsing (see imagenet.make_parse_fn)."""
    import zlib

    def parse(record):
        feats = tfrecord.decode_example(record)
        raw = feats["image"][1][0]
        image = np.frombuffer(raw, np.uint8).reshape(HEIGHT, WIDTH, NUM_CHANNELS)
        label = int(feats["label"][1][0])
        if is_training:
            rng = np.random.default_rng((seed << 32) ^ zlib.crc32(record))
            return preprocess_train(image, rng), label
        return preprocess_eval(image), label

    return parse


def encode_example(image_array, label):
    """uint8 HWC array + label → serialized Example (prep/test twin)."""
    arr = np.ascontiguousarray(np.asarray(image_array, np.uint8))
    return tfrecord.encode_example({"image": [arr.tobytes()], "label": [int(label)]})
