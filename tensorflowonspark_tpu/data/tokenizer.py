"""Deterministic, dependency-free tokenizers for the text plane.

The text plane (:mod:`~tensorflowonspark_tpu.data.text_plane`) needs three
things from a tokenizer, and nothing else:

1. a **cheap validating length** — :meth:`Tokenizer.token_length` tells the
   packer how many slots a record will occupy *before* anything is encoded,
   and it is the single place malformed input is rejected (not UTF-8, empty
   text, missing TFRecord feature). Because the packer calls it in the
   producer thread in every mode, the ``max_bad_records`` budget accounting
   is identical across thread and process packing — mode-invariant by
   construction.
2. a **deterministic encode** — :meth:`Tokenizer.encode` maps the same
   record bytes to the same ``int32`` ids everywhere (producer thread,
   forked pack worker, warm cache run), so the delivered ``[B, L]`` stream
   is byte-identical across worker counts and cache states.
3. a **config fingerprint** — :attr:`Tokenizer.cache_key` scopes the
   packed-slab cache (:mod:`~tensorflowonspark_tpu.data.slab_cache`) so a
   vocab or kind change can never serve stale token rows.

Two tokenizer kinds cover the subsystem without pulling in a vocab file
dependency (the container has none):

- ``"byte"`` — one token per UTF-8 byte, offset past the reserved ids.
  Lossless, vocabulary 259, the ByT5 shape (Xue et al. 2022).
- ``"word"`` — whitespace words hashed onto a fixed table with crc32
  ("feature hashing"); lossy but realistic LM lengths for benchmarks.

Ids ``0/1/2`` are reserved as ``PAD/BOS/EOS`` in both kinds; every encoded
sequence is ``[BOS] + body + [EOS]`` and truncation keeps the terminal EOS.

Records are raw text bytes by default; with ``field="name"`` the record is
a serialized TFRecord ``Example`` (the shape :meth:`TFEstimator
<tensorflowonspark_tpu.pipeline.TFEstimator>` materializes via
``setTFRecordDir``) and the named bytes feature is extracted first.
"""

import zlib

import numpy as np

__all__ = [
    "PAD_ID",
    "BOS_ID",
    "EOS_ID",
    "RESERVED_IDS",
    "TokenizeError",
    "Tokenizer",
    "make_pack_fn",
    "write_segment",
]

#: reserved special ids shared by every tokenizer kind
PAD_ID = 0
BOS_ID = 1
EOS_ID = 2
#: first id available to real tokens
RESERVED_IDS = 3

#: vocabulary a byte tokenizer always has: 256 byte values + reserved ids
BYTE_VOCAB = 256 + RESERVED_IDS


class TokenizeError(ValueError):
    """A record the tokenizer refuses: not valid UTF-8, empty text, or a
    TFRecord Example missing the configured text feature. Charged against
    the pipeline's ``max_bad_records`` budget like an undecodable JPEG."""


class Tokenizer:
    """Config + pure functions; safe to share across threads and to
    inherit into forked pack workers (no open handles, no RNG state).

    Parameters
    ----------
    kind:
        ``"byte"`` (default) or ``"word"``.
    vocab_size:
        Id-space size. Byte kind requires >= 259 (default exactly 259);
        word kind hashes words onto ``vocab_size - 3`` buckets (default
        32768).
    field:
        When set, records are serialized TFRecord ``Example`` protos and
        the text lives in this bytes feature (the ``dfutil`` /
        ``setTFRecordDir`` materialization shape). When None (default),
        records are the raw UTF-8 text bytes themselves.
    """

    def __init__(self, kind="byte", vocab_size=None, field=None):
        if kind not in ("byte", "word"):
            raise ValueError("kind must be 'byte' or 'word', got {!r}".format(kind))
        self.kind = kind
        if vocab_size is None:
            vocab_size = BYTE_VOCAB if kind == "byte" else 32768
        vocab_size = int(vocab_size)
        if kind == "byte" and vocab_size < BYTE_VOCAB:
            raise ValueError(
                "byte tokenizer needs vocab_size >= {} (got {})".format(
                    BYTE_VOCAB, vocab_size
                )
            )
        if kind == "word" and vocab_size <= RESERVED_IDS:
            raise ValueError("word tokenizer needs vocab_size > 3")
        self.vocab_size = vocab_size
        self.field = field

    # -- config fingerprint -------------------------------------------------

    @property
    def cache_key(self):
        """Scopes the packed-slab cache: any config change re-keys it."""
        return "text:{}:v{}:f{}".format(self.kind, self.vocab_size, self.field or "-")

    # -- validation + length ------------------------------------------------

    def _text_bytes(self, rec):
        """Raw UTF-8 text bytes of ``rec`` (after Example extraction when
        ``field`` is set). Raises :class:`TokenizeError` on anything that
        is not a non-empty, valid-UTF-8 text record."""
        raw = bytes(rec)
        if self.field is not None:
            from tensorflowonspark_tpu import tfrecord

            try:
                feats = tfrecord.decode_example(raw)
            except Exception as e:
                raise TokenizeError("record is not a TFRecord Example: {}".format(e))
            got = feats.get(self.field)
            if got is None or got[0] != "bytes" or not got[1]:
                raise TokenizeError(
                    "Example has no bytes feature {!r} (features: {})".format(
                        self.field, sorted(feats)
                    )
                )
            raw = got[1][0]
        try:
            text = raw.decode("utf-8")
        except UnicodeDecodeError as e:
            raise TokenizeError("record is not valid UTF-8: {}".format(e))
        if not text.strip():
            raise TokenizeError("empty text record")
        return raw

    def token_length(self, rec):
        """Untruncated token count of ``rec`` (BOS and EOS included)
        without building the id array — the packer's planning primitive.
        Raises :class:`TokenizeError` for malformed records, so budget
        accounting happens here, producer-side, in every pack mode."""
        raw = self._text_bytes(rec)
        if self.kind == "byte":
            return len(raw) + 2
        return len(raw.split()) + 2

    # -- encoding ------------------------------------------------------------

    def encode(self, rec, max_tokens=None):
        """``rec`` -> ``int32 [n]`` ids: ``[BOS] + body + [EOS]``; with
        ``max_tokens`` the body is truncated so ``n <= max_tokens`` and the
        terminal EOS survives (a truncated sequence still ends)."""
        raw = self._text_bytes(rec)
        if self.kind == "byte":
            body = np.frombuffer(raw, np.uint8).astype(np.int32) + RESERVED_IDS
        else:
            buckets = self.vocab_size - RESERVED_IDS
            body = np.fromiter(
                (RESERVED_IDS + zlib.crc32(w) % buckets for w in raw.split()),
                np.int32,
            )
        ids = np.empty(len(body) + 2, np.int32)
        ids[0] = BOS_ID
        ids[1:-1] = body
        ids[-1] = EOS_ID
        if max_tokens is not None and len(ids) > max_tokens:
            ids = ids[:max_tokens].copy()
            ids[-1] = EOS_ID
        return ids


def write_segment(row, offset, seg_id, ids):
    """Land one packed sequence into a ``[3, L]`` row at ``offset``:
    plane 0 = token ids, plane 1 = segment id (0 marks padding), plane 2 =
    positions restarting at 0 per segment (rotary phase must not leak
    across pack neighbours). Shared by the thread path and the forked
    pack workers — one writer, one byte layout."""
    n = len(ids)
    row[0, offset : offset + n] = ids
    row[1, offset : offset + n] = seg_id
    row[2, offset : offset + n] = np.arange(n, dtype=np.int32)


def make_pack_fn(tokenizer, seq_len):
    """Build the pack-plane ``parse_fn`` for :class:`~tensorflowonspark_tpu.
    data.text_plane.TextPipeline`.

    The decode plane's lease protocol ships an arbitrary picklable payload
    per slot; here the payload is a *pack plan* — a tuple of
    ``(offset, seg_id, eff_len, record_bytes)`` segments the producer could
    not serve from the packed-slab cache. ``.into(plan, row)`` tokenizes
    each segment and writes it at its planned offset via
    :func:`write_segment`; writes are deterministic and confined to the
    planned ranges, so a re-leased slot (worker death) simply rewrites the
    same bytes and the producer's own parent-side writes (zeroing, cache
    hits) are never touched.

    Returns a closure with the loader's parse-fn attributes: ``into``,
    ``cache_key`` (tokenizer fingerprint + ``seq_len``, because truncation
    depends on the bin capacity) and ``seq_len``.
    """
    seq_len = int(seq_len)

    def into(plan, row):
        for offset, seg_id, eff_len, rec in plan:
            write_segment(row, offset, seg_id, tokenizer.encode(rec, eff_len))
        return len(plan), False

    def pack_fn(plan):
        row = np.zeros((3, seq_len), np.int32)
        n, _ = into(plan, row)
        return row, n

    pack_fn.into = into
    pack_fn.cache_key = "{}:L{}".format(tokenizer.cache_key, seq_len)
    pack_fn.seq_len = seq_len
    pack_fn.tokenizer = tokenizer
    return pack_fn
