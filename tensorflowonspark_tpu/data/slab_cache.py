"""Cross-epoch decoded-slab cache: decode each record once per job, not once
per epoch.

Epoch 1 decodes every record (native or PIL) and the loader streams the
pixels into shared-memory slab slots; this module persists those decoded
rows so epoch >= 2 — and an elastic relaunch that re-reads the same shard
set — fills slots straight from a page-cached memory map instead of running
JPEG decode at all. A cache hit "leases a slot without touching a worker":
the loader writes the cached row parent-side and the decode plane never
sees the task.

Keying: a cache directory is scoped by the *decode-parameter fingerprint*
(``parse_fn.cache_key`` — train/eval, image size, augmentation seed, ...)
and rows inside it are keyed by the record's crc32. Same bytes + same
parameters ⇒ same pixels in every decode mode (the byte-identical stream
contract pinned by tests/test_loader_pipeline.py), so a cached row is
interchangeable with a fresh decode.

Durability uses the checkpoint commit pattern
(:mod:`tensorflowonspark_tpu.ckpt.manifest`): rows append to a staging
directory (``tmp.gen-*``), and :meth:`SlabCache.commit` seals it — fsync
the data file, write ``index.json``, write ``MANIFEST.json`` last, one
atomic rename to ``gen-<n>``. A generation is *adopted* only after
``manifest.verify`` passes on the published directory, so a torn commit
(crash mid-publish, or the ``data.cache_tear`` chaos site) is rejected and
its records simply decode again — the cache can serve stale-free or serve
nothing, never serve garbage.

Observability (rows in docs/architecture.md's Metrics inventory):

==================================  =======================================
metric                              meaning
==================================  =======================================
``decode_cache_hits_total``         slot fills served from the cache (any tier)
``decode_cache_rejects_total``      generations rejected by cheap-verify
``decode_cache_bytes``              bytes resident in committed generations
``tier_ram_hits_total``             hits served from the RAM tier
``tier_disk_hits_total``            hits served from a disk generation
``tier_promotions_total``           rows copied disk → RAM on a disk hit
``tier_demotions_total``            rows dropped from RAM by its LRU bound
``tier_evictions_total``            generations evicted by the disk bound
``tier_ram_bytes``                  bytes resident in the RAM tier
==================================  =======================================

Tier hierarchy (docs/architecture.md "Storage tiering"): RAM rows →
local-disk decoded generations → whatever cold store the loader reads
shards from (local filesystem, or a remote ``ShardStore`` behind the
prefetch stager). Both cache tiers are capacity-bounded — the RAM tier
drops least-recently-used rows (they stay on disk), the disk tier evicts
whole least-recently-used *generations* (their records decode again from
the cold store) — so the cache degrades to slower tiers, never to
unbounded growth.

Single-threaded by design: only the loader's producer thread touches a
``SlabCache`` (lookup/put/commit all happen on the slot-assignment path),
mirroring how the decode plane's lease protocol is driven from one thread.
"""

import collections
import json
import logging
import os
import shutil
import uuid

import numpy as np

from tensorflowonspark_tpu import chaos, durable, obs
from tensorflowonspark_tpu.ckpt import manifest

logger = logging.getLogger(__name__)

#: env default for the loader's ``slab_cache_dir`` knob
ENV_VAR = "TOS_SLAB_CACHE_DIR"
#: capacity bound (bytes) for the committed disk generations; 0/unset =
#: unbounded (the pre-tiering behavior)
BYTES_ENV_VAR = "TOS_SLAB_CACHE_BYTES"
#: capacity bound (bytes) for the RAM promotion tier
RAM_ENV_VAR = "TOS_SLAB_RAM_BYTES"
#: default RAM tier size: big enough to hold a benchmark epoch's hot rows,
#: small next to a training host's memory
DEFAULT_RAM_BYTES = 64 * 1024 * 1024

_DATA_NAME = "data.bin"
_INDEX_NAME = "index.json"


def resolve_dir(slab_cache_dir):
    """Normalize the loader knob: ``None`` reads :data:`ENV_VAR` (default
    off), empty string means off. Returns a path or None."""
    if slab_cache_dir is None:
        slab_cache_dir = os.environ.get(ENV_VAR, "")
    return slab_cache_dir or None


def _fingerprint(cache_key):
    """Filesystem-safe directory name for one decode-parameter set: a
    readable prefix plus a crc to keep distinct keys from colliding after
    sanitization."""
    import zlib

    safe = "".join(c if c.isalnum() or c in "._-" else "_" for c in str(cache_key))
    return "{}-{:08x}".format(safe[:80], zlib.crc32(str(cache_key).encode()))


class SlabCache:
    """Persistent decoded-row store for one ``(decode params, geometry)``.

    ``lookup(key)`` returns ``(pixels, label)`` from a committed generation
    (zero-copy view of a memory map) or None; ``put(key, pixels, label)``
    stages a freshly decoded row; ``commit()`` seals the staged rows into a
    new generation (call at epoch boundaries). Rows staged but never
    committed are discarded on :meth:`close` — exactly the checkpoint
    staging-dir contract.
    """

    def __init__(self, root, cache_key, shape, dtype, max_bytes=None, ram_bytes=None):
        self.shape = tuple(int(s) for s in shape)
        self.dtype = np.dtype(dtype)
        if self.dtype.hasobject:
            raise ValueError("slab cache rows must be a plain binary dtype")
        self.dir = os.path.join(
            os.path.abspath(os.path.expanduser(root)), _fingerprint(cache_key)
        )
        os.makedirs(self.dir, exist_ok=True)
        if max_bytes is None:
            max_bytes = int(os.environ.get(BYTES_ENV_VAR, "0")) or None
        if ram_bytes is None:
            ram_bytes = int(os.environ.get(RAM_ENV_VAR, str(DEFAULT_RAM_BYTES)))
        self.max_bytes = max_bytes
        self.ram_bytes = max(0, int(ram_bytes))
        self._row_bytes = int(np.prod(self.shape)) * self.dtype.itemsize
        # committed generations: (memmap, {key: (row, label)}), tombstoned
        # to None on eviction so _index map indices stay stable
        self._maps = []
        self._index = {}  # key -> (map idx, row) merged across generations
        self._staging = None  # (dir, open data file, {key: (row, label)})
        self._gen_dirs = {}  # map idx -> published directory (for eviction)
        self._gen_use = {}  # map idx -> tick of last hit (LRU eviction order)
        self._tick = 0
        self._ram = collections.OrderedDict()  # key -> (row copy, label), LRU
        self._hits_c = obs.counter(
            "decode_cache_hits_total", help="slot fills served from the decoded-slab cache"
        )
        self._rejects_c = obs.counter(
            "decode_cache_rejects_total",
            help="decoded-slab cache generations rejected by cheap-verify",
        )
        self._bytes_g = obs.gauge(
            "decode_cache_bytes", help="bytes resident in committed decoded-slab generations"
        )
        self._ram_hits_c = obs.counter(
            "tier_ram_hits_total", help="slab-cache hits served from the RAM tier"
        )
        self._disk_hits_c = obs.counter(
            "tier_disk_hits_total", help="slab-cache hits served from a disk generation"
        )
        self._promote_c = obs.counter(
            "tier_promotions_total", help="slab-cache rows promoted disk → RAM"
        )
        self._demote_c = obs.counter(
            "tier_demotions_total", help="slab-cache rows demoted out of the RAM tier"
        )
        self._evict_c = obs.counter(
            "tier_evictions_total",
            help="slab-cache generations evicted by the disk capacity bound",
        )
        self._ram_bytes_g = obs.gauge(
            "tier_ram_bytes", help="bytes resident in the slab-cache RAM tier"
        )
        self._load_generations()

    # -- read side --------------------------------------------------------------

    def _load_generations(self):
        for name in sorted(os.listdir(self.dir)):
            gen = os.path.join(self.dir, name)
            if not name.startswith("gen-") or not os.path.isdir(gen):
                continue
            ok, reason = manifest.verify(gen)
            if not ok or manifest.read_manifest(gen) is None:
                logger.warning("slab cache: rejecting %s (%s)", gen, reason if not ok else "no manifest")
                self._rejects_c.inc()
                shutil.rmtree(gen, ignore_errors=True)
                continue
            try:
                with open(os.path.join(gen, _INDEX_NAME)) as f:
                    meta = json.load(f)
                if tuple(meta["shape"]) != self.shape or meta["dtype"] != self.dtype.str:
                    logger.warning("slab cache: %s has geometry %s/%s, want %s/%s; skipping",
                                   gen, meta.get("shape"), meta.get("dtype"),
                                   list(self.shape), self.dtype.str)
                    continue
                rows = len(meta["keys"])
                mm = np.memmap(os.path.join(gen, _DATA_NAME), mode="r",
                               dtype=self.dtype, shape=(rows,) + self.shape)
            except (OSError, ValueError, KeyError) as e:
                logger.warning("slab cache: rejecting %s (%s)", gen, e)
                self._rejects_c.inc()
                shutil.rmtree(gen, ignore_errors=True)
                continue
            idx = len(self._maps)
            table = {}
            for row, (key, label) in enumerate(zip(meta["keys"], meta["labels"])):
                table[int(key)] = (row, int(label))
                self._index[int(key)] = (idx, row)
            self._maps.append((mm, table))
            self._gen_dirs[idx] = gen
            self._gen_use[idx] = 0
        self._evict_over_capacity()
        self._bytes_g.set(float(self._disk_bytes()))
        if self._index:
            logger.info("slab cache: %d row(s) across %d generation(s) at %s",
                        len(self._index), len(self._maps), self.dir)

    def _next_gen_dir(self):
        """First unused ``gen-<n>`` name. Collisions with a concurrent
        publisher surface as an OSError from :func:`os.rename` (rename onto
        an existing non-empty dir fails), which commit() treats as a reject
        — never as silent corruption."""
        taken = set()
        for name in os.listdir(self.dir):
            if name.startswith("gen-"):
                try:
                    taken.add(int(name[4:]))
                except ValueError:
                    pass
        n = 0
        while n in taken:
            n += 1
        return os.path.join(self.dir, "gen-{:06d}".format(n))

    def lookup(self, key):
        """``(pixels, label)`` for a record crc, or None — RAM tier first,
        then the disk generations (a disk hit promotes the row into RAM).
        The pixels are a read-only view (memmap) or the promoted copy —
        copy-on-assign into the slab slot is the single copy on either hit
        path."""
        key = int(key)
        hit = self._ram.get(key)
        if hit is not None:
            self._ram.move_to_end(key)
            self._hits_c.inc()
            self._ram_hits_c.inc()
            return hit
        loc = self._index.get(key)
        if loc is None:
            return None
        mm, table = self._maps[loc[0]]
        row, label = table[key]
        self._tick += 1
        self._gen_use[loc[0]] = self._tick
        self._hits_c.inc()
        self._disk_hits_c.inc()
        self._promote(key, mm[row], label)
        return mm[row], label

    def _promote(self, key, pixels, label):
        """Copy one disk-hit row into the RAM tier, demoting LRU rows past
        the RAM bound (they stay on disk — demotion is a free drop)."""
        if self._row_bytes > self.ram_bytes:
            return
        self._ram[key] = (np.array(pixels), int(label))
        self._ram.move_to_end(key)
        self._promote_c.inc()
        while len(self._ram) * self._row_bytes > self.ram_bytes:
            self._ram.popitem(last=False)
            self._demote_c.inc()
        self._ram_bytes_g.set(float(len(self._ram) * self._row_bytes))

    def __len__(self):
        return len(self._index)

    # -- write side -------------------------------------------------------------

    def put(self, key, pixels, label):
        """Stage one decoded row (no-op when the key is already cached or
        already staged). ``pixels`` must match the cache geometry."""
        key = int(key)
        if key in self._index:
            return
        if self._staging is None:
            stage = os.path.join(self.dir, "tmp.gen-{}".format(uuid.uuid4().hex[:8]))
            os.makedirs(stage)
            self._staging = (stage, open(os.path.join(stage, _DATA_NAME), "wb"), {})
        stage, data_f, staged = self._staging
        if key in staged:
            return
        arr = np.ascontiguousarray(pixels, dtype=self.dtype)
        if arr.shape != self.shape:
            raise ValueError("row shape {} != cache geometry {}".format(arr.shape, self.shape))
        data_f.write(arr.tobytes())
        staged[key] = (len(staged), int(label))

    def commit(self):
        """Seal the staged rows into a committed generation: fsync data,
        ``index.json``, ``MANIFEST.json`` last, atomic rename, then adopt
        the generation only after cheap-verify passes on the published
        directory (a torn publish is rejected and deleted — its records
        decode again). Returns the number of rows committed, 0 when nothing
        was staged."""
        if self._staging is None:
            return 0
        stage, data_f, staged = self._staging
        self._staging = None
        if not staged:
            data_f.close()
            shutil.rmtree(stage, ignore_errors=True)
            return 0
        data_f.flush()
        os.fsync(data_f.fileno())
        data_f.close()
        keys = sorted(staged, key=lambda k: staged[k][0])
        meta = {
            "shape": list(self.shape),
            "dtype": self.dtype.str,
            "keys": keys,
            "labels": [staged[k][1] for k in keys],
        }
        with open(os.path.join(stage, _INDEX_NAME), "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        manifest.write_manifest(stage)
        if chaos.active and chaos.fire("data.cache_tear"):
            # publish a *torn* manifest: the commit marker exists but lies,
            # exactly what a crash between manifest write and fsync leaves
            mpath = os.path.join(stage, manifest.MANIFEST_NAME)
            with open(mpath, "r+") as f:
                f.truncate(os.path.getsize(mpath) // 2)
        final = self._next_gen_dir()
        try:
            os.rename(stage, final)
        except OSError as e:
            logger.warning("slab cache: publish rename failed (%s); dropping", e)
            self._rejects_c.inc()
            shutil.rmtree(stage, ignore_errors=True)
            return 0
        # a generation that vanishes with a power cut is merely a cold
        # cache, but a half-visible one would be re-staged under a new
        # name while the old entry lingers — make the publish durable
        durable.fsync_dir(os.path.dirname(final))
        ok, reason = manifest.verify(final)
        if not ok:
            logger.warning("slab cache: published generation failed verify (%s); dropping", reason)
            self._rejects_c.inc()
            shutil.rmtree(final, ignore_errors=True)
            return 0
        rows = len(keys)
        mm = np.memmap(os.path.join(final, _DATA_NAME), mode="r",
                       dtype=self.dtype, shape=(rows,) + self.shape)
        idx = len(self._maps)
        table = {}
        for row, key in enumerate(keys):
            table[key] = (row, staged[key][1])
            self._index[key] = (idx, row)
        self._maps.append((mm, table))
        self._gen_dirs[idx] = final
        self._tick += 1
        self._gen_use[idx] = self._tick
        self._evict_over_capacity(keep=idx)
        self._bytes_g.set(float(self._disk_bytes()))
        logger.info("slab cache: committed %d row(s) (%d total) at %s", rows, len(self._index), self.dir)
        return rows

    # -- capacity bound ---------------------------------------------------------

    def _disk_bytes(self):
        return sum(entry[0].nbytes for entry in self._maps if entry is not None)

    def _evict_over_capacity(self, keep=None):
        """Evict least-recently-used generations until the committed bytes
        fit ``max_bytes`` (never the just-committed ``keep``). An evicted
        generation is tombstoned — map indices in ``_index`` stay stable —
        and its records simply decode again from the cold store."""
        if not self.max_bytes:
            return
        while self._disk_bytes() > self.max_bytes:
            live = [
                i for i, entry in enumerate(self._maps)
                if entry is not None and i != keep
            ]
            if not live:
                return
            victim = min(live, key=lambda i: self._gen_use.get(i, 0))
            mm, table = self._maps[victim]
            self._maps[victim] = None
            for key in table:
                self._index.pop(key, None)
                self._ram.pop(key, None)
            self._ram_bytes_g.set(float(len(self._ram) * self._row_bytes))
            gen = self._gen_dirs.pop(victim, None)
            self._gen_use.pop(victim, None)
            self._evict_c.inc()
            logger.info("slab cache: evicting generation %s (disk tier over capacity)", gen)
            del mm
            if gen:
                shutil.rmtree(gen, ignore_errors=True)
            self._bytes_g.set(float(self._disk_bytes()))

    def close(self):
        """Release memory maps and discard any uncommitted staging dir."""
        if self._staging is not None:
            stage, data_f, _staged = self._staging
            self._staging = None
            try:
                data_f.close()
            except OSError:
                pass
            shutil.rmtree(stage, ignore_errors=True)
        self._maps = []
        self._index = {}
        self._ram.clear()
        self._gen_dirs = {}
        self._gen_use = {}
