"""Adaptive device-feed autotuner: online link probing + dynamic packed windows.

docs/perf.md establishes that on relayed/tunneled TPU runtimes the
host→device link — not the MXU and not the host pipeline — sets the training
ceiling: **~250 ms fixed cost per transfer plus a 6–30 MB/s stream that
swings 3× within minutes**. The packed-window size ``K`` that amortizes that
fixed cost (``compile_train_loop(packed=True)`` +
:func:`~tensorflowonspark_tpu.data.packed_prefetch`) was a constant chosen
offline; this module chooses it *online*, the way tf.data's AUTOTUNE and
Plumber tune input pipelines by measurement instead of configuration —
exactly the right trade when the bottleneck resource shifts at runtime,
which is this link's defining pathology.

The pieces:

* :class:`LinkEstimator` — the two-parameter cost model
  ``T(bytes) = fixed + bytes / bytes_per_sec``. The *fixed* term is
  estimated from timed, fenced micro-probes (a few bytes: stream time is
  negligible, so the probe time IS the fixed cost); the *stream* term from
  timed, fenced production window transfers (observed time minus the
  current fixed estimate). Both update through an EWMA, seeded one-shot by
  the first observation of each kind.
* :class:`FeedAutotuner` — the controller: owns the estimator, a bounded
  bucket set of window sizes (powers of two, default ``{1, 2, 4, 8, 16}``
  so the per-K compiled-loop cache stays small), and the decision rule:
  the smallest bucket whose predicted fixed-cost share
  ``fixed / T(K · batch_bytes)`` is at or below ``overhead_target``.
  Upward moves apply immediately (a latency spike is expensive *now*);
  downward moves wait for ``down_patience`` consecutive recommendations
  (hysteresis against mood flicker, and each downward bucket move risks a
  recompile). Prefetch depth comes along for free: small windows pipeline
  ``depth=2`` ahead, large windows (≥ ``deep_window_k``) hold device
  memory to the double buffer (current + one in flight).
* :func:`autotuned_prefetch` — the drop-in sibling of
  :func:`~tensorflowonspark_tpu.data.loop_prefetch` /
  :func:`~tensorflowonspark_tpu.data.packed_prefetch`: groups host batches
  into device-resident ``[K, B, ...]`` stacks where ``K`` follows the
  controller, windows double-buffered ``depth`` ahead. The delivered batch
  stream is **byte-identical regardless of K** (batches are grouped in
  arrival order and the source tail is flushed by binary decomposition
  into bucket-sized windows, so nothing is dropped and every window size
  is a bucket).
* :class:`~tensorflowonspark_tpu.train.strategy.PackedLoopCache` (train
  layer) — compiles the packed train loop at most once per bucket and
  counts ``feed_recompiles_total``.

Donation safety: windows are retained by the prefetch buffer for
double-buffering, so the packed train loop must NOT donate them — the
``[K,B,H,W,C]`` uint8 input stack aliases no output anyway, and donating it
bought nothing but XLA's "donated buffers were not usable" warning
(BENCH_r05). ``compile_train_loop(packed=True)`` therefore donates only the
train state, and :class:`PackedLoopCache` compiles with that contract.

Every decision is exported through :mod:`~tensorflowonspark_tpu.obs` and
surfaces in ``TFCluster.metrics()``:

==================================  =======================================
metric                              meaning
==================================  =======================================
``feed_link_bytes_per_sec``         current stream-bandwidth estimate
``feed_transfer_fixed_cost_seconds``current per-transfer fixed-cost estimate
``feed_window_size``                the K the controller currently feeds
``feed_recompiles_total``           packed-loop compilations (≤ one/bucket)
``feed_transfer_seconds_total``     fenced wall time spent in transfers
``readahead_depth``                 shard read-ahead depth currently allowed
==================================  =======================================

The ``data.device_link`` chaos site injects a per-transfer delay inside the
timed region (probes and production windows alike), which makes adaptation
deterministically testable: raise the injected latency mid-run and the
controller must move K up; drop it and K must come back down
(tests/test_autotune.py, and the ``--perf-smoke`` leg of run_tests.sh).
"""

import collections
import logging
import time

from tensorflowonspark_tpu import chaos, obs
from tensorflowonspark_tpu.control import Controller, DeltaTicker, EwmaEstimator, StallRule

logger = logging.getLogger(__name__)

#: default bounded bucket set for the packed-window size K: powers of two,
#: so the per-K compiled-loop cache holds at most 5 programs and any source
#: tail decomposes exactly into bucket-sized windows (binary representation)
DEFAULT_BUCKETS = (1, 2, 4, 8, 16)

#: resolvability threshold for the stream term: an observed transfer whose
#: time beyond the fixed-cost estimate is below this says nothing about
#: bandwidth (dividing by ~0 would poison the model with a near-infinite
#: estimate that takes many windows to forget), so such samples only feed
#: the fixed-cost clamp
MIN_STREAM_SECONDS = 1e-6


class LinkEstimator:
    """EWMA estimate of the link cost model ``T(bytes) = fixed + bytes/bw``.

    ``alpha`` is the EWMA weight of the newest observation (0.3 default:
    responsive within a handful of windows, yet one freak sample cannot
    swing a bucket decision by itself). The first observation of each kind
    seeds its parameter directly — the one-shot probe contract.
    """

    def __init__(self, alpha=0.3):
        # one shared EWMA core (validates alpha) blending both model terms
        # under the same weight — the seed-on-first-observation semantics
        # live in control.EwmaEstimator now
        self._blender = EwmaEstimator(alpha=alpha)
        self.alpha = self._blender.alpha
        self.fixed_s = None
        self.bytes_per_sec = None

    @property
    def ready(self):
        """True once both model parameters have at least one observation."""
        return self.fixed_s is not None and self.bytes_per_sec is not None

    def _ewma(self, old, new):
        return self._blender.blend(old, new)

    def observe_fixed(self, seconds):
        """Feed one timed micro-probe (payload small enough that stream time
        is negligible): the sample IS the per-transfer fixed cost."""
        self.fixed_s = self._ewma(self.fixed_s, max(0.0, seconds))

    def observe(self, nbytes, seconds):
        """Feed one timed, fenced production transfer of ``nbytes``.

        The stream share is ``seconds`` minus the current fixed estimate; a
        transfer that beats the fixed estimate also drags ``fixed_s`` down
        (the link cannot have a fixed cost larger than a whole observed
        transfer), so the model recovers even if the probe caught a spike.
        A transfer that fits entirely inside the fixed estimate resolves no
        stream share at all and leaves the bandwidth estimate untouched.
        """
        if nbytes <= 0 or seconds <= 0:
            return
        if self.fixed_s is None:
            self.fixed_s = 0.0
        if seconds < self.fixed_s:
            self.fixed_s = self._ewma(self.fixed_s, seconds)
        stream = seconds - self.fixed_s
        if stream < MIN_STREAM_SECONDS:
            return
        self.bytes_per_sec = self._ewma(self.bytes_per_sec, nbytes / stream)

    def predict(self, nbytes):
        """Predicted transfer seconds for ``nbytes`` under the current model
        (None until :attr:`ready`)."""
        if not self.ready:
            return None
        return self.fixed_s + nbytes / max(self.bytes_per_sec, 1e-9)

    def fixed_share(self, nbytes):
        """Fraction of a predicted ``nbytes`` transfer spent on the fixed
        cost — the quantity the window size K exists to amortize."""
        total = self.predict(nbytes)
        if not total:
            return 0.0
        return self.fixed_s / total


class AutotunedWindow:
    """One device-resident packed window: ``data`` is the ``[k, B, ...]``
    pytree (placed via :func:`~tensorflowonspark_tpu.data.packed_place`),
    ``k`` the bucket it was built for — feed it to
    :meth:`PackedLoopCache.run <tensorflowonspark_tpu.train.strategy.PackedLoopCache.run>`."""

    __slots__ = ("data", "k")

    def __init__(self, data, k):
        self.data = data
        self.k = k


class FeedAutotuner:
    """Online controller for the packed-window size K and prefetch depth.

    Decision rule: the smallest bucket whose predicted fixed-cost share
    ``fixed / (fixed + K·batch_bytes/bw)`` is ≤ ``overhead_target``
    (default 0.1 — at the measured ~250 ms fixed cost and ~20 MB/s this
    lands on K=8, the value BENCH_FUSED converged to by hand). Upward
    moves apply immediately; downward moves need ``down_patience``
    consecutive lower recommendations. Every ``reprobe_every``-th window a
    fenced micro-probe refreshes the fixed-cost estimate, so a mood change
    is seen even while the window size (and thus the bytes term) is
    steady.

    ``clock`` is injectable for deterministic tests (defaults to
    ``time.perf_counter``); the estimator itself is pure arithmetic and can
    be driven directly through :meth:`note_fixed_probe` /
    :meth:`note_transfer`.
    """

    def __init__(
        self,
        buckets=DEFAULT_BUCKETS,
        overhead_target=0.1,
        down_patience=2,
        reprobe_every=4,
        deep_window_k=8,
        alpha=0.3,
        clock=None,
    ):
        if not buckets:
            raise ValueError("buckets must be non-empty")
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        if any(b < 1 for b in self.buckets):
            raise ValueError("buckets must be >= 1")
        if not 0.0 < overhead_target < 1.0:
            raise ValueError("overhead_target must be in (0, 1)")
        self.overhead_target = overhead_target
        self.down_patience = max(1, int(down_patience))
        self.reprobe_every = max(0, int(reprobe_every))
        self.deep_window_k = int(deep_window_k)
        self.estimator = LinkEstimator(alpha=alpha)
        self._clock = clock or time.perf_counter
        self._k = None
        # the shared hysteresis core: up one bucket immediately, down one
        # bucket after down_patience consecutive lower recommendations
        self._ctl = Controller(
            levels=self.buckets, down_patience=self.down_patience,
            name="feed_window",
        )
        self._windows_placed = 0
        # instruments created eagerly so the five feed_* metrics exist in
        # every snapshot that saw a tuner, even before the first transfer
        self._bw_g = obs.gauge(
            "feed_link_bytes_per_sec",
            help="autotuner estimate of the host->device stream bandwidth",
        )
        self._fixed_g = obs.gauge(
            "feed_transfer_fixed_cost_seconds",
            help="autotuner estimate of the per-transfer fixed cost",
        )
        self._k_g = obs.gauge(
            "feed_window_size", help="packed-window size K currently fed"
        )
        obs.counter(
            "feed_recompiles_total",
            help="packed train-loop compilations (bounded by the bucket set)",
        )
        self._transfer_c = obs.counter(
            "feed_transfer_seconds_total",
            help="fenced wall seconds spent in host->device window transfers",
        )

    # -- estimator feeding (pure; used by the timed paths below) ---------------

    def note_fixed_probe(self, seconds):
        """Record one fixed-cost probe sample and publish the estimate."""
        self.estimator.observe_fixed(seconds)
        self._fixed_g.set(self.estimator.fixed_s)

    def note_transfer(self, nbytes, seconds):
        """Record one production window transfer and publish the estimates."""
        self.estimator.observe(nbytes, seconds)
        self._transfer_c.inc(seconds)
        if self.estimator.bytes_per_sec is not None:
            self._bw_g.set(self.estimator.bytes_per_sec)
        if self.estimator.fixed_s is not None:
            self._fixed_g.set(self.estimator.fixed_s)

    # -- the decision -----------------------------------------------------------

    def recommend(self, batch_bytes):
        """The bucket the model currently argues for (no hysteresis)."""
        if not self.estimator.ready or batch_bytes <= 0:
            return self.buckets[0]
        for k in self.buckets:
            if self.estimator.fixed_share(k * batch_bytes) <= self.overhead_target:
                return k
        return self.buckets[-1]

    def decide(self, batch_bytes):
        """Select ``(k, depth)`` for the NEXT window and publish the choice.

        The first call jumps straight to the recommendation (the one-shot
        probe seeded the model; there is no history to be cautious about);
        after that, K moves at most one bucket per call — up immediately,
        down only after ``down_patience`` consecutive lower
        recommendations.
        """
        rec = self.recommend(batch_bytes)
        if self._k is None:
            self._k = rec
        else:
            self._k = self._ctl.toward(self._k, rec)
        self._k_g.set(self._k)
        return self._k, self.depth(self._k)

    def depth(self, k):
        """Windows kept in flight beyond the one handed out: 2 for small
        windows (cheap, deep pipeline), 1 from ``deep_window_k`` up (the
        double buffer — current window training, one window in transfer —
        bounds device memory at ~2 windows like the static packed path)."""
        return 1 if k >= self.deep_window_k else 2

    # -- timed, fenced placement ------------------------------------------------

    @staticmethod
    def _fence(tree):
        """One-element readback proving the transfer landed (slicing on
        device first, so the fence never ships the array back — the same
        fencing bench.py uses; ``block_until_ready`` can return at the
        relay ack)."""
        import jax
        import numpy as np

        leaf = jax.tree.leaves(tree)[0]
        _ = np.asarray(jax.device_get(leaf[(0,) * leaf.ndim]))

    def _fire_link_chaos(self):
        if chaos.active:
            spec = chaos.fire("data.device_link")
            if spec is not None:
                time.sleep(spec.get("delay_s", 0.05))

    def probe_fixed(self, strategy):
        """One fenced micro-transfer (8 bytes: pure fixed cost) through the
        same device path as production windows; feeds the fixed estimate."""
        import jax
        import numpy as np

        del strategy  # placement target is any addressable device
        payload = np.zeros(8, np.uint8)
        t0 = self._clock()
        self._fire_link_chaos()
        self._fence(jax.device_put(payload))
        self.note_fixed_probe(self._clock() - t0)

    def place(self, window, strategy):
        """Stack ``window`` (list of host batch pytrees) into one device
        transfer via :func:`~tensorflowonspark_tpu.data.packed_place`,
        timed and fenced, feeding the estimator. Returns an
        :class:`AutotunedWindow`."""
        import jax

        from tensorflowonspark_tpu.data.loader import packed_place

        if self.reprobe_every and self._windows_placed % self.reprobe_every == 0:
            self.probe_fixed(strategy)
        self._windows_placed += 1
        nbytes = sum(
            leaf.nbytes for batch in window for leaf in jax.tree.leaves(batch)
        )
        # the h2d phase of the step timeline: the same fenced interval that
        # feeds the estimator becomes a span for the merged trace
        with obs.span("h2d_transfer", nbytes=nbytes, k=len(window)):
            t0 = self._clock()
            self._fire_link_chaos()
            placed = packed_place(window, strategy)
            self._fence(placed)
            self.note_transfer(nbytes, self._clock() - t0)
        return AutotunedWindow(placed, len(window))


#: default upper bound for the stall-steered shard read-ahead depth
#: (``ImagePipeline(readahead="auto")``): deep enough to hide a slow remote
#: store behind decode, small enough that chunk queues stay bounded
DEFAULT_MAX_READAHEAD = 8


class ReadaheadAutotuner:
    """Self-sizing controller for the shard read-ahead depth.

    The third member of the autotuner family: :class:`FeedAutotuner` sizes
    the packed device window, :class:`~tensorflowonspark_tpu.data.decode_plane.DecodeAutotuner`
    sizes the decode worker pool, and this one sizes how many shards the
    reader executor streams ahead of the parse stage — the knob that
    matters when the stall classification says **io_bound** (remote stores:
    gcsfuse, NFS, object stores with high per-read latency).

    Decision rule per interval of ``check_every`` seconds, from the deltas
    of the producer/consumer stall counters (the same accounting
    ``bench.classify_stalls`` reads):

    * consumer starved for more than ``starve_ratio`` of the interval AND
      shard IO dominated the parse stage (``read_delta >= parse_delta`` —
      the interval was io_bound, not decode_bound) → **deepen read-ahead
      one shard immediately**. Starvation whose cause is decode is left to
      the decode autotuner; deepening read-ahead cannot fix it.
    * consumer essentially never starved (wait share below ``idle_ratio``)
      → **shallow by one after ``down_patience`` consecutive idle
      intervals** (hysteresis), releasing reader threads and chunk-queue
      memory the pipeline demonstrably does not need.

    Bounds ``[min_depth, max_depth]``. Counter reads and the clock are
    injectable so the decision core is a pure function in tests, exactly
    like the decode autotuner. Publishes the chosen depth on the
    ``readahead_depth`` gauge.
    """

    def __init__(
        self,
        min_depth=1,
        max_depth=DEFAULT_MAX_READAHEAD,
        starve_ratio=0.05,
        idle_ratio=0.01,
        down_patience=2,
        check_every=2.0,
        clock=None,
        read_counters=None,
        gauge=None,
    ):
        self.min_depth = max(1, int(min_depth))
        self.max_depth = int(max_depth)
        if self.max_depth < self.min_depth:
            raise ValueError("max_depth must be >= min_depth")
        self.starve_ratio = float(starve_ratio)
        self.idle_ratio = float(idle_ratio)
        self.down_patience = max(1, int(down_patience))
        self.check_every = float(check_every)
        # the shared control core: starvation verdict, up-fast/down-slow
        # hysteresis inside the depth bounds, and the clocked delta gate
        self._rule = StallRule(
            starve_ratio=self.starve_ratio, idle_ratio=self.idle_ratio
        )
        self._ctl = Controller(
            lo=self.min_depth, hi=self.max_depth,
            down_patience=self.down_patience, name="readahead",
        )
        self._ticker = DeltaTicker(
            self.check_every, read_counters or self._read_obs, clock=clock
        )
        # the depth gauge is injectable so other read-ahead-shaped planes
        # (the store prefetch stager) can reuse the whole controller while
        # publishing on their own metric name
        self._depth_g = gauge if gauge is not None else obs.gauge(
            "readahead_depth", help="shard read-ahead depth currently allowed"
        )

    @staticmethod
    def _read_obs():
        counters = obs.snapshot()["counters"]

        def _c(counter_name):
            return counters.get(counter_name, {}).get("value", 0.0)

        return (
            _c("data_producer_read_seconds_total"),
            _c("data_producer_parse_seconds_total"),
            _c("data_consumer_wait_seconds_total"),
        )

    def publish(self, depth):
        """Publish ``depth`` on the ``readahead_depth`` gauge (the loader
        calls this once at startup so the gauge exists before the first
        interval elapses)."""
        self._depth_g.set(int(depth))

    def decide(self, depth, read_delta, parse_delta, wait_delta, elapsed):
        """Pure decision: the read-ahead depth argued for by one interval's
        counter deltas (no clock, no obs — the unit-testable core)."""
        if elapsed <= 0:
            return depth
        want = self._rule.want(wait_delta / elapsed, read_delta >= parse_delta)
        return self._ctl.step(depth, want)

    def tick(self, depth):
        """Clocked wrapper for :meth:`decide`: reads the counters at most
        every ``check_every`` seconds; returns the new target depth, or
        None when the interval has not elapsed yet."""
        out = self._ticker.tick()
        if out is None:
            return None
        (read_delta, parse_delta, wait_delta), elapsed = out
        target = self.decide(depth, read_delta, parse_delta, wait_delta, elapsed)
        if target != depth:
            self._depth_g.set(int(target))
        return target


def batch_nbytes(batch):
    """Host-side bytes of one batch pytree (the controller's size unit)."""
    import jax

    return sum(leaf.nbytes for leaf in jax.tree.leaves(batch))


def bucket_decomposition(n, buckets):
    """Greedy decomposition of ``n`` batches into bucket-sized windows,
    largest first — with power-of-two buckets down to 1 this is the binary
    representation of ``n``, so the source tail is delivered exactly and
    every emitted window size has (or will have) a cached compiled loop.
    Any residue smaller than the smallest bucket is dropped (impossible
    when 1 is a bucket)."""
    sizes = []
    for b in sorted(buckets, reverse=True):
        while n >= b:
            sizes.append(b)
            n -= b
    return sizes


def autotuned_prefetch(batches, strategy, tuner=None, **tuner_kw):
    """Group host batches into device-resident packed windows whose size K
    follows the :class:`FeedAutotuner` — the adaptive sibling of
    :func:`~tensorflowonspark_tpu.data.packed_prefetch`.

    Yields :class:`AutotunedWindow` objects (``.data`` = ``[k, B, ...]``
    device pytree, ``.k`` = its bucket); run them with
    :class:`~tensorflowonspark_tpu.train.strategy.PackedLoopCache`, which
    compiles the packed loop at most once per bucket::

        tuner = FeedAutotuner()
        cache = PackedLoopCache(strategy, loss_fn, optimizer, mutable=True)
        for window in autotuned_prefetch(pipe, strategy, tuner=tuner):
            state, metrics = cache.run(state, window)

    The delivered batch stream is byte-identical to the K=1 reference for
    any controller trajectory: batches are grouped strictly in arrival
    order, and the source tail is flushed through
    :func:`bucket_decomposition` instead of being dropped. Windows are
    double-buffered ``tuner.depth(k)`` ahead; the handed-out window stays
    referenced by the consumer while the next transfers — which is exactly
    why the packed loop donates only state (see module docstring).

    Extra keyword arguments construct the default tuner
    (``autotuned_prefetch(pipe, strategy, overhead_target=0.2)``).
    """
    if tuner is None:
        tuner = FeedAutotuner(**tuner_kw)
    it = iter(batches)
    buf = collections.deque()
    pending = []  # host batches drawn but not yet placed
    exhausted = False

    def _pull():
        nonlocal exhausted
        try:
            pending.append(next(it))
            return True
        except StopIteration:
            exhausted = True
            return False

    depth = 1
    while True:
        while not exhausted and len(buf) <= depth:
            if not pending and not _pull():
                break
            k, depth = tuner.decide(batch_nbytes(pending[0]))
            while len(pending) < k and _pull():
                pass
            if len(pending) < k:
                break  # tail: flushed below by bucket decomposition
            buf.append(tuner.place(pending[:k], strategy))
            del pending[:k]
        if exhausted and pending:
            for k in bucket_decomposition(len(pending), tuner.buckets):
                buf.append(tuner.place(pending[:k], strategy))
                del pending[:k]
            pending = []
        if not buf:
            return
        yield buf.popleft()
