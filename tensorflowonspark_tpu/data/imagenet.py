"""ImageNet-style record parsing and augmentation (PIL + numpy).

Capability-parity with the reference's pipeline
(/root/reference/examples/resnet/imagenet_preprocessing.py: record schema
:156-223, distorted-bbox crop+flip :326-373, aspect-preserving resize +
central crop for eval :375-501, channel-mean subtraction :397-430), built
host-side without TensorFlow: decode and resize ride PIL's C codecs on the
executor/TPU-host CPUs, the TPU never sees a dynamic shape.

Record schema (the de-facto ImageNet TFRecord layout the reference parses):
``image/encoded`` JPEG bytes, ``image/class/label`` int64.
"""

import io
import logging

import numpy as np

from tensorflowonspark_tpu import tfrecord

logger = logging.getLogger(__name__)

IMAGE_SIZE = 224
#: standard per-channel RGB means (same constants the reference subtracts,
#: imagenet_preprocessing.py:54-57)
CHANNEL_MEANS = np.array([123.68, 116.78, 103.94], np.float32)
#: eval-time aspect-preserving resize target for the short side
RESIZE_MIN = 256

NUM_CLASSES = 1000
NUM_IMAGES = {"train": 1281167, "validation": 50000}


def _decode(image_bytes):
    from PIL import Image

    img = Image.open(io.BytesIO(image_bytes))
    if img.mode != "RGB":
        img = img.convert("RGB")
    return img


def _random_crop_box(width, height, rng, area_range=(0.05, 1.0), aspect_range=(0.75, 1.33), attempts=10):
    """Inception-style distorted bounding box: sample a crop whose area and
    aspect ratio fall in the given ranges; fall back to a central square
    (the reference's sample_distorted_bounding_box fallback,
    imagenet_preprocessing.py:326-373)."""
    area = width * height
    for _ in range(attempts):
        target_area = rng.uniform(*area_range) * area
        aspect = rng.uniform(*aspect_range)
        w = int(round(np.sqrt(target_area * aspect)))
        h = int(round(np.sqrt(target_area / aspect)))
        if w <= width and h <= height and w > 0 and h > 0:
            x = rng.integers(0, width - w + 1)
            y = rng.integers(0, height - h + 1)
            return x, y, w, h
    side = min(width, height)
    return (width - side) // 2, (height - side) // 2, side, side


def preprocess_train(image_bytes, rng, image_size=IMAGE_SIZE, raw_uint8=False):
    """JPEG bytes → float32 HWC: distorted crop, resize, random flip, mean
    subtract. ``raw_uint8=True`` skips the mean subtraction and returns the
    uint8 pixels — quarter the feed bytes; normalize on device with
    :func:`device_normalize`."""
    from PIL import Image

    img = _decode(image_bytes)
    x, y, w, h = _random_crop_box(img.width, img.height, rng)
    img = img.resize((image_size, image_size), Image.BILINEAR, box=(x, y, x + w, y + h))
    arr = np.asarray(img)
    if rng.random() < 0.5:
        arr = arr[:, ::-1]
    if raw_uint8:
        return np.ascontiguousarray(arr)
    return arr.astype(np.float32) - CHANNEL_MEANS


def preprocess_eval(image_bytes, image_size=IMAGE_SIZE, resize_min=RESIZE_MIN, raw_uint8=False):
    """JPEG bytes → float32 HWC: aspect-preserving resize, central crop, mean
    subtract (imagenet_preprocessing.py:375-501)."""
    from PIL import Image

    img = _decode(image_bytes)
    scale = resize_min / min(img.width, img.height)
    nw, nh = int(round(img.width * scale)), int(round(img.height * scale))
    img = img.resize((nw, nh), Image.BILINEAR)
    x = (nw - image_size) // 2
    y = (nh - image_size) // 2
    arr = np.asarray(img.crop((x, y, x + image_size, y + image_size)))
    if raw_uint8:
        return arr
    return arr.astype(np.float32) - CHANNEL_MEANS


def device_normalize(images):
    """Device-side twin of the host mean subtraction: uint8 ``[B,H,W,C]`` →
    float32 minus :data:`CHANNEL_MEANS`. XLA fuses this into the first conv,
    so shipping uint8 over the host→device link (4× fewer bytes than f32,
    the usual bottleneck on a tunneled runtime) costs no extra HBM pass."""
    import jax.numpy as jnp

    return images.astype(jnp.float32) - jnp.asarray(CHANNEL_MEANS)


def make_parse_fn(is_training, image_size=IMAGE_SIZE, label_offset=0, seed=0, raw_uint8=False):
    """record bytes → (image f32 HWC, label int32).

    ``label_offset`` handles 1-based ImageNet labels (pass -1 to map 1..1000
    onto 0..999). The augmentation rng is keyed to (seed, crc32 of the record
    bytes) so a seeded run applies identical crops/flips to each image no
    matter how the thread pool schedules the parses. ``raw_uint8=True``
    keeps images uint8 and un-normalized for the slim feed path (pair with
    :func:`device_normalize` on device).

    Decode-plane contract: the returned closure must work after a fork —
    it captures only plain values (no locks, threads or open handles) and
    lives at module level, so ``ImagePipeline(decode_workers=N)`` can run
    it inside worker processes. Keep custom ``parse_fn`` replacements to
    the same shape: fork-inheritable state only, deterministic per record
    bytes (the record-keyed rng above), since a chaos-killed worker's slot
    may be decoded twice and both decodes must write identical pixels.
    """
    import zlib

    def parse(record):
        feats = tfrecord.decode_example(record)
        image_bytes = feats["image/encoded"][1][0]
        label = int(feats["image/class/label"][1][0]) + label_offset
        if is_training:
            rng = np.random.default_rng((seed << 32) ^ zlib.crc32(record))
            image = preprocess_train(image_bytes, rng, image_size, raw_uint8=raw_uint8)
        else:
            image = preprocess_eval(image_bytes, image_size, raw_uint8=raw_uint8)
        return image, label

    def into(record, out):
        """record bytes → pixels written directly into ``out`` (a uint8
        ``(image_size, image_size, 3)`` view of a shared-memory slab slot).

        The native fast path: one C call decodes the JPEG and lands the
        Pillow-exact crop/resize/flip in ``out`` — no PIL, no intermediate
        copy. The augmentation rng is keyed and *drawn* in exactly
        :func:`preprocess_train`'s order (crop-box draws, then the flip
        draw), so native and PIL modes produce byte-identical streams.
        Returns ``(label, used_native)``; any native failure — library
        absent, unsupported coding, corrupt stream — falls back to the full
        PIL parse, so a record is charged against ``max_bad_records``
        exactly when PIL itself cannot decode it.
        """
        from tensorflowonspark_tpu import native_io

        feats = tfrecord.decode_example(record)
        image_bytes = feats["image/encoded"][1][0]
        label = int(feats["image/class/label"][1][0]) + label_offset
        if native_io.jpg_available():
            try:
                width, height = native_io.jpg_info(image_bytes)
                if is_training:
                    rng = np.random.default_rng((seed << 32) ^ zlib.crc32(record))
                    x, y, w, h = _random_crop_box(width, height, rng)
                    flip = rng.random() < 0.5
                    native_io.jpg_decode_window(
                        image_bytes, out, (x, y, x + w, y + h),
                        (image_size, image_size), flip=flip)
                else:
                    scale = RESIZE_MIN / min(width, height)
                    nw, nh = int(round(width * scale)), int(round(height * scale))
                    ox, oy = (nw - image_size) // 2, (nh - image_size) // 2
                    if ox < 0 or oy < 0:
                        raise native_io.JpegError("image smaller than crop")
                    native_io.jpg_decode_window(
                        image_bytes, out, (0, 0, width, height), (nw, nh),
                        window_origin=(ox, oy))
                return label, True
            except (native_io.JpegError, RuntimeError):
                pass  # PIL below is both oracle and fallback
        image, label = parse(record)
        out[...] = image
        return label, False

    if raw_uint8:
        # the native into-slab path produces uint8 pixels only; float32
        # parses (mean-subtracted) keep the plain PIL closure
        parse.into = into
    #: decode-parameter fingerprint: keys the cross-epoch decoded-slab cache
    #: (same bytes + same key ⇒ same pixels, in every decode mode)
    parse.cache_key = "imagenet:v1:{}:{}:{}:{}:{}".format(
        "train" if is_training else "eval", image_size, label_offset, seed,
        int(bool(raw_uint8)))
    return parse


def encode_example(image_array, label, quality=90):
    """uint8 HWC array + label → serialized Example with JPEG bytes (for
    dataset prep and tests; the write-side twin of :func:`make_parse_fn`)."""
    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(np.asarray(image_array, np.uint8)).save(buf, "JPEG", quality=quality)
    return tfrecord.encode_example(
        {"image/encoded": [buf.getvalue()], "image/class/label": [int(label)]}
    )
