"""Sequence-packed tokenized-text input pipeline (the text plane).

:class:`TextPipeline` is :class:`~tensorflowonspark_tpu.data.loader.
ImagePipeline`'s contract transplanted onto variable-length text: stages
1+2 (shard read-ahead over the chunked-read ABI, bounded shuffle, raw
cache, ``max_bad_records``, the ``data.shard_read`` /
``data.readahead_stall`` chaos seams) are inherited verbatim, and stage 3
replaces fixed-geometry batch assembly with **sequence packing**: records
are tokenized and first-fit-decreasing bin-packed into fixed ``[B, L]``
int32 buffers (T5-style packing, Raffel et al. 2020), so the accelerator
sees one static shape regardless of the length distribution.

Each emitted batch is ``{"tokens", "segment_ids", "positions"}``, all
``int32 [B, L]`` views of one ``[B, 3, L]`` buffer:

- ``tokens`` — packed ids, 0 (PAD) in the slack;
- ``segment_ids`` — 0 for padding, 1..n per packed sequence, the
  cross-attention fence :mod:`~tensorflowonspark_tpu.models.transformer`
  turns into a block-diagonal attention mask (flash and ring included);
- ``positions`` — restart at 0 per segment so rotary phases never leak
  across pack neighbours.

Packing runs producer-side as a *plan* (lengths only, via the tokenizer's
cheap validating :meth:`~tensorflowonspark_tpu.data.tokenizer.Tokenizer.
token_length`), then the plan's cache misses are tokenized either on the
in-process thread pool or — with ``pack_workers > 0`` — in the decode
plane's forked workers writing straight into shared-memory slabs under the
slot-lease protocol (:mod:`~tensorflowonspark_tpu.data.decode_plane`; the
payload is the pack plan, one lease per packed row). Because the plan, the
budget accounting, and the zeroing all happen in the producer thread, the
delivered ``[B, L]`` stream is **byte-identical** across ``pack_workers``
settings, readahead/chunk knobs, and packed-slab cache states (cold, warm,
off) — the same determinism contract the image plane enforces.

The packed-slab cache (:mod:`~tensorflowonspark_tpu.data.slab_cache`) is
reused with per-*sequence* geometry ``(L,) int32``: rows are keyed by
record crc32 under the tokenizer-config fingerprint (kind, vocab, field,
``L`` — truncation depends on the bin capacity), the row label is the
effective token count, and epoch >= 2 (or a warm relaunch) serves token
ids from a memory map instead of re-tokenizing.

Chaos sites native to this stage: ``data.tokenize_error`` poisons a
record's bytes producer-side so the tokenizer rejects it (charged against
``max_bad_records``, identically in every pack mode) and
``data.pack_stall`` injects a delay inside the timed pack region, charged
to parse time so the stall classifier reports the run input-bound.
"""

import logging
import queue
import threading
import time
import zlib

import numpy as np

from tensorflowonspark_tpu import chaos, obs
from tensorflowonspark_tpu.data import decode_plane, slab_cache
from tensorflowonspark_tpu.data import tokenizer as tokenizer_mod
from tensorflowonspark_tpu.data.loader import ImagePipeline, _Stopped

logger = logging.getLogger(__name__)

__all__ = ["TextPipeline", "pack_bins"]

#: invalid UTF-8 the ``data.tokenize_error`` site swaps in for a record
_CHAOS_BAD_RECORD = b"\xff\xfe chaos-malformed-text-record"


def pack_bins(lengths, capacity):
    """First-fit-decreasing bin packing of ``lengths`` into bins of
    ``capacity``. Returns bins in creation order, each a list of indices
    into ``lengths`` in placement (descending-length, arrival-stable)
    order. Pure and deterministic — the packing *plan* is computed once,
    producer-side, and every pack mode executes the same plan.

    FFD's classic guarantee (11/9 OPT + 6/9, Dósa 2007) is what bounds the
    pad waste the efficiency tests assert on adversarial distributions.
    """
    order = sorted(range(len(lengths)), key=lambda i: (-lengths[i], i))
    bins = []  # [used, [idx, ...]]
    for i in order:
        n = lengths[i]
        for b in bins:
            if b[0] + n <= capacity:
                b[0] += n
                b[1].append(i)
                break
        else:
            bins.append([n, [i]])
    return [b[1] for b in bins]


class TextPipeline(ImagePipeline):
    """files -> shuffled, tokenized, sequence-packed batches of
    ``{"tokens", "segment_ids", "positions"}`` (all ``int32 [B, L]``).

    Mirrors :class:`~tensorflowonspark_tpu.data.loader.ImagePipeline`'s
    constructor and determinism contract; the differences:

    - ``tokenizer`` + ``seq_len`` replace ``parse_fn`` (the pack-plane
      parse fn is built internally via :func:`~tensorflowonspark_tpu.data.
      tokenizer.make_pack_fn`);
    - ``pack_workers`` is the text plane's ``decode_workers`` (0 = thread
      pool, ``"auto"``/N = forked slab workers);
    - ``pack_ahead`` sizes the packing window: records accumulate until
      roughly ``pack_ahead * B * L`` tokens are pending, then the window
      is FFD-packed — deeper windows pack tighter, at more producer
      buffering (leftover part-full bins carry their sequences into the
      next window, so nothing is dropped mid-stream);
    - ``cache="decoded"`` and ``recycle_buffers`` are not supported (the
      decoded-pair cache is image-geometry machinery; packed rows already
      have the packed-slab cache).

    ``max_bad_records`` budgets records the tokenizer rejects (malformed
    UTF-8, empty text, missing Example feature) exactly like undecodable
    images: skipped and counted until the budget is spent, then the
    :class:`~tensorflowonspark_tpu.data.tokenizer.TokenizeError` surfaces
    to the consumer. Sequences longer than ``L`` are not errors — they are
    truncated (terminal EOS kept) and counted in
    ``text_sequences_truncated_total``.
    """

    def __init__(
        self,
        files,
        tokenizer,
        seq_len,
        batch_size,
        shuffle=True,
        seed=0,
        num_threads=None,
        epochs=1,
        prefetch_batches=2,
        verify_crc=False,
        drop_remainder=True,
        max_bad_records=0,
        readahead=None,
        chunk_records=None,
        shuffle_buffer=4096,
        cache=None,
        pack_workers=None,
        pack_ahead=2.0,
        slab_cache_dir=None,
        store=None,
        prefetch=None,
    ):
        if cache == "decoded":
            raise ValueError(
                "cache='decoded' is image-plane machinery; the text plane's "
                "cross-epoch cache is the packed-slab cache (slab_cache_dir)"
            )
        seq_len = int(seq_len)
        if seq_len < 4:
            raise ValueError("seq_len must be >= 4 (BOS + body + EOS)")
        super().__init__(
            files,
            tokenizer_mod.make_pack_fn(tokenizer, seq_len),
            batch_size,
            shuffle=shuffle,
            seed=seed,
            num_threads=num_threads,
            epochs=epochs,
            prefetch_batches=prefetch_batches,
            verify_crc=verify_crc,
            drop_remainder=drop_remainder,
            max_bad_records=max_bad_records,
            readahead=readahead,
            chunk_records=chunk_records,
            shuffle_buffer=shuffle_buffer,
            cache=cache,
            decode_workers=pack_workers,
            slab_cache_dir=slab_cache_dir,
            store=store,
            prefetch=prefetch,
        )
        self.tokenizer = tokenizer
        self.seq_len = seq_len
        self.pack_ahead = float(pack_ahead)

    # -- stage 3: pack assembly ---------------------------------------------

    def __iter__(self):
        from concurrent.futures import ThreadPoolExecutor

        B, L = self.batch_size, self.seq_len
        out_q = queue.Queue(maxsize=max(1, self.prefetch_batches))
        stop = threading.Event()  # consumer departed
        abort = threading.Event()  # producer died: unblocks reader threads
        _END = object()
        free_q = queue.Queue()  # recycled slab pairs (process mode only)
        pool_cap = max(1, self.prefetch_batches) + 2
        alloc_count = [0]

        produced_c = obs.counter(
            "data_batches_produced_total", help="batches parsed by the input pipeline"
        )
        consumed_c = obs.counter(
            "data_batches_consumed_total", help="batches handed to the training loop"
        )
        depth_g = obs.gauge(
            "data_prefetch_depth", help="parsed batches waiting in the prefetch queue"
        )
        skipped_c = obs.counter(
            "data_records_skipped_total",
            help="undecodable records skipped within the max_bad_records budget",
        )
        read_c = obs.counter(
            "data_producer_read_seconds_total",
            help="seconds spent in shard IO (open + chunk reads)",
        )
        parse_c = obs.counter(
            "data_producer_parse_seconds_total",
            help="seconds the parse pool spent decoding records into batch buffers",
        )
        emit_c = obs.counter(
            "data_producer_emit_seconds_total",
            help="seconds the producer blocked on a full prefetch queue "
            "(backpressure: the consumer is the bottleneck)",
        )
        wait_c = obs.counter(
            "data_consumer_wait_seconds_total",
            help="seconds the consumer waited on an empty prefetch queue "
            "(starvation: the input pipeline is the bottleneck)",
        )
        tok_err_c = obs.counter(
            "text_tokenize_errors_total",
            help="records the tokenizer rejected (charged to max_bad_records)",
        )
        trunc_c = obs.counter(
            "text_sequences_truncated_total",
            help="sequences longer than seq_len cut down to the bin capacity",
        )
        tokens_c = obs.counter(
            "text_tokens_packed_total", help="real (non-pad) tokens emitted in packed batches"
        )
        seqs_c = obs.counter(
            "text_sequences_packed_total", help="sequences emitted inside packed batches"
        )
        stall_c = obs.counter(
            "text_pack_stall_seconds_total",
            help="seconds the packer stalled inside the pack stage "
            "(slab-pool waits and injected data.pack_stall faults)",
        )
        eff_g = obs.gauge(
            "text_pack_efficiency",
            help="cumulative real-token fraction of emitted [B, L] slots",
        )
        pad_g = obs.gauge(
            "text_pad_fraction", help="cumulative pad fraction of emitted [B, L] slots"
        )

        # the pack plane forks its workers HERE, before any pipeline thread
        # exists (fork-with-threads is the one mp lifecycle hazard)
        plane = None
        workers, _auto = decode_plane.resolve_workers(self.decode_workers)
        if workers > 0:
            if decode_plane.available():
                plane = decode_plane.DecodePlane(self.parse_fn, workers)
            else:
                logger.warning(
                    "pack_workers=%s requested but fork/shared_memory is "
                    "unavailable here; falling back to the thread pack pool",
                    workers,
                )

        reader_pool = (
            ThreadPoolExecutor(self.readahead, thread_name_prefix="tos-text-reader")
            if self.readahead > 0
            else None
        )

        # packed-row geometry is static — unlike images no bootstrap record
        # is needed to size the cache or the buffers
        cache_box = [None]
        if self.slab_cache_dir is not None:
            try:
                cache_box[0] = slab_cache.SlabCache(
                    self.slab_cache_dir, self.parse_fn.cache_key, (L,), np.int32
                )
            except Exception as e:
                logger.warning("packed-slab cache disabled: %s", e)
        into = self.parse_fn.into

        def _final_put(item):
            # never block forever on a departed consumer: its finally drains
            # the queue and sets stop, so either the put lands or stop shows
            while not stop.is_set():
                try:
                    out_q.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        def _acquire():
            # slabs are pooled (workers hold attachments by name); thread
            # mode emits fresh heap buffers, nothing to recycle
            if plane is None:
                return np.zeros((B, 3, L), np.int32), np.empty((B,), np.int32)
            try:
                pair = free_q.get_nowait()
            except queue.Empty:
                pair = None
            if pair is None:
                if alloc_count[0] < pool_cap:
                    alloc_count[0] += 1
                    pair = plane.new_slab(B, (3, L), np.int32)
                else:
                    # pool exhausted: timed-get until a slab returns or the
                    # consumer departs — this is a genuine pack stall
                    t0 = time.monotonic()
                    while True:
                        if stop.is_set():
                            raise _Stopped()
                        try:
                            pair = free_q.get(timeout=0.1)
                            break
                        except queue.Empty:
                            continue
                    waited = time.monotonic() - t0
                    plane.note_slab_wait(waited)
                    stall_c.inc(waited)
            pair[0][...] = 0  # zero tokens/segments/positions: pad baseline
            return pair

        def producer():
            bad = []  # tokenize errors absorbed so far (within budget)
            window = []  # (record bytes, eff_len) awaiting packing
            window_tokens = 0
            # at least one batch's worth of tokens per window: a mid-stream
            # flush then always yields >= B bins (ceil(tokens/L) >= B) and
            # the carry can never exceed the window it came from
            window_cap = max(B * L, int(self.pack_ahead * B * L))
            emitted_slots = [0]
            emitted_tokens = [0]

            def _absorb(err):
                if len(bad) >= self.max_bad_records:
                    raise err
                bad.append(err)
                skipped_c.inc()
                tok_err_c.inc()
                logger.warning("skipping untokenizable record: %s", err)

            def _emit(batch):
                if chaos.active:
                    chaos.delay("data.producer_delay")
                t0 = time.monotonic()
                while True:
                    try:
                        out_q.put(batch, timeout=0.5)
                        break
                    except queue.Full:
                        if stop.is_set():
                            raise _Stopped()
                emit_c.inc(time.monotonic() - t0)
                produced_c.inc()
                depth_g.set(out_q.qsize())

            def _cache_hit(rec, eff_len):
                """Serve a sequence's token ids from the packed-slab cache:
                returns (ids, None) on a hit, (None, crc) on a miss to be
                staged after tokenizing, (None, None) when the cache is
                off."""
                cache = cache_box[0]
                if cache is None:
                    return None, None
                crc = zlib.crc32(rec)
                hit = cache.lookup(crc)
                if hit is None:
                    return None, crc
                row, lbl = hit
                if int(lbl) != eff_len:  # stale geometry guard; re-tokenize
                    return None, crc
                return row[:eff_len], None

            def _fill_and_emit(bins):
                """Assemble one batch from packed bins: zeroed buffer, cache
                hits written parent-side, misses tokenized by the pack
                plane (one slot lease per row, the plan as payload) or the
                thread pool, fresh rows staged back into the cache."""
                rows = len(bins)
                buf, labels = _acquire()
                t0 = time.monotonic()
                if chaos.active:
                    tc = time.monotonic()
                    if chaos.delay("data.pack_stall"):
                        stall_c.inc(time.monotonic() - tc)
                plans = []  # (slot, plan tuple) for rows with cache misses
                puts = []  # (crc, slot, offset, eff_len) staged after the round
                for slot, entries in enumerate(bins):
                    offset = 0
                    plan = []
                    for seg_id, (rec, eff_len) in enumerate(entries, start=1):
                        ids, crc = _cache_hit(rec, eff_len)
                        if ids is not None:
                            tokenizer_mod.write_segment(buf[slot], offset, seg_id, ids)
                        else:
                            plan.append((offset, seg_id, eff_len, rec))
                            if crc is not None:
                                puts.append((crc, slot, offset, eff_len))
                        offset += eff_len
                    labels[slot] = len(entries)
                    if plan:
                        plans.append((slot, tuple(plan)))
                if plane is not None:
                    if plans:
                        try:
                            failures = plane.run_round(
                                buf, labels, plans, should_stop=stop.is_set
                            )
                        except decode_plane.Stopped:
                            raise _Stopped()
                        if failures:
                            # token_length already validated every record —
                            # a worker-side encode failure is a real bug,
                            # not a budget event
                            raise failures[0][1]
                else:
                    list(pool.map(lambda sp: into(sp[1], buf[sp[0]]), plans))
                cache = cache_box[0]
                if cache is not None:
                    padded = np.zeros((L,), np.int32)
                    for crc, slot, offset, eff_len in puts:
                        padded[...] = 0
                        padded[:eff_len] = buf[slot, 0, offset : offset + eff_len]
                        cache.put(crc, padded, eff_len)
                parse_c.inc(time.monotonic() - t0)
                n_tokens = sum(n for entries in bins for _, n in entries)
                tokens_c.inc(n_tokens)
                seqs_c.inc(sum(len(entries) for entries in bins))
                emitted_tokens[0] += n_tokens
                emitted_slots[0] += rows * L
                eff = emitted_tokens[0] / emitted_slots[0]
                eff_g.set(eff)
                pad_g.set(1.0 - eff)
                if plane is not None:
                    # slab views are copied out and the slab returns to the
                    # pool at once (yielded batches are retainable)
                    out = np.array(buf[:rows])
                    free_q.put((buf, labels))
                else:
                    out = buf[:rows]
                _emit(
                    {
                        "tokens": out[:, 0],
                        "segment_ids": out[:, 1],
                        "positions": out[:, 2],
                    }
                )

            def _flush(final):
                """FFD-pack the window and emit whole batches of B bins.
                Mid-stream, sequences in leftover part-full bins carry into
                the next window (arrival order preserved); at stream end
                the leftovers become one short batch unless
                ``drop_remainder``."""
                nonlocal window, window_tokens
                bins = pack_bins([n for _, n in window], L)
                full = (len(bins) // B) * B
                for g in range(0, full, B):
                    _fill_and_emit([[window[i] for i in b] for b in bins[g : g + B]])
                rest = bins[full:]
                if final:
                    if rest and not self.drop_remainder:
                        _fill_and_emit([[window[i] for i in b] for b in rest])
                    # else: short remainder dropped (one static shape)
                    window, window_tokens = [], 0
                else:
                    carry = sorted(i for b in rest for i in b)
                    window = [window[i] for i in carry]
                    window_tokens = sum(n for _, n in window)

            def _epoch_end():
                # pack the epoch's tail into full batches, then seal the
                # staged cache generation — epoch >= 2 reads it back.
                # Part-full leftover bins carry across the epoch boundary
                # (their rows join the next epoch's first commit).
                _flush(final=False)
                if cache_box[0] is not None:
                    cache_box[0].commit()

            try:
                pool_cm = (
                    ThreadPoolExecutor(self.num_threads)
                    if plane is None
                    else _NullPool()
                )
                with pool_cm as pool:
                    for rec in self._record_stream(
                        reader_pool, stop, abort, read_c, on_epoch_end=_epoch_end
                    ):
                        if stop.is_set():
                            return
                        # rolled here, in the producer thread, so the seeded
                        # schedule is independent of reader-thread timing
                        # (chaos call-order determinism) — and identical in
                        # thread and process pack modes: mode-invariant
                        if chaos.active and chaos.fire("data.tokenize_error"):
                            rec = _CHAOS_BAD_RECORD
                        t0 = time.monotonic()
                        try:
                            raw_len = self.tokenizer.token_length(rec)
                        except Exception as e:
                            parse_c.inc(time.monotonic() - t0)
                            _absorb(e)
                            continue
                        parse_c.inc(time.monotonic() - t0)
                        if raw_len > L:
                            trunc_c.inc()
                        window.append((bytes(rec), min(raw_len, L)))
                        window_tokens += min(raw_len, L)
                        if window_tokens >= window_cap:
                            _flush(final=False)
                    if window:
                        _flush(final=True)
            except _Stopped:
                return
            except BaseException as e:  # surfaced on the consuming side
                _final_put(e)
                return
            finally:
                if cache_box[0] is not None:
                    # commit the stream tail's staged rows, then release
                    cache_box[0].commit()
                    cache_box[0].close()
                _final_put(_END)
                abort.set()
                if reader_pool is not None:
                    reader_pool.shutdown(wait=False, cancel_futures=True)

        thread = threading.Thread(target=producer, name="tos-text-producer", daemon=True)
        thread.start()
        try:
            while True:
                t0 = time.monotonic()
                item = out_q.get()
                wait_c.inc(time.monotonic() - t0)
                if item is _END:
                    return
                if isinstance(item, BaseException):
                    raise item
                consumed_c.inc()
                depth_g.set(out_q.qsize())
                yield item
        finally:
            stop.set()
            # unblock the producer if it is waiting on a full queue (empty()
            # instead of catching Empty: exception classes may already be
            # torn down when a half-consumed generator is GC'd at exit)
            while not out_q.empty():
                out_q.get_nowait()
            if plane is not None:
                # the producer observes stop within one poll interval; only
                # after it is out of the lease protocol is the plane torn
                # down (workers drained, slab pool unlinked)
                thread.join(timeout=10.0)
                plane.close()


class _NullPool:
    """Context stand-in for the thread pool when the pack plane owns the
    parse stage (mirrors the loader's nullcontext use, but typed so the
    ``pool`` name always exists)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def map(self, fn, items):
        return [fn(it) for it in items]
