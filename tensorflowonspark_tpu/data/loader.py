"""Pipelined TFRecord→batch input path with device double-buffering.

The tf.data replacement for the InputMode.TENSORFLOW perf path (reference
input_fn: imagenet_preprocessing.py:259-323 — shard per worker, shuffle,
parallel parse, batch with drop_remainder, prefetch), restructured as a
three-stage pipeline so IO, decode and the device never wait on each other:

1. **Shard read-ahead** — a small reader executor streams the next
   ``readahead`` shards off disk while the parse pool decodes the current
   one (the ``interleave``/``prefetch`` overlap of the reference input_fn).
   Each reader pushes record *chunks* through a bounded queue, so a shard
   is never fully materialized just to be read.
2. **Streaming chunked reads** — shards arrive in ``chunk_records``-sized
   chunks (native ``tfr_stream_next`` when built, the Python codec
   otherwise), and a bounded ``shuffle_buffer`` re-orders records on the
   fly: the ``ds.shuffle(buffer)`` contract instead of whole-shard
   permutations, with peak memory of one buffer instead of one shard.
3. **Zero-copy batch assembly** — parse workers decode records straight
   into slots of a preallocated ``[B,H,W,C]`` batch buffer (no per-batch
   ``np.stack`` copy). With ``recycle_buffers=True`` the buffers circulate
   through a fixed pool instead of being reallocated per batch. With
   ``decode_workers > 0`` the parse stage moves off the GIL entirely: a
   :class:`~tensorflowonspark_tpu.data.decode_plane.DecodePlane` of worker
   *processes* decodes records straight into shared-memory batch slabs and
   the pool becomes a cross-process slab free list — same slot-assignment
   algorithm, same byte-identical stream, different place the decode runs.

Stall accounting: the producer and consumer publish
``data_producer_read_seconds_total`` / ``data_producer_parse_seconds_total``
/ ``data_producer_emit_seconds_total`` / ``data_consumer_wait_seconds_total``
to :mod:`~tensorflowonspark_tpu.obs`, so ``TFCluster.metrics()`` shows at a
glance whether a run is IO-bound (read time dominates), decode-bound (parse
dominates) or device-bound (emit blocks on the full prefetch queue while
the consumer never waits).
"""

import collections
import contextlib
import logging
import os
import queue
import threading
import time
import zlib

import numpy as np

from tensorflowonspark_tpu import chaos, obs, resilience
from tensorflowonspark_tpu.data import autotune, decode_plane, slab_cache
from tensorflowonspark_tpu.store import base as store_base

logger = logging.getLogger(__name__)

#: retry policy for opening/bulk-reading a shard: network filesystems
#: (gcsfuse, NFS) fail transiently under pressure and a re-open is cheap
#: next to losing the epoch. Mid-stream corruption is not retried — the
#: stream position is gone and corrupt bytes don't heal.
SHARD_READ_RETRY = resilience.RetryPolicy(
    max_attempts=3,
    backoff=resilience.Backoff(base=0.05, factor=2.0, max_delay=0.5, jitter=0.5),
    retry_on=(IOError,),
    name="loader-shard-read",
)

#: chunks a read-ahead reader may buffer per shard before blocking — bounds
#: memory to readahead * depth * chunk_records records
_CHUNK_QUEUE_DEPTH = 4

_SHARD_END = object()


class _ParseError:
    """Per-record parse failure carried out of the thread pool (a raised
    exception would abort the whole ``pool.map`` batch)."""

    __slots__ = ("error",)

    def __init__(self, error):
        self.error = error


class _Keyed:
    """A raw record tagged with its ``(path, index)`` decoded-cache key so
    the parse worker knows where to store the decoded result."""

    __slots__ = ("rec", "key")

    def __init__(self, rec, key):
        self.rec = rec
        self.key = key


class _Decoded:
    """A decoded-cache hit flowing through the stream in place of raw
    bytes — the parse stage passes it straight into the batch buffer."""

    __slots__ = ("image", "label")

    def __init__(self, image, label):
        self.image = image
        self.label = label


class _Stopped(Exception):
    """Consumer departed mid-iteration; unwind the producer quietly."""


def shard_files(files, num_shards, index):
    """Deterministic per-worker file sharding (the reference used
    ``ds.shard(num_workers, worker_num)``, mnist_inference.py:42 — same
    round-robin contract).

    Sorted by shard basename first, full path second
    (:func:`tensorflowonspark_tpu.store.base.shard_sort_key`): a local glob
    and a remote URL listing of the same corpus order identically, so every
    worker gets the same shards no matter where the corpus lives."""
    files = sorted(files, key=store_base.shard_sort_key)
    if num_shards <= 1:
        return list(files)
    if index >= num_shards:
        raise ValueError("shard index {} out of range for {} shards".format(index, num_shards))
    return files[index::num_shards]


def _chunks_of(records, chunk_records):
    """Slice an in-memory record list into chunk_records-sized chunks
    (``chunk_records <= 0`` means one chunk: the bulk contract)."""
    if chunk_records <= 0:
        yield records
        return
    for i in range(0, len(records), chunk_records):
        yield records[i : i + chunk_records]


def _staged_or_cold(staged, path, store, verify_crc, chunk_records):
    """Chunks of ``path`` from its staged local copy, falling back to the
    cold remote read if the local copy fails before its first chunk — the
    window where the capacity bound may have evicted the staged directory
    between ``stager.fetch`` and the open. After the first chunk the file
    handle pins the bytes (POSIX unlink semantics), so a mid-stream error
    is a real one and surfaces."""
    try:
        it = _shard_chunk_iter(staged, verify_crc, chunk_records)
        first = next(it, None)
    except (OSError, IOError):
        logger.warning(
            "staged copy of %s unreadable (evicted or torn); reading cold", path
        )
        yield from _shard_chunk_iter(path, verify_crc, chunk_records, store=store)
        return
    if first is None:
        return
    yield first
    yield from it


def _shard_chunk_iter(path, verify_crc, chunk_records, store=None, stager=None):
    """Iterator of record-lists for one shard. ``chunk_records > 0``
    streams chunks (native ``tfr_stream_next`` for local files, the Python
    codec for fsspec URIs or a stale prebuilt library); ``chunk_records
    <= 0`` is the bulk path — the whole shard as a single chunk.

    Remote shards (``store`` handles the path) are served from the staged
    local copy when the prefetch ``stager`` has one (the read then falls
    through to the native local fast path below), or stream *cold* through
    the store's ranged chunk reads — same chunks, same bytes, either way.
    A staged copy that fails before its first chunk (evicted by the
    capacity bound between ``fetch`` and open, or corrupt on disk) falls
    back to the cold remote read — serve cold, never garbage."""
    from tensorflowonspark_tpu import native_io, tfrecord

    if path.startswith("file://"):
        path = path[len("file://"):]
    if store is not None and store.handles(path):
        staged = stager.fetch(path) if stager is not None else None
        if staged is not None:
            store_base.note_backend("{} staged".format(store.fingerprint()))
            return _staged_or_cold(
                staged, path, store, verify_crc, chunk_records
            )
        elif chunk_records > 0:
            return store.read_records_chunked(
                path, chunk_records=chunk_records, verify_crc=verify_crc
            )
        else:
            return iter([store.read_records(path, verify_crc=verify_crc)])
    local = not tfrecord.is_uri(path)
    if chunk_records > 0:
        if local and native_io.stream_available():
            return native_io.read_records_chunked(
                path, chunk_records=chunk_records, verify_crc=verify_crc
            )
        return tfrecord.read_records_chunked(
            path, chunk_records=chunk_records, verify_crc=verify_crc
        )
    if local and native_io.available():
        return iter([native_io.read_records(path, verify_crc=verify_crc)])
    return iter([list(tfrecord.read_records(path, verify_crc=verify_crc))])


def _stop_put(q, item, stop, abort):
    """Bounded put that gives up when the pipeline is tearing down."""
    while not (stop.is_set() or abort.is_set()):
        try:
            q.put(item, timeout=0.1)
            return True
        except queue.Full:
            continue
    return False


def _stop_get(q, stop):
    """Blocking get that returns None once the consumer has departed."""
    while not stop.is_set():
        try:
            return q.get(timeout=0.1)
        except queue.Empty:
            continue
    return None


def _shuffle_stream(records, rng, buffer_size):
    """Bounded streaming shuffle: the ``ds.shuffle(buffer_size)`` contract.

    Keeps at most ``buffer_size`` records buffered; each output is drawn
    uniformly from the buffer (swap-random-to-end, pop). Deterministic for
    a given ``rng`` and input order — and the input order is the shard
    order regardless of readahead/chunking, so the output stream is too.
    """
    buf = []
    for rec in records:
        buf.append(rec)
        if len(buf) >= buffer_size:
            j = int(rng.integers(len(buf)))
            buf[j], buf[-1] = buf[-1], buf[j]
            yield buf.pop()
    while buf:
        j = int(rng.integers(len(buf)))
        buf[j], buf[-1] = buf[-1], buf[j]
        yield buf.pop()


class ImagePipeline:
    """files → shuffled, parsed, fixed-shape batches of
    ``{"image": f32 [B,H,W,C], "label": i32 [B]}``.

    ``parse_fn(record_bytes) -> (image, label)`` comes from
    :mod:`~tensorflowonspark_tpu.data.imagenet` / ``cifar``. Iterating yields
    ``steps_per_epoch * epochs`` batches (``epochs=None`` repeats forever).
    By default short final batches are dropped (static shapes for XLA, the
    reference's ``drop_remainder=True``); pass ``drop_remainder=False`` for
    complete-coverage eval (one extra compile for the short batch).

    Pipelining knobs (all deterministic: the record stream is byte-identical
    for a given ``seed`` regardless of ``readahead``, ``chunk_records`` or
    ``num_threads``):

    - ``readahead`` — how many shards the reader executor fetches ahead of
      the parse stage (default env ``TOS_DATA_READAHEAD`` or 2; 0 reads
      shards inline, no IO/parse overlap). ``"auto"`` lets a
      :class:`~tensorflowonspark_tpu.data.autotune.ReadaheadAutotuner`
      steer the depth at runtime from the stall counters: deepen while the
      interval is io_bound and the consumer starves, shallow when the
      pipeline is comfortably ahead (published as ``readahead_depth``).
    - ``chunk_records`` — records per streamed chunk (default env
      ``TOS_DATA_CHUNK_RECORDS`` or 1024; 0 bulk-loads whole shards).
    - ``shuffle_buffer`` — bounded streaming shuffle window (the
      ``ds.shuffle(buffer)`` contract); ``<= 1`` disables record-level
      shuffling (shard order is still shuffled).
    - ``cache`` — ``"raw"`` keeps each shard's record bytes in memory after
      its first read (epochs ≥ 2 skip the filesystem); ``"decoded"``
      additionally keeps decoded ``(image, label)`` pairs so later epochs
      skip the parse too — only sound when ``parse_fn`` is deterministic
      per record (the imagenet/cifar parse_fns key their augmentation RNG
      to the record bytes, so they are). Caches persist across iterations
      of the same pipeline object; concurrent iterations of one cached
      pipeline are not supported.
    - ``recycle_buffers`` — emitted batch buffers circulate through a fixed
      pool instead of being reallocated. The yielded batch is then only
      valid until the *next* ``next()``; leave False (default) if batches
      are retained (e.g. ``list(pipe)``).
    - ``decode_workers`` — run the parse stage in worker *processes*
      decoding straight into shared-memory slabs (GIL-free; see
      :mod:`~tensorflowonspark_tpu.data.decode_plane`). Default env
      ``TOS_DECODE_WORKERS`` or 0 = today's in-process thread pool;
      ``"auto"`` self-sizes from the parse/wait stall counters. Requires a
      fork start method, an importable/fork-inheritable ``parse_fn``
      (module-level factories like ``imagenet.make_parse_fn`` qualify) and
      ``multiprocessing.shared_memory`` — otherwise the thread pool is used
      with a warning. The delivered batch stream is byte-identical across
      thread and process modes.
    - ``slab_cache_dir`` — root for the cross-epoch decoded-slab cache
      (default env ``TOS_SLAB_CACHE_DIR``; unset = off). Decoded rows are
      persisted keyed by record crc32 under the ``parse_fn.cache_key``
      decode-parameter fingerprint, so epoch ≥ 2 — and an elastic relaunch
      over the same shards — fills slots from a memory map instead of
      decoding (see :mod:`~tensorflowonspark_tpu.data.slab_cache`). Only
      active when the ``parse_fn`` exposes ``cache_key``; the stream stays
      byte-identical with the cache on, off, cold or warm.
    - ``store`` — an explicit
      :class:`~tensorflowonspark_tpu.store.base.ShardStore` the shard paths
      live in. ``http(s)://`` shard lists auto-detect an
      :class:`~tensorflowonspark_tpu.store.http.HTTPStore`; ``gs://`` /
      ``s3://`` corpora pass one explicitly with the matching endpoint
      adapter. The record stream is byte-identical to reading the same
      corpus from local disk.
    - ``prefetch`` — remote-shard staging window (default env
      ``TOS_STORE_PREFETCH`` or ``"auto"``): shards are downloaded to
      executor-local disk (``TOS_PREFETCH_DIR``) ahead of the reader and
      served through the native local fast path; ``"auto"`` lets the
      read-ahead autotuner steer the window from the stall counters
      (``store_prefetch_depth``); ``0`` streams cold through ranged remote
      reads. Only meaningful with a remote ``store``.

    ``max_bad_records`` is the poisoned-input budget: records whose
    ``parse_fn`` raises are skipped (counted in
    ``data_records_skipped_total``) until the budget is spent, then the
    parse error surfaces to the consumer. The default of 0 keeps the
    strict fail-fast contract; long production runs over petabyte-scale
    stores set a small tolerance so one torn record cannot kill an epoch.
    Batches stay full-size — good records backfill into the holes,
    preserving the static shapes XLA compiled for.
    """

    def __init__(
        self,
        files,
        parse_fn,
        batch_size,
        shuffle=True,
        seed=0,
        num_threads=None,
        epochs=1,
        prefetch_batches=2,
        verify_crc=False,
        drop_remainder=True,
        max_bad_records=0,
        readahead=None,
        chunk_records=None,
        shuffle_buffer=4096,
        cache=None,
        recycle_buffers=False,
        decode_workers=None,
        slab_cache_dir=None,
        store=None,
        prefetch=None,
    ):
        if not files:
            raise ValueError("no input files")
        self.files = list(files)
        # remote shard source: explicit store=, or auto-detected for
        # http(s):// shard lists (gs://, s3:// need an explicit store with
        # the matching endpoint adapter — never silently unauthenticated;
        # other URI schemes keep today's fsspec route)
        if store is None and any(
            str(f).startswith(("http://", "https://")) for f in self.files
        ):
            from tensorflowonspark_tpu.store.http import resolve_store

            store = resolve_store(self.files)
        self.store = store
        #: remote prefetch window (``TOS_STORE_PREFETCH`` default: "auto" =
        #: stall-steered staging to local disk; "0" streams cold)
        self.prefetch = prefetch
        self._stager = None  # built per-iteration, after the plane forks
        self.parse_fn = parse_fn
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        # default threads from TOS_DATA_THREADS — the ML pipeline's `readers`
        # param lands here (reference HasReaders controlled enqueue threads)
        self.num_threads = num_threads or int(os.environ.get("TOS_DATA_THREADS", "8"))
        self.epochs = epochs
        self.prefetch_batches = prefetch_batches
        self.verify_crc = verify_crc
        #: training wants static shapes (XLA recompiles per shape); eval
        #: wants every example scored — drop_remainder=False emits the short
        #: final batch (one extra compile, complete coverage)
        self.drop_remainder = drop_remainder
        self.max_bad_records = int(max_bad_records)
        if readahead is None:
            readahead = os.environ.get("TOS_DATA_READAHEAD", "2")
        self.readahead_auto = str(readahead).strip().lower() == "auto"
        if self.readahead_auto:
            # stall-steered: the reader pool is sized to the ceiling; the
            # live depth starts shallow and the ReadaheadAutotuner moves it
            self.readahead = autotune.DEFAULT_MAX_READAHEAD
            self._ra_depth = [min(2, self.readahead)]
        else:
            self.readahead = max(0, int(readahead))
            self._ra_depth = [self.readahead]
        if chunk_records is None:
            chunk_records = int(os.environ.get("TOS_DATA_CHUNK_RECORDS", "1024"))
        self.chunk_records = max(0, int(chunk_records))
        self.shuffle_buffer = int(shuffle_buffer)
        if cache not in (None, "raw", "decoded"):
            raise ValueError(
                "cache must be None, 'raw' or 'decoded', got {!r}".format(cache)
            )
        self.cache = cache
        self.recycle_buffers = bool(recycle_buffers)
        self.decode_workers = decode_workers
        self.slab_cache_dir = slab_cache.resolve_dir(slab_cache_dir)
        # raw cache: path -> [record bytes], marked complete only after a
        # full clean read; decoded cache: (path, record index) -> _Decoded
        self._raw_cache = {}
        self._raw_complete = set()
        self._decoded = {}

    # -- stage 1+2: shard read-ahead and chunked streaming ---------------------

    def _is_cached(self, path):
        return self.cache is not None and path in self._raw_complete

    def _open_shard(self, path, chunk_records):
        """Open one shard as a chunk iterator; the ``data.shard_read`` chaos
        site injects delay or IOError here (retried under
        ``SHARD_READ_RETRY``, like the transient filesystem faults it
        models)."""
        if chaos.active:
            spec = chaos.fire("data.shard_read")
            if spec is not None:
                if spec.get("error"):
                    raise IOError(
                        "chaos: injected shard read failure for {}".format(path)
                    )
                time.sleep(spec.get("delay_s", 0.05))
        return _shard_chunk_iter(
            path, self.verify_crc, chunk_records,
            store=self.store, stager=self._stager,
        )

    def _decorate(self, path, base, records):
        """Swap records for decoded-cache hits / cache-keyed raw records.
        Misses (e.g. records left unparsed at an epoch-boundary teardown of
        the parse stage) fall back to the raw bytes kept by the raw cache."""
        if self.cache != "decoded":
            return records
        out = []
        for i, rec in enumerate(records):
            key = (path, base + i)
            out.append(self._decoded.get(key) or _Keyed(rec, key))
        return out

    def _shard_chunks_sync(self, path, read_c):
        """Yield one shard's record chunks, serving/filling the raw cache
        and accounting IO time into ``read_c``."""
        cs = self.chunk_records
        if self._is_cached(path):
            base = 0
            for chunk in _chunks_of(self._raw_cache[path], cs):
                yield self._decorate(path, base, chunk)
                base += len(chunk)
            return
        caching = self.cache is not None
        acc = [] if caching else None
        t0 = time.monotonic()
        it = SHARD_READ_RETRY.call(self._open_shard, path, cs)
        read_c.inc(time.monotonic() - t0)
        base = 0
        while True:
            t0 = time.monotonic()
            chunk = next(it, None)
            read_c.inc(time.monotonic() - t0)
            if chunk is None:
                break
            if caching:
                acc.extend(chunk)
            yield self._decorate(path, base, chunk)
            base += len(chunk)
        # only reached on a clean EOF — an abandoned or failed read never
        # marks the shard complete
        if caching:
            self._raw_cache[path] = acc
            self._raw_complete.add(path)

    def _read_shard_task(self, path, q, stop, abort, read_c):
        """Reader-executor task: stream one shard's chunks into ``q``,
        terminated by ``_SHARD_END`` or the exception that broke the read."""
        try:
            for chunk in self._shard_chunks_sync(path, read_c):
                if chaos.active:
                    # a remote store gone slow: per-chunk latency inside the
                    # reader task, charged to read time so the stall
                    # classifier (and the readahead autotuner) sees io_bound
                    t0 = time.monotonic()
                    if chaos.delay("data.readahead_stall"):
                        read_c.inc(time.monotonic() - t0)
                if not _stop_put(q, chunk, stop, abort):
                    return
            _stop_put(q, _SHARD_END, stop, abort)
        except BaseException as e:  # delivered to the producer thread
            _stop_put(q, e, stop, abort)

    def _epoch_chunks(self, reader_pool, order, stop, abort, read_c):
        """Yield record chunks for one epoch in deterministic shard order,
        with up to ``readahead`` shards being read concurrently."""
        if reader_pool is None:
            for path in order:
                for chunk in self._shard_chunks_sync(path, read_c):
                    yield chunk
            return
        inflight = {}
        ahead = [0]

        def _top_up():
            # the live depth (not self.readahead): with readahead="auto"
            # the ReadaheadAutotuner moves it inside [1, self.readahead]
            while ahead[0] < len(order) and len(inflight) < self._ra_depth[0]:
                idx = ahead[0]
                ahead[0] += 1
                path = order[idx]
                if self._is_cached(path):
                    inflight[idx] = path  # in memory: serve synchronously
                    continue
                q = queue.Queue(maxsize=_CHUNK_QUEUE_DEPTH)
                fut = reader_pool.submit(
                    self._read_shard_task, path, q, stop, abort, read_c
                )
                inflight[idx] = (q, fut)

        _top_up()
        for k in range(len(order)):
            if k not in inflight:
                _top_up()
            entry = inflight.pop(k)
            _top_up()  # keep the read-ahead window full while we drain k
            if isinstance(entry, str):
                for chunk in self._shard_chunks_sync(entry, read_c):
                    yield chunk
                continue
            q, fut = entry
            while True:
                item = _stop_get(q, stop)
                if item is None:
                    raise _Stopped()
                if item is _SHARD_END:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
            fut.result()

    def _record_stream(self, reader_pool, stop, abort, read_c, on_epoch_end=None):
        # two independent RNGs: shard order must not depend on how many
        # records the shuffle buffer drew, or determinism across
        # shuffle_buffer settings would silently couple to shard sizes
        order_rng = np.random.default_rng(self.seed)
        shuffle_rng = np.random.default_rng((self.seed, 1))
        epoch = 0
        while self.epochs is None or epoch < self.epochs:
            order = list(self.files)
            if self.shuffle:
                order_rng.shuffle(order)
            if self._stager is not None:
                # the staging tier warms its window in this epoch's visit
                # order — the same order the reader executor will drain
                self._stager.plan(order)
            records = (
                rec
                for chunk in self._epoch_chunks(reader_pool, order, stop, abort, read_c)
                for rec in chunk
            )
            if self.shuffle and self.shuffle_buffer > 1:
                # buffer drains at epoch end: no cross-epoch record bleed
                records = _shuffle_stream(records, shuffle_rng, self.shuffle_buffer)
            for rec in records:
                yield rec
            if on_epoch_end is not None:
                # epoch boundary (shuffle buffer drained): the slab-cache
                # commit hook runs here, in the producer thread
                on_epoch_end()
            epoch += 1

    # -- stage 3: zero-copy batch assembly --------------------------------------

    def __iter__(self):
        from concurrent.futures import ThreadPoolExecutor

        B = self.batch_size
        out_q = queue.Queue(maxsize=max(1, self.prefetch_batches))
        stop = threading.Event()  # consumer departed
        abort = threading.Event()  # producer died: unblocks reader threads
        _END = object()
        free_q = queue.Queue()  # recycled (image, label) buffer pairs
        # buffers simultaneously alive: the prefetch queue, the producer's
        # in-progress batch, and the one the consumer still holds
        pool_cap = max(1, self.prefetch_batches) + 2
        alloc_count = [0]
        img_meta = {}

        produced_c = obs.counter(
            "data_batches_produced_total", help="batches parsed by the input pipeline"
        )
        consumed_c = obs.counter(
            "data_batches_consumed_total", help="batches handed to the training loop"
        )
        depth_g = obs.gauge(
            "data_prefetch_depth", help="parsed batches waiting in the prefetch queue"
        )
        skipped_c = obs.counter(
            "data_records_skipped_total",
            help="undecodable records skipped within the max_bad_records budget",
        )
        read_c = obs.counter(
            "data_producer_read_seconds_total",
            help="seconds spent in shard IO (open + chunk reads)",
        )
        parse_c = obs.counter(
            "data_producer_parse_seconds_total",
            help="seconds the parse pool spent decoding records into batch buffers",
        )
        emit_c = obs.counter(
            "data_producer_emit_seconds_total",
            help="seconds the producer blocked on a full prefetch queue "
            "(backpressure: the consumer is the bottleneck)",
        )
        wait_c = obs.counter(
            "data_consumer_wait_seconds_total",
            help="seconds the consumer waited on an empty prefetch queue "
            "(starvation: the input pipeline is the bottleneck)",
        )
        native_c = obs.counter(
            "decode_native_total",
            help="records decoded by the native JPEG path (no PIL)",
        )

        # the decode plane forks its workers HERE, before any pipeline
        # thread exists (the reader/parse executors spawn lazily, on first
        # submit) — fork-with-threads is the one mp lifecycle hazard
        plane = None
        workers, auto = decode_plane.resolve_workers(self.decode_workers)
        if workers > 0:
            if decode_plane.available():
                tuner = (
                    decode_plane.DecodeAutotuner(
                        max_workers=max(workers, os.cpu_count() or 1)
                    )
                    if auto
                    else None
                )
                plane = decode_plane.DecodePlane(self.parse_fn, workers, autotuner=tuner)
            else:
                logger.warning(
                    "decode_workers=%s requested but fork/shared_memory is "
                    "unavailable here; falling back to the thread parse pool",
                    workers,
                )

        # the remote staging tier, rebuilt per iteration: its download pool
        # spawns threads only on first submit (inside the producer thread),
        # so constructing it here — after the plane forked — is fork-safe
        stager = None
        if self.store is not None:
            from tensorflowonspark_tpu.store import staging as store_staging

            stager = store_staging.resolve_stager(self.store, prefetch=self.prefetch)
        self._stager = stager

        reader_pool = (
            ThreadPoolExecutor(self.readahead, thread_name_prefix="tos-data-reader")
            if self.readahead > 0
            else None
        )
        ra_tuner = None
        if reader_pool is not None and self.readahead_auto:
            ra_tuner = autotune.ReadaheadAutotuner(max_depth=self.readahead)
            ra_tuner.publish(self._ra_depth[0])

        # cross-epoch decoded-slab cache: constructed lazily once bootstrap
        # fixes the batch geometry (cache_box[0] stays None when off)
        cache_box = [None]
        cache_key = getattr(self.parse_fn, "cache_key", None)
        cache_root = self.slab_cache_dir if cache_key is not None else None
        # the thread-mode native fast path (process mode binds it in the
        # worker): only sound when the parse_fn advertises into-slab decode
        into = getattr(self.parse_fn, "into", None)

        def _final_put(item):
            # never block forever on a departed consumer: its finally drains
            # the queue and sets stop, so either the put lands or stop shows
            while not stop.is_set():
                try:
                    out_q.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        def _new_pair():
            # process mode mints a shared-memory slab (the view circulates
            # exactly like a plain buffer pair); thread mode a heap buffer
            if plane is not None:
                return plane.new_slab(B, img_meta["shape"], img_meta["dtype"])
            return (
                np.empty((B,) + img_meta["shape"], img_meta["dtype"]),
                np.empty((B,), np.int32),
            )

        def _acquire():
            # slabs are ALWAYS pooled (workers hold attachments by name);
            # plain buffers only when recycling was asked for
            if plane is None and not self.recycle_buffers:
                return _new_pair()
            try:
                return free_q.get_nowait()
            except queue.Empty:
                pass
            if alloc_count[0] < pool_cap:
                alloc_count[0] += 1
                return _new_pair()
            # pool exhausted: one timed-get path (no spin) until a buffer
            # comes back or the consumer departs
            t0 = time.monotonic()
            while True:
                if stop.is_set():
                    raise _Stopped()
                try:
                    pair = free_q.get(timeout=0.1)
                    break
                except queue.Empty:
                    continue
            if plane is not None:
                plane.note_slab_wait(time.monotonic() - t0)
            return pair

        def producer():
            bad = []  # parse errors absorbed so far (within budget)
            images = None  # current batch buffer [B, H, W, C]
            labels = None  # current label buffer [B]
            free_slots = []  # unfilled slot indices of the current buffer
            pending = []  # records awaiting a parse round

            def _parse_el(el):
                try:
                    if isinstance(el, _Decoded):
                        return el.image, el.label
                    rec, key = el, None
                    if isinstance(el, _Keyed):
                        rec, key = el.rec, el.key
                    img, lbl = self.parse_fn(rec)
                    img = np.asarray(img)
                    if key is not None:
                        self._decoded[key] = _Decoded(img, lbl)
                    return img, lbl
                except Exception as e:
                    return _ParseError(e)

            def _rec_bytes(el):
                """Raw record bytes of a stream element (None for a
                decoded-cache hit — nothing left to key or decode)."""
                if isinstance(el, _Decoded):
                    return None
                return el.rec if isinstance(el, _Keyed) else el

            def _parse_slot(el, slot):
                """Pool worker: decode ``el`` straight into buffer slot
                ``slot``. Distinct slots per worker — no write overlap."""
                if into is not None and not isinstance(el, _Decoded):
                    # native fast path: one C call lands decode+crop+resize+
                    # flip in the slot; any failure inside into() already
                    # fell back to PIL, so an exception here means the
                    # record is genuinely undecodable (budget accounting
                    # identical to the plain path)
                    rec, key = (el.rec, el.key) if isinstance(el, _Keyed) else (el, None)
                    try:
                        lbl, used_native = into(rec, images[slot])
                        labels[slot] = lbl
                    except Exception as e:
                        return (slot, _ParseError(e))
                    if used_native:
                        native_c.inc()
                    if key is not None:
                        self._decoded[key] = _Decoded(np.array(images[slot]), int(lbl))
                    return None
                p = _parse_el(el)
                if not isinstance(p, _ParseError):
                    try:
                        images[slot] = p[0]
                        labels[slot] = p[1]
                        return None
                    except Exception as e:  # shape/dtype mismatch vs slot 0
                        p = _ParseError(e)
                return (slot, p)

            def _absorb(err):
                if len(bad) >= self.max_bad_records:
                    raise err
                bad.append(err)
                skipped_c.inc()
                logger.warning("skipping undecodable record: %s", err)

            def _emit(img_out, lbl_out):
                if chaos.active:
                    chaos.delay("data.producer_delay")
                batch = {"image": img_out, "label": lbl_out}
                t0 = time.monotonic()
                while True:
                    try:
                        out_q.put(batch, timeout=0.5)
                        break
                    except queue.Full:
                        if stop.is_set():
                            raise _Stopped()
                emit_c.inc(time.monotonic() - t0)
                produced_c.inc()
                depth_g.set(out_q.qsize())

            def _next_buffers():
                nonlocal images, labels, free_slots
                images, labels = _acquire()
                free_slots = list(range(B))

            def _emit_full():
                # a full batch goes out; in non-recycle process mode the
                # slab view is copied out and returned to the pool at once
                # (the consumer only recycles when recycle_buffers is set)
                if plane is not None and not self.recycle_buffers:
                    _emit(np.array(images), labels.copy())
                    free_q.put((images, labels))
                else:
                    _emit(images, labels)
                _next_buffers()

            def _slab_hit(el, slot):
                """Serve ``el`` from the cross-epoch slab cache if it can:
                the cached row is written into the slot parent-side (the
                hit leases the slot without touching a worker or a pool
                thread). Returns the record's crc (a miss, to be staged
                after decode), True (served), or None (cache off /
                already-decoded element)."""
                cache = cache_box[0]
                rec = _rec_bytes(el)
                if cache is None or rec is None:
                    return None
                crc = zlib.crc32(rec)
                hit = cache.lookup(crc)
                if hit is None:
                    return crc
                images[slot] = hit[0]
                labels[slot] = hit[1]
                if isinstance(el, _Keyed):
                    self._decoded[el.key] = _Decoded(
                        np.array(images[slot]), int(labels[slot])
                    )
                return True

            def _plane_round(els, slots):
                """Decode one round on the process plane: cache hits are
                written inline (already-decoded pixels never cross a
                process), raw records lease slab slots to the workers, and
                keyed slots flow back into the decoded cache *via the
                slab* — no pickle on the result path."""
                results = []
                tasks = []
                keyed = {}
                crcs = {}  # slot -> record crc for slab-cache misses
                for el, slot in zip(els, slots):
                    if isinstance(el, _Decoded):
                        try:
                            images[slot] = el.image
                            labels[slot] = el.label
                        except Exception as e:  # shape/dtype mismatch
                            results.append((slot, _ParseError(e)))
                        continue
                    try:
                        served = _slab_hit(el, slot)
                    except Exception as e:  # cached-row geometry mismatch
                        results.append((slot, _ParseError(e)))
                        continue
                    if served is True:
                        continue
                    if served is not None:
                        crcs[slot] = served
                    rec, key = el, None
                    if isinstance(el, _Keyed):
                        rec, key = el.rec, el.key
                    if key is not None:
                        keyed[slot] = key
                    tasks.append((slot, rec))
                try:
                    failures = plane.run_round(
                        images, labels, tasks, should_stop=stop.is_set
                    )
                except decode_plane.Stopped:
                    raise _Stopped()
                failed = set()
                for slot, err in failures:
                    failed.add(slot)
                    results.append((slot, _ParseError(err)))
                for slot, key in keyed.items():
                    if slot not in failed:
                        self._decoded[key] = _Decoded(
                            np.array(images[slot]), int(labels[slot])
                        )
                if cache_box[0] is not None:
                    for slot, crc in crcs.items():
                        if slot not in failed:
                            cache_box[0].put(crc, images[slot], labels[slot])
                plane.autotune_tick()
                return results

            def _thread_round(els, slots):
                """Decode one round on the in-process pool: slab-cache hits
                are written inline by the producer (the cache is
                single-threaded by contract), misses fan out to the pool
                and their freshly decoded rows are staged back."""
                results = []
                run_els = []
                run_slots = []
                crcs = {}
                for el, slot in zip(els, slots):
                    try:
                        served = _slab_hit(el, slot)
                    except Exception as e:  # cached-row geometry mismatch
                        results.append((slot, _ParseError(e)))
                        continue
                    if served is True:
                        continue
                    if served is not None:
                        crcs[slot] = served
                    run_els.append(el)
                    run_slots.append(slot)
                results.extend(
                    r for r in pool.map(_parse_slot, run_els, run_slots) if r is not None
                )
                if cache_box[0] is not None and crcs:
                    failed = {slot for slot, _ in results}
                    for slot, crc in crcs.items():
                        if slot not in failed:
                            cache_box[0].put(crc, images[slot], labels[slot])
                return results

            def _round():
                # parse all pending records into the lowest free slots;
                # failures leave holes that the next records backfill, so
                # emitted batches stay full-size
                nonlocal free_slots, pending
                if not pending:
                    return
                slots = free_slots[: len(pending)]
                t0 = time.monotonic()
                if plane is not None:
                    results = _plane_round(pending, slots)
                else:
                    results = _thread_round(pending, slots)
                parse_c.inc(time.monotonic() - t0)
                if ra_tuner is not None:
                    target = ra_tuner.tick(self._ra_depth[0])
                    if target is not None:
                        self._ra_depth[0] = target
                pending = []
                holes = []
                for slot, perr in results:
                    _absorb(perr.error)
                    holes.append(slot)
                free_slots = free_slots[len(slots):] + holes
                if not free_slots:
                    _emit_full()

            def _bootstrap(el):
                # the first good record defines the batch geometry: its
                # shape and dtype size the preallocated buffers (only f64 is
                # narrowed — uint8 parses quarter the host->device bytes)
                nonlocal free_slots
                p = _parse_el(el)
                if isinstance(p, _ParseError):
                    _absorb(p.error)
                    return
                img = np.asarray(p[0])
                img_meta["shape"] = img.shape
                img_meta["dtype"] = np.float32 if img.dtype == np.float64 else img.dtype
                if cache_root is not None:
                    # geometry is now known: open (or create) the decoded-
                    # slab cache scoped by the decode-parameter fingerprint
                    try:
                        cache_box[0] = slab_cache.SlabCache(
                            cache_root, cache_key, img_meta["shape"], img_meta["dtype"]
                        )
                    except Exception as e:
                        logger.warning("decoded-slab cache disabled: %s", e)
                _next_buffers()
                images[0] = img
                labels[0] = p[1]
                free_slots = free_slots[1:]
                rec = _rec_bytes(el)
                if cache_box[0] is not None and rec is not None:
                    cache_box[0].put(zlib.crc32(rec), images[0], labels[0])
                if not free_slots:
                    _emit_full()

            def _epoch_end():
                # flush the epoch's tail round so its rows make this commit
                # (slot assignment is unchanged: the same records land in
                # the same lowest free slots, just one round earlier), then
                # seal the staged generation — epoch >= 2 reads it back
                _round()
                if cache_box[0] is not None:
                    cache_box[0].commit()

            try:
                # with a decode plane the parse happens out of process; the
                # in-process pool (and its threads) never spawns
                pool_cm = (
                    contextlib.nullcontext()
                    if plane is not None
                    else ThreadPoolExecutor(self.num_threads)
                )
                with pool_cm as pool:
                    for rec in self._record_stream(
                        reader_pool, stop, abort, read_c, on_epoch_end=_epoch_end
                    ):
                        if stop.is_set():
                            return
                        # poison is rolled here, in the producer thread, so
                        # the seeded schedule is independent of reader/parse
                        # thread timing (chaos call-order determinism)
                        if chaos.active and chaos.fire("data.poison"):
                            if isinstance(rec, _Keyed):
                                rec = _Keyed(b"\x00chaos-poisoned-record", rec.key)
                            elif not isinstance(rec, _Decoded):
                                rec = b"\x00chaos-poisoned-record"
                        if images is None:
                            _bootstrap(rec)
                            continue
                        pending.append(rec)
                        if len(pending) >= len(free_slots):
                            _round()
                    if pending:
                        _round()
                    if images is not None and 0 < len(free_slots) < B and not self.drop_remainder:
                        # fancy indexing copies out of the recycled buffer:
                        # a short batch is never handed out aliased
                        keep = sorted(set(range(B)) - set(free_slots))
                        _emit(images[keep], labels[keep])
                    # else: short remainder dropped (one static shape)
            except _Stopped:
                return
            except BaseException as e:  # surfaced on the consuming side
                _final_put(e)
                return
            finally:
                if cache_box[0] is not None:
                    # uncommitted staging is discarded (the commit contract:
                    # a generation exists fully or not at all)
                    cache_box[0].close()
                _final_put(_END)
                abort.set()
                if reader_pool is not None:
                    reader_pool.shutdown(wait=False, cancel_futures=True)
                if stager is not None:
                    self._stager = None
                    stager.close()

        thread = threading.Thread(target=producer, name="tos-data-producer", daemon=True)
        thread.start()
        prev = None
        try:
            while True:
                if (
                    self.recycle_buffers
                    and prev is not None
                    and prev["image"].shape[0] == B
                ):
                    # the previous batch is done with (the "valid until the
                    # next next()" contract) — its buffers go back in the pool
                    free_q.put((prev["image"], prev["label"]))
                prev = None
                t0 = time.monotonic()
                item = out_q.get()
                wait_c.inc(time.monotonic() - t0)
                if item is _END:
                    return
                if isinstance(item, BaseException):
                    raise item
                consumed_c.inc()
                depth_g.set(out_q.qsize())
                prev = item
                yield item
        finally:
            stop.set()
            # unblock the producer if it is waiting on a full queue (empty()
            # instead of catching Empty: exception classes may already be
            # torn down when a half-consumed generator is GC'd at exit)
            while not out_q.empty():
                out_q.get_nowait()
            if plane is not None:
                # the producer observes stop within one poll interval; only
                # after it is out of the lease protocol is the plane torn
                # down (workers drained, slab pool unlinked)
                thread.join(timeout=10.0)
                plane.close()


def device_prefetch(batches, strategy, depth=2):
    """Shard host batches onto the mesh ``depth`` steps ahead of the consumer
    (the ``tf.data.prefetch``-to-device analogue): while the device crunches
    step N, the host is already transferring N+1."""
    buf = collections.deque()
    it = iter(batches)
    try:
        for _ in range(depth):
            buf.append(strategy.shard_batch(next(it)))
    except StopIteration:
        pass
    while buf:
        out = buf.popleft()
        try:
            buf.append(strategy.shard_batch(next(it)))
        except StopIteration:
            pass
        yield out


def loop_prefetch(batches, strategy, num_steps, depth=None):
    """Group host batches into device-resident lists of ``num_steps`` for
    :meth:`~tensorflowonspark_tpu.train.SyncDataParallel.compile_train_loop`.

    Each batch is placed with ``strategy.shard_batch`` as it arrives — the
    transfers are async and overlap the previous loop dispatch's compute —
    and handed out in windows of ``num_steps``. ``depth`` is how many batches
    beyond the current window stay in flight (default ``num_steps``, i.e.
    the next window transfers while the current one trains). Short final
    windows are dropped (the loop is compiled for a static ``num_steps``).
    """
    if depth is None:
        depth = num_steps
    buf = collections.deque()
    it = iter(batches)
    try:
        while True:
            while len(buf) < num_steps + depth:
                buf.append(strategy.shard_batch(next(it)))
            yield [buf.popleft() for _ in range(num_steps)]
    except StopIteration:
        pass
    while len(buf) >= num_steps:
        yield [buf.popleft() for _ in range(num_steps)]


def packed_place(window, strategy):
    """Stack a list of host batches into ONE ``[K, B, ...]`` pytree and ship
    it as a single sharded host→device transfer — the placement used by
    :func:`packed_prefetch` and mirrored by bench.py's packed link probe
    (kept here so the probe can never measure a different shape than the
    training path)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorflowonspark_tpu.parallel.sharding import data_axes

    axes = data_axes(strategy.mesh)
    spec = P(None, (axes if len(axes) > 1 else axes[0]) if axes else None)
    sharding = NamedSharding(strategy.mesh, spec)
    stacked = jax.tree.map(lambda *xs: np.stack(xs), *window)
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(x, sharding), stacked)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, x), stacked
    )


def packed_prefetch(batches, strategy, num_steps, depth=1):
    """Group host batches into device-resident ``[num_steps, B, ...]`` stacks,
    each shipped as ONE host→device transfer, double-buffered ``depth``
    windows ahead — for :meth:`compile_train_loop(packed=True)
    <tensorflowonspark_tpu.train.SyncDataParallel.compile_train_loop>`.

    Use this instead of :func:`loop_prefetch` when the device link has a
    large per-transfer fixed cost (relayed/tunneled TPU runtimes: ~250 ms
    per transfer measured here — docs/perf.md). One big transfer per window
    amortizes that cost ``num_steps``×; the host-side ``np.stack`` is a
    memcpy, cheap next to the wire. Short final windows are dropped.
    """
    buf = collections.deque()
    it = iter(batches)
    try:
        while True:
            while len(buf) < depth + 1:
                buf.append(packed_place([next(it) for _ in range(num_steps)], strategy))
            yield buf.popleft()
    except StopIteration:
        pass
    while buf:
        yield buf.popleft()
