"""Threaded TFRecord→batch pipeline with device double-buffering.

The tf.data replacement for the InputMode.TENSORFLOW perf path (reference
input_fn: imagenet_preprocessing.py:259-323 — shard per worker, shuffle,
parallel parse, batch with drop_remainder, prefetch): shards are bulk-read
through the native C++ reader when built (one FFI call per file,
native/tfrecord_io.cc), records parsed on a thread pool (PIL/numpy release
the GIL in their C cores), and fixed-shape batches handed out one step ahead
of the device so the MXU never waits on the host.
"""

import logging
import os
import queue
import threading

import numpy as np

from tensorflowonspark_tpu import chaos, obs

logger = logging.getLogger(__name__)


class _ParseError:
    """Per-record parse failure carried out of the thread pool (a raised
    exception would abort the whole ``pool.map`` batch)."""

    __slots__ = ("error",)

    def __init__(self, error):
        self.error = error


def shard_files(files, num_shards, index):
    """Deterministic per-worker file sharding (the reference used
    ``ds.shard(num_workers, worker_num)``, mnist_inference.py:42 — same
    round-robin contract)."""
    files = sorted(files)
    if num_shards <= 1:
        return list(files)
    if index >= num_shards:
        raise ValueError("shard index {} out of range for {} shards".format(index, num_shards))
    return files[index::num_shards]


def _read_shard(path, verify_crc=True):
    """All raw records of one shard; native bulk reader for local files
    (file:// included), fsspec-routed Python codec for remote URIs."""
    from tensorflowonspark_tpu import native_io, tfrecord

    if path.startswith("file://"):
        path = path[len("file://"):]
    if not tfrecord.is_uri(path) and native_io.available():
        return native_io.read_records(path, verify_crc=verify_crc)
    return list(tfrecord.read_records(path, verify_crc=verify_crc))


class ImagePipeline:
    """files → shuffled, parsed, fixed-shape batches of
    ``{"image": f32 [B,H,W,C], "label": i32 [B]}``.

    ``parse_fn(record_bytes) -> (image, label)`` comes from
    :mod:`~tensorflowonspark_tpu.data.imagenet` / ``cifar``. Iterating yields
    ``steps_per_epoch * epochs`` batches (``epochs=None`` repeats forever).
    By default short final batches are dropped (static shapes for XLA, the
    reference's ``drop_remainder=True``); pass ``drop_remainder=False`` for
    complete-coverage eval (one extra compile for the short batch).

    ``max_bad_records`` is the poisoned-input budget: records whose
    ``parse_fn`` raises are skipped (counted in
    ``data_records_skipped_total``) until the budget is spent, then the
    parse error surfaces to the consumer. The default of 0 keeps the
    strict fail-fast contract; long production runs over petabyte-scale
    stores set a small tolerance so one torn record cannot kill an epoch.
    Batches stay full-size — good records backfill across chunk
    boundaries, preserving the static shapes XLA compiled for.
    """

    def __init__(
        self,
        files,
        parse_fn,
        batch_size,
        shuffle=True,
        seed=0,
        num_threads=None,
        epochs=1,
        prefetch_batches=2,
        verify_crc=False,
        drop_remainder=True,
        max_bad_records=0,
    ):
        if not files:
            raise ValueError("no input files")
        self.files = list(files)
        self.parse_fn = parse_fn
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = seed
        # default threads from TOS_DATA_THREADS — the ML pipeline's `readers`
        # param lands here (reference HasReaders controlled enqueue threads)
        self.num_threads = num_threads or int(os.environ.get("TOS_DATA_THREADS", "8"))
        self.epochs = epochs
        self.prefetch_batches = prefetch_batches
        self.verify_crc = verify_crc
        #: training wants static shapes (XLA recompiles per shape); eval
        #: wants every example scored — drop_remainder=False emits the short
        #: final batch (one extra compile, complete coverage)
        self.drop_remainder = drop_remainder
        self.max_bad_records = int(max_bad_records)

    def _record_stream(self):
        rng = np.random.default_rng(self.seed)
        epoch = 0
        while self.epochs is None or epoch < self.epochs:
            order = list(self.files)
            if self.shuffle:
                rng.shuffle(order)
            for path in order:
                records = _read_shard(path, self.verify_crc)
                if self.shuffle:
                    idx = rng.permutation(len(records))
                    records = [records[i] for i in idx]
                for rec in records:
                    if chaos.active and chaos.fire("data.poison"):
                        rec = b"\x00chaos-poisoned-record"
                    yield rec
            epoch += 1

    def __iter__(self):
        from concurrent.futures import ThreadPoolExecutor

        out_q = queue.Queue(maxsize=max(1, self.prefetch_batches))
        stop = threading.Event()
        _END = object()
        produced_c = obs.counter(
            "data_batches_produced_total", help="batches parsed by the input pipeline"
        )
        consumed_c = obs.counter(
            "data_batches_consumed_total", help="batches handed to the training loop"
        )
        depth_g = obs.gauge(
            "data_prefetch_depth", help="parsed batches waiting in the prefetch queue"
        )

        def _final_put(item):
            # never block forever on a departed consumer: its finally drains
            # the queue and sets stop, so either the put lands or stop shows
            while not stop.is_set():
                try:
                    out_q.put(item, timeout=0.1)
                    return
                except queue.Full:
                    continue

        skipped_c = obs.counter(
            "data_records_skipped_total",
            help="undecodable records skipped within the max_bad_records budget",
        )

        def producer():
            bad = []  # parse errors absorbed so far (within budget)

            def _emit(parsed):
                images = np.stack([p[0] for p in parsed])
                # parse_fn's dtype is respected (uint8 parses quarter the
                # host->device bytes; normalization then runs on device) —
                # only f64 is narrowed
                if images.dtype == np.float64:
                    images = images.astype(np.float32)
                labels = np.asarray([p[1] for p in parsed], np.int32)
                out_q.put({"image": images, "label": labels})
                produced_c.inc()
                depth_g.set(out_q.qsize())

            def _safe_parse(rec):
                try:
                    return self.parse_fn(rec)
                except Exception as e:
                    return _ParseError(e)

            def _parse_into(pool, raw, parsed):
                # good records backfill across raw-chunk boundaries so
                # emitted batches stay full-size despite skips
                for p in pool.map(_safe_parse, raw):
                    if isinstance(p, _ParseError):
                        if len(bad) >= self.max_bad_records:
                            raise p.error
                        bad.append(p.error)
                        skipped_c.inc()
                        logger.warning("skipping undecodable record: %s", p.error)
                    else:
                        parsed.append(p)

            try:
                with ThreadPoolExecutor(self.num_threads) as pool:
                    raw, parsed = [], []
                    for rec in self._record_stream():
                        if stop.is_set():
                            return
                        raw.append(rec)
                        if len(raw) == self.batch_size:
                            if chaos.active:
                                chaos.delay("data.producer_delay")
                            _parse_into(pool, raw, parsed)
                            raw = []
                            while len(parsed) >= self.batch_size:
                                _emit(parsed[: self.batch_size])
                                parsed = parsed[self.batch_size:]
                    if raw:
                        _parse_into(pool, raw, parsed)
                    while len(parsed) >= self.batch_size:
                        _emit(parsed[: self.batch_size])
                        parsed = parsed[self.batch_size:]
                    if parsed and not self.drop_remainder:
                        _emit(parsed)
                    # else: short remainder dropped (one static shape)
            except BaseException as e:  # surfaced on the consuming side
                _final_put(e)
                return
            finally:
                _final_put(_END)

        thread = threading.Thread(target=producer, name="tos-data-producer", daemon=True)
        thread.start()
        try:
            while True:
                item = out_q.get()
                if item is _END:
                    return
                if isinstance(item, BaseException):
                    raise item
                consumed_c.inc()
                depth_g.set(out_q.qsize())
                yield item
        finally:
            stop.set()
            # unblock the producer if it is waiting on a full queue (empty()
            # instead of catching Empty: exception classes may already be
            # torn down when a half-consumed generator is GC'd at exit)
            while not out_q.empty():
                out_q.get_nowait()


def device_prefetch(batches, strategy, depth=2):
    """Shard host batches onto the mesh ``depth`` steps ahead of the consumer
    (the ``tf.data.prefetch``-to-device analogue): while the device crunches
    step N, the host is already transferring N+1."""
    import collections

    buf = collections.deque()
    it = iter(batches)
    try:
        for _ in range(depth):
            buf.append(strategy.shard_batch(next(it)))
    except StopIteration:
        pass
    while buf:
        out = buf.popleft()
        try:
            buf.append(strategy.shard_batch(next(it)))
        except StopIteration:
            pass
        yield out


def loop_prefetch(batches, strategy, num_steps, depth=None):
    """Group host batches into device-resident lists of ``num_steps`` for
    :meth:`~tensorflowonspark_tpu.train.SyncDataParallel.compile_train_loop`.

    Each batch is placed with ``strategy.shard_batch`` as it arrives — the
    transfers are async and overlap the previous loop dispatch's compute —
    and handed out in windows of ``num_steps``. ``depth`` is how many batches
    beyond the current window stay in flight (default ``num_steps``, i.e.
    the next window transfers while the current one trains). Short final
    windows are dropped (the loop is compiled for a static ``num_steps``).
    """
    import collections

    if depth is None:
        depth = num_steps
    buf = collections.deque()
    it = iter(batches)
    try:
        while True:
            while len(buf) < num_steps + depth:
                buf.append(strategy.shard_batch(next(it)))
            yield [buf.popleft() for _ in range(num_steps)]
    except StopIteration:
        pass
    while len(buf) >= num_steps:
        yield [buf.popleft() for _ in range(num_steps)]


def packed_place(window, strategy):
    """Stack a list of host batches into ONE ``[K, B, ...]`` pytree and ship
    it as a single sharded host→device transfer — the placement used by
    :func:`packed_prefetch` and mirrored by bench.py's packed link probe
    (kept here so the probe can never measure a different shape than the
    training path)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tensorflowonspark_tpu.parallel.sharding import data_axes

    axes = data_axes(strategy.mesh)
    spec = P(None, (axes if len(axes) > 1 else axes[0]) if axes else None)
    sharding = NamedSharding(strategy.mesh, spec)
    stacked = jax.tree.map(lambda *xs: np.stack(xs), *window)
    if jax.process_count() == 1:
        return jax.tree.map(lambda x: jax.device_put(x, sharding), stacked)
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, x), stacked
    )


def packed_prefetch(batches, strategy, num_steps, depth=1):
    """Group host batches into device-resident ``[num_steps, B, ...]`` stacks,
    each shipped as ONE host→device transfer, double-buffered ``depth``
    windows ahead — for :meth:`compile_train_loop(packed=True)
    <tensorflowonspark_tpu.train.SyncDataParallel.compile_train_loop>`.

    Use this instead of :func:`loop_prefetch` when the device link has a
    large per-transfer fixed cost (relayed/tunneled TPU runtimes: ~250 ms
    per transfer measured here — docs/perf.md). One big transfer per window
    amortizes that cost ``num_steps``×; the host-side ``np.stack`` is a
    memcpy, cheap next to the wire. Short final windows are dropped.
    """
    import collections

    buf = collections.deque()
    it = iter(batches)
    try:
        while True:
            while len(buf) < depth + 1:
                buf.append(packed_place([next(it) for _ in range(num_steps)], strategy))
            yield buf.popleft()
    except StopIteration:
        pass
    while buf:
        yield buf.popleft()
