"""Host-side input pipelines feeding the TPU (the InputMode.TENSORFLOW perf
path).

The reference shipped its input pipeline as example code driving tf.data
(/root/reference/examples/resnet/imagenet_preprocessing.py:259 input_fn,
cifar_preprocessing.py:42 parse_record); here it is a framework subpackage:
TFRecord shards are streamed in chunks through the native C++ reader
(:mod:`tensorflowonspark_tpu.native_io`) with shard read-ahead overlapping
IO against the parse stage, records re-ordered by a bounded shuffle buffer,
images decoded/augmented with PIL+numpy on a thread pool — or, with
``decode_workers > 0``, GIL-free in the :mod:`~tensorflowonspark_tpu.data.
decode_plane` worker processes writing into shared-memory slabs — straight
into preallocated batch buffers, and fixed-shape batches double-buffered
onto the device mesh — static shapes and steady feed keep XLA and the MXU
busy.
The device placement itself is adaptive: :mod:`~tensorflowonspark_tpu.data.
autotune` measures the host→device link online (fixed cost + bandwidth) and
sizes the packed transfer window K to amortize the link's per-transfer
fixed cost, instead of trusting an offline constant.
"""

from tensorflowonspark_tpu.data.loader import (  # noqa: F401
    ImagePipeline,
    device_prefetch,
    loop_prefetch,
    packed_place,
    packed_prefetch,
    shard_files,
)
from tensorflowonspark_tpu.data.autotune import (  # noqa: F401
    AutotunedWindow,
    FeedAutotuner,
    LinkEstimator,
    autotuned_prefetch,
)
from tensorflowonspark_tpu.data.decode_plane import (  # noqa: F401
    DecodeAutotuner,
    DecodePlane,
)
from tensorflowonspark_tpu.data.text_plane import (  # noqa: F401
    TextPipeline,
    pack_bins,
)
from tensorflowonspark_tpu.data.tokenizer import (  # noqa: F401
    TokenizeError,
    Tokenizer,
)
from tensorflowonspark_tpu.data import cifar, imagenet  # noqa: F401
