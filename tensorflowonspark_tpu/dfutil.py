"""DataFrame ↔ TFRecord conversion utilities.

Capability-parity with /root/reference/tensorflowonspark/dfutil.py — but where
the reference shelled DataFrames through the tensorflow-hadoop jar
(dfutil.py:39-41,63-65) and TF's Example class, this uses the framework's own
TFRecord codec (:mod:`tensorflowonspark_tpu.tfrecord`), so it works on the
local backend (shards on a shared filesystem) and on pyspark alike.

Matching the reference surface: ``saveAsTFRecords`` / ``loadTFRecords`` /
``toTFExample`` / ``fromTFExample`` / ``infer_schema`` / ``isLoadedDF``
(loaded-DF provenance, reference dfutil.py:15-26).
"""

import logging
import os
import weakref

from tensorflowonspark_tpu import tfrecord

logger = logging.getLogger(__name__)

#: provenance registry: DataFrames produced by loadTFRecords (reference
#: dfutil.py:15-26). Weak values so entries die with their DataFrame — id()
#: reuse after GC can't produce false positives.
loadedDF = weakref.WeakValueDictionary()
_loaded_dirs = {}


def isLoadedDF(df):
    return loadedDF.get(id(df)) is df


def loadedDFSource(df):
    """Input directory a loaded DataFrame came from, or None — the provenance
    lookup the reference's pipeline used to reuse already-converted TFRecords
    (reference pipeline.py tfrecord_dir reuse)."""
    return _loaded_dirs.get(id(df)) if isLoadedDF(df) else None


def toTFExample(row, columns, binary_features=()):
    """One row (sequence) → feature dict ready for Example encoding.

    dtype mapping mirrors the reference's table (dfutil.py:84-131): ints →
    Int64List, floats → FloatList, strings/bytes → BytesList; list columns map
    to multi-valued features; columns named in ``binary_features`` are written
    as raw bytes.
    """
    features = {}
    for name, value in zip(columns, row):
        if value is None:
            continue
        if name in binary_features:
            features[name] = [bytes(value) if not isinstance(value, bytes) else value]
            continue
        if isinstance(value, (list, tuple)):
            vals = list(value)
        else:
            vals = [value]
        if vals and isinstance(vals[0], float):
            vals = [float(v) for v in vals]
        features[name] = vals
    return features


def fromTFExample(example, columns=None, binary_features=()):
    """Decoded example dict → row tuple in ``columns`` order
    (reference dfutil.py:171-211)."""
    decoded = {}
    for name, (kind, values) in example.items():
        if kind == "bytes":
            if name in binary_features:
                decoded[name] = values[0] if len(values) == 1 else values
            else:
                strings = [v.decode("utf-8", "replace") for v in values]
                decoded[name] = strings[0] if len(strings) == 1 else strings
        else:
            decoded[name] = values[0] if len(values) == 1 else values
    if columns is None:
        columns = sorted(decoded)
    return tuple(decoded.get(c) for c in columns)


def infer_schema(example, binary_features=()):
    """Column names + kinds from a decoded example
    (reference dfutil.py:134-168 inferred Spark types the same way)."""
    schema = {}
    for name, (kind, values) in sorted(example.items()):
        multi = len(values) > 1
        if kind == "bytes" and name not in binary_features:
            kind = "string"
        schema[name] = {"kind": kind, "multi": multi}
    return schema


def saveAsTFRecords(df, output_dir, binary_features=()):
    """Write a DataFrame as TFRecord shards, one per partition
    (reference dfutil.py:29-41)."""
    columns = list(df.columns)
    if not tfrecord.is_uri(output_dir):
        output_dir = os.path.abspath(os.path.expanduser(output_dir))
    tfrecord.makedirs(output_dir)
    bin_feats = tuple(binary_features)

    def _write_partition(pidx, it):
        import uuid as _uuid

        examples = [toTFExample(row, columns, bin_feats) for row in it]
        if not examples:
            return []
        # commit protocol standing in for the Hadoop output committer: write
        # to a temp name, then rename onto the deterministic per-partition
        # name — task retries/speculative duplicates overwrite instead of
        # duplicating records (atomic locally; on object stores the rename is
        # delete+copy, so duplicates overwrite but the final shard may be
        # transiently absent — see tfrecord.rename)
        final = "{}/part-r-{:05d}".format(output_dir.rstrip("/"), pidx)
        tmp = final + "." + _uuid.uuid4().hex[:8] + ".tmp"
        n = tfrecord.write_shard(tmp, examples)
        tfrecord.rename(tmp, final)
        return [n]

    rdd = df.rdd
    counts = rdd.mapPartitionsWithIndex(_write_partition).collect()
    logger.info("wrote %d records in %d shards to %s", sum(counts), len(counts), output_dir)
    return output_dir


def loadTFRecords(sc, input_dir, binary_features=(), columns=None):
    """Read TFRecord shards back into a DataFrame (reference dfutil.py:44-81):
    schema inferred from the first record, provenance recorded in
    ``loadedDF``."""
    if not tfrecord.is_uri(input_dir):
        input_dir = os.path.abspath(os.path.expanduser(input_dir))
    shards = tfrecord.list_shards(input_dir)
    if not shards:
        raise FileNotFoundError("no TFRecord shards under {}".format(input_dir))
    bin_feats = tuple(binary_features)

    if columns is None:
        # union the schema over the whole first shard plus the first record of
        # every other shard: a None value makes toTFExample omit that column
        # from a record, so no single record (or single shard) is a reliable
        # schema witness
        names = set()
        for example in tfrecord.read_examples(shards[0]):
            names.update(infer_schema(example, bin_feats))
        for path in shards[1:]:
            try:
                names.update(infer_schema(next(tfrecord.read_examples(path)), bin_feats))
            except StopIteration:
                pass
        columns = sorted(names)

    def _read_shard(it):
        rows = []
        for path in it:
            for example in tfrecord.read_examples(path):
                rows.append(fromTFExample(example, columns, bin_feats))
        return rows

    rdd = sc.parallelize(shards, len(shards)).mapPartitions(_read_shard)
    if hasattr(sc, "createDataFrame"):  # local backend: wrap the lazy RDD
        from tensorflowonspark_tpu.backends.local import LocalDataFrame

        df = LocalDataFrame(rdd, columns)
    else:  # pyspark SparkContext: go through the session
        from pyspark.sql import SparkSession

        df = SparkSession.builder.getOrCreate().createDataFrame(rdd, columns)
    loadedDF[id(df)] = df
    _loaded_dirs[id(df)] = input_dir
    weakref.finalize(df, _loaded_dirs.pop, id(df), None)
    logger.info("loaded %d shards from %s as columns %s", len(shards), input_dir, columns)
    return df
