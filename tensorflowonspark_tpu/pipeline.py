"""ML-pipeline layer: Estimator/Model wrappers over the cluster runtime.

Capability-parity with /root/reference/tensorflowonspark/pipeline.py: the same
``Has*`` param-mixin surface (pipeline.py:49-293), the ``Namespace``
args adapter (pipeline.py:296-336), ``merge_args_params`` (pipeline.py:343),
``TFEstimator._fit`` spinning up a cluster over the input DataFrame
(pipeline.py:392-432), and ``TFModel._transform`` running single-process
batch inference per executor with input/output column↔tensor mappings and a
per-worker model cache (pipeline.py:435-644).

TPU-native differences: the trained artifact is a jax **model bundle**
(:mod:`tensorflowonspark_tpu.train.export`: orbax checkpoint + pickled
predict-fn builder) rather than a TF SavedModel; ``protocol`` selects
ICI/DCN behavior rather than grpc/RDMA; inference executors run the bundle on
whatever platform they have (CPU executors included).

When pyspark is installed, :class:`TFEstimator`/:class:`TFModel` subclass
``pyspark.ml.Estimator``/``pyspark.ml.Model`` (the reference subclassed them
too, pipeline.py:349,433), so they pass ``pyspark.ml.Pipeline``'s isinstance
checks and sit in real ML pipelines. Without pyspark the bases degrade to
``object`` and everything runs against the local backend's ``LocalDataFrame``.
"""

import argparse
import logging

logger = logging.getLogger(__name__)

try:  # real pyspark.ml citizenship when pyspark is importable
    from pyspark.ml import Estimator as _MLEstimatorBase
    from pyspark.ml import Model as _MLModelBase
except Exception:  # local backend: no pyspark dependency

    class _MLEstimatorBase:
        pass

    class _MLModelBase:
        pass


# -- param plumbing (pyspark.ml.param.Param equivalent) ------------------------


def _nullable_str(value):
    """str converter that keeps None as None: str(None) == "None" would turn
    e.g. setMasterNode(None) into a bogus 'None' cluster role, and
    setModelDir(None) into a directory literally named None."""
    return None if value is None else str(value)


class Param:
    def __init__(self, name, doc, converter=None):
        self.name = name
        self.doc = doc
        self.converter = converter

    def __repr__(self):
        return "Param({})".format(self.name)


class Params:
    """Minimal pyspark.ml.param.Params: typed params with defaults + setters.

    When the pyspark bases are live, their ``Params``/``Identifiable`` chain
    runs first (sets ``uid`` and pyspark's own empty maps) and then this
    class installs its string-keyed maps; the accessors defined here shadow
    pyspark's Param-object-keyed machinery throughout (``_param_index`` is
    deliberately not named ``_params`` — pyspark's ``Params.__init__`` sets
    an instance attribute of that name which would shadow a method).
    """

    def __init__(self):
        super().__init__()
        self._paramMap = {}
        self._defaultParamMap = {}

    def _param_index(self):
        out = {}
        for klass in type(self).__mro__:
            for name, val in vars(klass).items():
                if isinstance(val, Param):
                    out[val.name] = val
        return out

    def _set(self, **kwargs):
        params = self._param_index()
        for name, value in kwargs.items():
            if name not in params:
                raise ValueError("unknown param {!r}".format(name))
            p = params[name]
            self._paramMap[p.name] = p.converter(value) if p.converter else value
        return self

    def _setDefault(self, **kwargs):
        for name, value in kwargs.items():
            self._defaultParamMap[name] = value
        return self

    def getOrDefault(self, param):
        name = param.name if isinstance(param, Param) else param
        if name in self._paramMap:
            return self._paramMap[name]
        return self._defaultParamMap.get(name)

    def isDefined(self, param):
        name = param.name if isinstance(param, Param) else param
        return name in self._paramMap or name in self._defaultParamMap

    def extractParamMap(self, extra=None):
        """Defaults overlaid with explicit settings, then ``extra``.

        ``extra`` accepts pyspark's dict-of-Param (or string) keys —
        ``Pipeline.copy()`` / ML persistence call
        ``extractParamMap(extra)``, so refusing the argument would
        TypeError inside pyspark internals."""
        out = dict(self._defaultParamMap)
        out.update(self._paramMap)
        if extra:
            for k, v in extra.items():
                out[k.name if isinstance(k, Param) else k] = v
        return out

    def copyParamsTo(self, other):
        other._paramMap.update(self._paramMap)
        other._defaultParamMap.update(self._defaultParamMap)
        return other


def _toDict(value):
    """reference TFTypeConverters.toDict (pipeline.py:39-46)."""
    if not isinstance(value, dict):
        raise TypeError("expected a dict, got {!r}".format(type(value)))
    return value


# -- Has* mixins: the reference's 17 (pipeline.py:49-293) ----------------------


class HasBatchSize(Params):
    batch_size = Param("batch_size", "number of records per batch", int)

    def __init__(self):
        super().__init__()
        self._setDefault(batch_size=100)

    def setBatchSize(self, value):
        return self._set(batch_size=value)

    def getBatchSize(self):
        return self.getOrDefault("batch_size")


class HasClusterSize(Params):
    cluster_size = Param("cluster_size", "number of nodes in the cluster", int)

    def __init__(self):
        super().__init__()
        self._setDefault(cluster_size=1)

    def setClusterSize(self, value):
        return self._set(cluster_size=value)

    def getClusterSize(self):
        return self.getOrDefault("cluster_size")


class HasEpochs(Params):
    epochs = Param("epochs", "number of epochs to train", int)

    def __init__(self):
        super().__init__()
        self._setDefault(epochs=1)

    def setEpochs(self, value):
        return self._set(epochs=value)

    def getEpochs(self):
        return self.getOrDefault("epochs")


class HasGraceSecs(Params):
    grace_secs = Param("grace_secs", "seconds to wait after feeding (for final export)", int)

    def __init__(self):
        super().__init__()
        self._setDefault(grace_secs=30)

    def setGraceSecs(self, value):
        return self._set(grace_secs=value)

    def getGraceSecs(self):
        return self.getOrDefault("grace_secs")


class HasInputMapping(Params):
    input_mapping = Param("input_mapping", "mapping of input DataFrame column to input tensor", _toDict)

    def __init__(self):
        super().__init__()

    def setInputMapping(self, value):
        return self._set(input_mapping=value)

    def getInputMapping(self):
        return self.getOrDefault("input_mapping")


class HasInputMode(Params):
    input_mode = Param("input_mode", "input data feeding mode (InputMode.SPARK only here)", int)

    def __init__(self):
        super().__init__()
        from tensorflowonspark_tpu.TFCluster import InputMode

        self._setDefault(input_mode=InputMode.SPARK)

    def setInputMode(self, value):
        from tensorflowonspark_tpu.TFCluster import InputMode

        if value != InputMode.SPARK:
            # the reference rejects TENSORFLOW mode in pipelines too
            # (pipeline.py:121-124)
            raise ValueError("TFEstimator only supports InputMode.SPARK")
        return self._set(input_mode=value)

    def getInputMode(self):
        return self.getOrDefault("input_mode")


class HasMasterNode(Params):
    master_node = Param("master_node", "job name of the master/chief node", _nullable_str)

    def __init__(self):
        super().__init__()
        self._setDefault(master_node="chief")

    def setMasterNode(self, value):
        return self._set(master_node=value)

    def getMasterNode(self):
        return self.getOrDefault("master_node")


class HasModelDir(Params):
    model_dir = Param("model_dir", "directory to write checkpoints", _nullable_str)

    def __init__(self):
        super().__init__()

    def setModelDir(self, value):
        return self._set(model_dir=value)

    def getModelDir(self):
        return self.getOrDefault("model_dir")


class HasNumPS(Params):
    num_ps = Param("num_ps", "number of ps nodes (API compat; no PS on TPU)", int)
    driver_ps_nodes = Param("driver_ps_nodes", "run ps nodes on driver (unsupported)", bool)

    def __init__(self):
        super().__init__()
        self._setDefault(num_ps=0, driver_ps_nodes=False)

    def setNumPS(self, value):
        return self._set(num_ps=value)

    def getNumPS(self):
        return self.getOrDefault("num_ps")

    def setDriverPSNodes(self, value):
        return self._set(driver_ps_nodes=value)

    def getDriverPSNodes(self):
        return self.getOrDefault("driver_ps_nodes")


class HasOutputMapping(Params):
    output_mapping = Param("output_mapping", "mapping of output tensor to output DataFrame column", _toDict)

    def __init__(self):
        super().__init__()

    def setOutputMapping(self, value):
        return self._set(output_mapping=value)

    def getOutputMapping(self):
        return self.getOrDefault("output_mapping")


class HasProtocol(Params):
    protocol = Param(
        "protocol",
        "fabric selection: 'ici' (single slice; default) | 'dcn' (cross-host/"
        "slice: forces the jax.distributed world on). Reference: grpc/rdma",
        str,
    )

    def __init__(self):
        super().__init__()
        self._setDefault(protocol="ici")

    def setProtocol(self, value):
        return self._set(protocol=value)

    def getProtocol(self):
        return self.getOrDefault("protocol")


class HasReaders(Params):
    readers = Param(
        "readers",
        "input-pipeline reader/parse threads per node (lands in the jax "
        "children as TOS_DATA_THREADS, the data.ImagePipeline default)",
        int,
    )

    def __init__(self):
        super().__init__()
        self._setDefault(readers=1)

    def setReaders(self, value):
        return self._set(readers=value)

    def getReaders(self):
        return self.getOrDefault("readers")


class HasSteps(Params):
    steps = Param("steps", "maximum number of steps to train", int)

    def __init__(self):
        super().__init__()
        self._setDefault(steps=1000)

    def setSteps(self, value):
        return self._set(steps=value)

    def getSteps(self):
        return self.getOrDefault("steps")


class HasTensorboard(Params):
    tensorboard = Param("tensorboard", "launch tensorboard/profiler on chief", bool)

    def __init__(self):
        super().__init__()
        self._setDefault(tensorboard=False)

    def setTensorboard(self, value):
        return self._set(tensorboard=value)

    def getTensorboard(self):
        return self.getOrDefault("tensorboard")


class HasTFRecordDir(Params):
    tfrecord_dir = Param("tfrecord_dir", "directory of TFRecords to use as input", _nullable_str)

    def __init__(self):
        super().__init__()

    def setTFRecordDir(self, value):
        return self._set(tfrecord_dir=value)

    def getTFRecordDir(self):
        return self.getOrDefault("tfrecord_dir")


class HasExportDir(Params):
    export_dir = Param("export_dir", "directory to export the trained model bundle", _nullable_str)

    def __init__(self):
        super().__init__()

    def setExportDir(self, value):
        return self._set(export_dir=value)

    def getExportDir(self):
        return self.getOrDefault("export_dir")


class HasSignatureDefKey(Params):
    signature_def_key = Param("signature_def_key", "bundle signature to use (API compat)", _nullable_str)

    def __init__(self):
        super().__init__()
        self._setDefault(signature_def_key="serving_default")

    def setSignatureDefKey(self, value):
        return self._set(signature_def_key=value)

    def getSignatureDefKey(self):
        return self.getOrDefault("signature_def_key")


class HasTagSet(Params):
    tag_set = Param("tag_set", "bundle tag set (API compat)", _nullable_str)

    def __init__(self):
        super().__init__()
        self._setDefault(tag_set="serve")

    def setTagSet(self, value):
        return self._set(tag_set=value)

    def getTagSet(self):
        return self.getOrDefault("tag_set")


class Namespace(object):
    """argparse.Namespace-alike accepting dict / Namespace / argv list
    (reference pipeline.py:296-336)."""

    def __init__(self, d=None):
        if d is None:
            return
        if isinstance(d, dict):
            self.__dict__.update(d)
        elif isinstance(d, argparse.Namespace) or isinstance(d, Namespace):
            self.__dict__.update(vars(d))
        elif isinstance(d, (list, tuple)):
            self.argv = list(d)
        else:
            raise TypeError("unsupported Namespace source: {!r}".format(type(d)))

    def __contains__(self, item):
        return item in self.__dict__

    def __iter__(self):
        return iter(self.__dict__)

    def __repr__(self):
        return "Namespace({})".format(self.__dict__)


class TFParams(Params):
    """Base for estimator/model: merges argparse-style args with ML params
    (params win — reference pipeline.py:339-348)."""

    args = None

    def merge_args_params(self):
        args = Namespace(vars(self.args) if self.args is not None else {})
        for name, value in self.extractParamMap().items():
            setattr(args, name, value)
        return args


class TFEstimator(TFParams, HasBatchSize, HasClusterSize, HasEpochs, HasGraceSecs,
                  HasInputMapping, HasInputMode, HasMasterNode, HasModelDir, HasNumPS,
                  HasProtocol, HasReaders, HasSteps, HasTensorboard, HasTFRecordDir,
                  HasExportDir, _MLEstimatorBase):
    """Spark-ML Estimator (a real ``pyspark.ml.Estimator`` subclass when
    pyspark is installed): ``fit(df)`` trains ``train_fn`` on a cluster
    fed from the DataFrame and returns a :class:`TFModel`
    (reference pipeline.py:351-432).

    ``train_fn(args, ctx)`` is the user's ``main_fun``; it should honor
    ``args.batch_size`` / ``args.steps`` / ``args.export_dir`` and export a
    model bundle (``tensorflowonspark_tpu.train.export.export_model``) on the
    chief when feeding ends.
    """

    def __init__(self, train_fn, tf_args=None, export_fn=None, env=None, jax_distributed=None,
                 obs=None):
        """``env``/``jax_distributed``/``obs`` forward to ``TFCluster.run``
        (e.g. ``env={"JAX_PLATFORMS": "cpu"}`` for CPU clusters; ``obs=False``
        turns the observability plane off for this estimator's clusters)."""
        # cooperative super: every Has* mixin sets its defaults, Params (the
        # MRO root before object) creates the maps first
        super().__init__()
        self.train_fn = train_fn
        self.export_fn = export_fn
        self.env = env
        self.jax_distributed = jax_distributed
        self.obs = obs
        #: merged cluster metrics snapshot captured at the end of the last
        #: ``fit`` (before shutdown); None until a fit completes
        self.cluster_metrics_ = None
        self.args = Namespace(tf_args) if tf_args is not None else Namespace({})

    def fit(self, dataset, params=None):
        # pyspark's Estimator.fit(params=dict) copies the stage; here extra
        # params are applied in place (this estimator's maps are string-keyed)
        if isinstance(params, (list, tuple)):
            # pyspark's list-of-param-maps form (CrossValidator et al.) wants
            # one trained model per map — each map here is a full cluster
            # run; refuse clearly rather than AttributeError on .items()
            raise NotImplementedError(
                "TFEstimator.fit does not support a list of param maps; fit "
                "once per configuration (each fit is a full cluster run)"
            )
        if params:
            # pyspark fits a COPY carrying the extra params; match that
            # observable contract by restoring the pre-call map afterwards
            # instead of letting call-scoped params stick to the stage
            saved = dict(self._paramMap)
            self._set(**{(k.name if isinstance(k, Param) else k): v
                         for k, v in params.items()})
            try:
                return self._fit(dataset)
            finally:
                self._paramMap = saved
        return self._fit(dataset)

    def _fit(self, dataset):
        from tensorflowonspark_tpu import TFCluster

        args = self.merge_args_params()
        logger.info("TFEstimator.fit: cluster_size=%s epochs=%s batch_size=%s",
                    args.cluster_size, args.epochs, args.batch_size)

        input_cols = sorted(args.input_mapping)
        rdd = dataset.rdd
        sc = getattr(rdd, "_sc", None)  # local backend
        if sc is None:
            sc = rdd.context  # real pyspark

        tfrecord_dir = getattr(args, "tfrecord_dir", None)
        if tfrecord_dir:
            # materialize the input DataFrame as TFRecord shards
            # (reference dfutil flow), provenance-aware: a DataFrame that was
            # LOADED from this very directory is not re-written (reference
            # loadedDF registry, dfutil.py:15-26). The feed then reads the
            # materialized shards, so the source DataFrame is evaluated at
            # most once per fit.
            import os as _os

            from tensorflowonspark_tpu import dfutil, tfrecord

            if not tfrecord.is_uri(tfrecord_dir):  # match loadTFRecords' form
                tfrecord_dir = _os.path.abspath(_os.path.expanduser(tfrecord_dir))
            if dfutil.isLoadedDF(dataset) and dfutil.loadedDFSource(dataset) == tfrecord_dir:
                logger.info("input DataFrame already lives at %s; reusing", tfrecord_dir)
            else:
                dfutil.saveAsTFRecords(dataset, tfrecord_dir)
            # feed from the shards, not the source DataFrame: no second
            # evaluation of an expensive input
            dataset = dfutil.loadTFRecords(sc, tfrecord_dir, columns=list(dataset.columns))

        env = dict(self.env or {})
        if getattr(args, "readers", 0):
            # `readers` → input-pipeline thread count in the jax children
            # (tensorflowonspark_tpu.data.ImagePipeline default; reference
            # HasReaders controlled the enqueue-thread count)
            env.setdefault("TOS_DATA_THREADS", str(args.readers))
        jax_distributed = self.jax_distributed
        if jax_distributed is None and getattr(args, "protocol", "ici") == "dcn":
            # 'dcn' = the cluster spans hosts/slices: the cross-process
            # jax.distributed world is mandatory (reference: protocol chose
            # the grpc vs grpc+verbs transport, TFNode.py:126-129)
            jax_distributed = True
        cluster = TFCluster.run(
            sc, self.train_fn, args, args.cluster_size, num_ps=args.num_ps,
            tensorboard=args.tensorboard, input_mode=TFCluster.InputMode.SPARK,
            master_node=args.master_node, driver_ps_nodes=args.driver_ps_nodes,
            env=env or None, jax_distributed=jax_distributed, obs=self.obs,
        )
        cluster.train(dataset.select(input_cols).rdd, args.epochs)
        try:
            # capture while node channels are still up — after shutdown the
            # executor managers (and their published snapshots) are gone
            self.cluster_metrics_ = cluster.metrics()
        except Exception as e:
            logger.debug("could not capture cluster metrics: %s", e)
        cluster.shutdown(grace_secs=args.grace_secs)

        model = TFModel(self.args)
        self.copyParamsTo(model)
        return model


class TFModel(TFParams, HasBatchSize, HasInputMapping, HasOutputMapping, HasModelDir,
              HasExportDir, HasSignatureDefKey, HasTagSet, _MLModelBase):
    """Spark-ML Model (a real ``pyspark.ml.Model``/``Transformer`` subclass
    when pyspark is installed): ``transform(df)`` runs batch inference from
    the exported bundle in each executor's python worker, no cluster needed
    (reference pipeline.py:435-644)."""

    def __init__(self, tf_args=None):
        super().__init__()
        self.args = Namespace(tf_args) if tf_args is not None else Namespace({})

    def transform(self, dataset, params=None):
        if params:
            # call-scoped extra params, same restore contract as fit()
            saved = dict(self._paramMap)
            self._set(**{(k.name if isinstance(k, Param) else k): v
                         for k, v in params.items()})
            try:
                return self._transform(dataset)
            finally:
                self._paramMap = saved
        return self._transform(dataset)

    def _transform(self, dataset):
        args = self.merge_args_params()
        logger.info("TFModel.transform: batch_size=%s export_dir=%s",
                    args.batch_size, getattr(args, "export_dir", None))
        input_cols = sorted(args.input_mapping)
        tensor_names = [args.input_mapping[c] for c in input_cols]
        output_items = sorted((args.output_mapping or {"output": "prediction"}).items())
        output_tensors = [t for t, _ in output_items]
        output_cols = [c for _, c in output_items]
        task = _RunModel(
            export_dir=getattr(args, "export_dir", None) or getattr(args, "model_dir", None),
            batch_size=args.batch_size,
            tensor_names=tensor_names,
            output_tensors=output_tensors,
        )
        rows = dataset.select(input_cols).rdd.mapPartitions(task)
        return _build_dataframe(dataset, rows, output_cols)


def _build_dataframe(source_df, rows, output_cols):
    rdd = rows
    # local backend: wrap back into a LocalDataFrame; pyspark: createDataFrame
    sc = getattr(rdd, "_sc", None)
    if sc is not None and hasattr(sc, "createDataFrame"):
        from tensorflowonspark_tpu.backends.local import LocalDataFrame

        return LocalDataFrame(rdd, output_cols)
    # df.sparkSession is the Spark>=3.3 surface; sql_ctx was removed in
    # Spark 4 (kept as the fallback for older pyspark)
    spark = getattr(source_df, "sparkSession", None) or getattr(source_df, "sql_ctx", None)
    if spark is not None:
        return spark.createDataFrame(rdd, output_cols)
    return rdd


#: per-worker-process model cache (reference pred_fn/global_args cache,
#: pipeline.py:492-496): transform tasks landing on the same executor reuse
#: the loaded bundle instead of re-reading it per partition
_model_cache = {}


class _RunModel:
    """mapPartitions closure: batches rows → predict_fn → output rows
    (reference _run_model_tf2, pipeline.py:585-644)."""

    def __init__(self, export_dir, batch_size, tensor_names, output_tensors):
        if not export_dir:
            raise ValueError("TFModel needs export_dir (or model_dir) pointing at a model bundle")
        self.export_dir = export_dir
        self.batch_size = batch_size
        self.tensor_names = tensor_names
        self.output_tensors = output_tensors

    def __call__(self, iterator):
        import numpy as np

        bundle = _model_cache.get(self.export_dir)
        if bundle is None:
            from tensorflowonspark_tpu.train import export as export_lib

            bundle = export_lib.load_model(self.export_dir)
            _model_cache[self.export_dir] = bundle
        predict_fn, params, model_state = bundle

        results = []
        for batch in yield_batch(iterator, self.batch_size):
            n = len(batch)
            cols = list(zip(*batch))
            arrays = {
                name: np.asarray(col) for name, col in zip(self.tensor_names, cols)
            }
            # pad the final partial batch so jit sees one shape, then truncate
            if n < self.batch_size:
                arrays = {
                    k: np.concatenate([v, np.repeat(v[-1:], self.batch_size - n, axis=0)])
                    for k, v in arrays.items()
                }
            out = predict_fn(params, model_state, arrays)
            if not isinstance(out, dict):
                out = {self.output_tensors[0]: out}
            out_cols = [np.asarray(out[t])[:n] for t in self.output_tensors]
            for row in zip(*[c.tolist() for c in out_cols]):
                results.append(tuple(row))
        return results


def yield_batch(iterator, batch_size):
    """Group an iterator of rows into lists of ≤ batch_size
    (reference pipeline.py:688-710)."""
    batch = []
    for row in iterator:
        batch.append(row)
        if len(batch) >= batch_size:
            yield batch
            batch = []
    if batch:
        yield batch
