"""Per-process flight recorder: a crash-safe JSONL ring of spans/events.

Every traced process (driver, Spark executor, jax child, forked decode
worker, serving replica) owns one **shard** — a directory under
``TOS_TRACE_DIR`` named ``<host>-<pid>-<proc>`` — and appends framed JSONL
records to it.  The format is built from two idioms that already survive
crash tests elsewhere in the tree:

* **CRC line framing** (the membership registry's journal,
  :mod:`tensorflowonspark_tpu.registry`): every line is
  ``"{crc32:08x} {json}\\n"``.  A reader stops at the first torn or
  corrupt line and keeps the intact prefix — a process SIGKILLed mid-write
  loses at most its final line.
* **tmp+rename segment commit** (:mod:`tensorflowonspark_tpu.ckpt.manifest`):
  the active segment is ``seg-NNNNNN.open``; when it reaches the size bound
  it is flushed, fsynced, and *renamed* to ``seg-NNNNNN.jsonl``.  Sealed
  segments are therefore always whole; only the ``.open`` tail can tear.

The ring is bounded twice over: segments are size-bounded
(``TOS_TRACE_SEG_BYTES``, default 1 MiB) and the shard keeps at most
``TOS_TRACE_SEGMENTS`` sealed segments (default 8), deleting the oldest —
so a runaway loop cannot fill a disk, and the *most recent* history is what
survives.  Because the oldest segment may have been pruned, every segment
opens with its own ``meta`` header record (host, pid, proc label, trace id,
a paired wall/monotonic clock sample, and the current clock offset), keeping
any surviving segment self-describing for the merger.

:meth:`FlightRecorder.dump` is the black-box moment: it appends a ``dump``
marker record and fsyncs the active segment.  It is invoked on chaos fault
injection (:func:`tensorflowonspark_tpu.chaos._record`), on
``FailureEvent`` classification in the elastic ladder, and on unhandled
jax-child exit — so every recovery leaves a flight recording behind.

Fork safety: :class:`FlightRecorder` remembers the pid that opened it.  A
forked child (the decode plane uses the ``fork`` start method) that inherits
the module-global recorder re-opens a *new* shard directory for its own pid
on first write, and abandons — without flushing — the inherited file object,
so the parent's buffered bytes are never duplicated into the parent's file.
"""

import json
import os
import socket
import threading
import time
import zlib

from tensorflowonspark_tpu import durable
from tensorflowonspark_tpu.obs import registry as _registry

#: env var naming the root directory all shards are written under; unset
#: means the flight recorder (and the whole tracing plane) is inert
TRACE_DIR_ENV = "TOS_TRACE_DIR"

#: active-segment size bound before seal+rotate (bytes)
SEG_BYTES_ENV = "TOS_TRACE_SEG_BYTES"
DEFAULT_SEG_BYTES = 1 << 20

#: sealed segments retained per shard (oldest pruned beyond this)
SEGMENTS_ENV = "TOS_TRACE_SEGMENTS"
DEFAULT_SEGMENTS = 8


def _frame(payload):
    """CRC-frame one JSON payload line (the registry-journal idiom)."""
    return "{:08x} {}\n".format(zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF, payload)


def _unframe(line):
    """Return the decoded record, or None for a torn/corrupt line."""
    line = line.rstrip("\n")
    if not line:
        return None
    parts = line.split(" ", 1)
    if len(parts) != 2 or len(parts[0]) != 8:
        return None
    try:
        want = int(parts[0], 16)
    except ValueError:
        return None
    if zlib.crc32(parts[1].encode("utf-8")) & 0xFFFFFFFF != want:
        return None
    try:
        return json.loads(parts[1])
    except ValueError:
        return None


class FlightRecorder:
    """Appends framed records to a ring of segments in one shard directory."""

    def __init__(self, root, proc, trace_id=None, clock_offset=0.0,
                 max_segment_bytes=None, max_segments=None):
        self.root = root
        self.proc = proc
        self.trace_id = trace_id
        self.clock_offset = float(clock_offset)
        self.max_segment_bytes = int(
            max_segment_bytes
            if max_segment_bytes is not None
            else os.environ.get(SEG_BYTES_ENV, DEFAULT_SEG_BYTES)
        )
        self.max_segments = int(
            max_segments
            if max_segments is not None
            else os.environ.get(SEGMENTS_ENV, DEFAULT_SEGMENTS)
        )
        self._lock = threading.Lock()
        self._pid = None
        self._fh = None
        self._seg_index = 0
        self._seg_bytes = 0
        self._records = _registry.counter(
            "flight_records_total", help="records appended to the local flight shard"
        )
        self._dumps = _registry.counter(
            "flight_dumps_total", help="flight-recorder ring dumps (black-box flushes)"
        )
        self._open_for_pid()

    # -- shard/segment lifecycle --------------------------------------------

    @property
    def shard_dir(self):
        return os.path.join(
            self.root, "{}-{}-{}".format(socket.gethostname(), self._pid, self.proc)
        )

    def _open_for_pid(self):
        self._pid = os.getpid()
        os.makedirs(self.shard_dir, exist_ok=True)
        self._seg_index = 0
        self._open_segment()

    def _seg_path(self, sealed):
        return os.path.join(
            self.shard_dir,
            "seg-{:06d}.{}".format(self._seg_index, "jsonl" if sealed else "open"),
        )

    def _open_segment(self):
        self._fh = open(self._seg_path(sealed=False), "a", encoding="utf-8")
        self._seg_bytes = 0
        self._write_locked(self._header())

    def _header(self):
        return {
            "kind": "meta",
            "v": 1,
            "host": socket.gethostname(),
            "pid": self._pid,
            "proc": self.proc,
            "trace": self.trace_id,
            "wall": time.time(),
            "mono": time.monotonic(),
            "clock_off": self.clock_offset,
        }

    def _seal_locked(self):
        """Commit the active segment: flush+fsync, then rename .open -> .jsonl
        (the ckpt/manifest.py commit idiom — rename is the publish)."""
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self._fh.close()
        os.rename(self._seg_path(sealed=False), self._seg_path(sealed=True))
        # the crash that the flight recorder exists for is exactly the one
        # that loses an unfsynced directory entry: seal durably or the
        # post-mortem merge sees a gap where the final segment was
        durable.fsync_dir(self.shard_dir)
        self._seg_index += 1
        self._open_segment()
        self._prune_locked()

    def _prune_locked(self):
        sealed = sorted(
            f for f in os.listdir(self.shard_dir)
            if f.startswith("seg-") and f.endswith(".jsonl")
        )
        for victim in sealed[: max(0, len(sealed) - self.max_segments)]:
            try:
                os.unlink(os.path.join(self.shard_dir, victim))
            except OSError:
                pass

    # -- writes --------------------------------------------------------------

    def _write_locked(self, record):
        line = _frame(json.dumps(record, sort_keys=True, separators=(",", ":")))
        self._fh.write(line)
        self._seg_bytes += len(line.encode("utf-8"))

    def append(self, record):
        """Append one record dict (a ``kind`` key identifies the type)."""
        with self._lock:
            if os.getpid() != self._pid:
                # forked child: abandon the inherited file object WITHOUT
                # flushing (its buffer holds a copy of the parent's pending
                # bytes) and start a fresh shard for this pid
                self._fh = None
                self._open_for_pid()
            self._write_locked(record)
            self._fh.flush()
            if self._seg_bytes >= self.max_segment_bytes:
                self._seal_locked()
        self._records.inc()

    def dump(self, reason):
        """Black-box flush: append a ``dump`` marker and fsync the tail."""
        self.append({"kind": "dump", "reason": reason, "ts": time.time()})
        with self._lock:
            if self._fh is not None and os.getpid() == self._pid:
                self._fh.flush()
                os.fsync(self._fh.fileno())
        self._dumps.inc()

    def set_clock_offset(self, offset, rtt=None):
        """Record a measured wall-clock offset (local + offset = driver time);
        future segment headers carry it too."""
        self.clock_offset = float(offset)
        rec = {"kind": "clock", "offset_s": self.clock_offset, "ts": time.time()}
        if rtt is not None:
            rec["rtt_s"] = float(rtt)
        self.append(rec)

    def close(self):
        with self._lock:
            if self._fh is not None and os.getpid() == self._pid:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._fh.close()
            self._fh = None


# -- readers (used by the exporter /trace endpoint and tracemerge) -----------


def read_segment(path):
    """Parse one segment file.

    Returns ``(records, torn)`` where ``torn`` counts lines at/after the
    first framing failure — those (and everything following, which can no
    longer be trusted to be aligned) are discarded, keeping the intact
    prefix, exactly like the membership-registry journal replay.
    """
    records, torn = [], 0
    try:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            lines = f.readlines()
    except OSError:
        return records, torn
    for i, line in enumerate(lines):
        rec = _unframe(line)
        if rec is None:
            torn = len(lines) - i
            break
        records.append(rec)
    return records, torn


def read_shard(shard_dir):
    """All surviving records of one shard, sealed segments then open tail."""
    try:
        names = os.listdir(shard_dir)
    except OSError:
        return [], 0
    segs = sorted(n for n in names if n.startswith("seg-") and n.endswith(".jsonl"))
    segs += sorted(n for n in names if n.startswith("seg-") and n.endswith(".open"))
    records, torn = [], 0
    for name in segs:
        recs, t = read_segment(os.path.join(shard_dir, name))
        records.extend(recs)
        torn += t
    return records, torn


def list_shards(root):
    """Shard directories under a trace root (any dir holding seg files)."""
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return []
    out = []
    for name in entries:
        path = os.path.join(root, name)
        if not os.path.isdir(path):
            continue
        try:
            if any(n.startswith("seg-") for n in os.listdir(path)):
                out.append(path)
        except OSError:
            continue
    return out


# -- module-global recorder ---------------------------------------------------

_recorder = None
_rec_lock = threading.Lock()


def configure(root, proc, trace_id=None, clock_offset=0.0):
    """Open (or replace) the process-global recorder. Called at each process
    tier's entry point via :func:`tensorflowonspark_tpu.obs.tracing.install_from_env`."""
    global _recorder
    with _rec_lock:
        if _recorder is not None:
            _recorder.close()
        _recorder = FlightRecorder(root, proc, trace_id=trace_id, clock_offset=clock_offset)
        return _recorder


def current(create=True):
    """The process-global recorder, lazily created from ``TOS_TRACE_DIR``
    (with a generic proc label) so dump triggers work even in processes that
    never called an explicit install. None when tracing is inert."""
    global _recorder
    with _rec_lock:
        if _recorder is None and create:
            root = os.environ.get(TRACE_DIR_ENV)
            if root and _registry.enabled():
                _recorder = FlightRecorder(
                    root,
                    os.environ.get("TOS_TRACE_PROC", "proc"),
                    trace_id=os.environ.get("TOS_TRACE_ID"),
                    clock_offset=float(os.environ.get("TOS_TRACE_CLOCK_OFF", "0") or 0.0),
                )
        return _recorder


def dump(reason):
    """Dump the process-global recorder, if the tracing plane is active."""
    rec = current()
    if rec is not None:
        rec.dump(reason)


def reset():
    """Drop the process-global recorder (tests)."""
    global _recorder
    with _rec_lock:
        if _recorder is not None:
            _recorder.close()
        _recorder = None
