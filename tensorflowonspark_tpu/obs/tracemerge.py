"""Merge per-process flight shards into one Chrome-trace-event JSON.

``python -m tensorflowonspark_tpu.obs.tracemerge --dir $TOS_TRACE_DIR --out
trace.json`` walks every shard under the trace root
(:func:`tensorflowonspark_tpu.obs.flight.list_shards`), aligns each shard's
wall clock onto the driver's, and emits a single ``{"traceEvents": [...]}``
document loadable by Perfetto / ``chrome://tracing``.

Clock alignment.  The driver's clock is the reference (offset 0).  Every
other shard resolves its offset in priority order: the lowest-RTT ``clock``
record journaled by :func:`tensorflowonspark_tpu.obs.tracing.observe_clock`
(NTP-style midpoint estimate from the reservation REG round-trip), else the
``clock_off`` carried by the newest segment ``meta`` header (inherited by
same-host children via ``TOS_TRACE_CLOCK_OFF``), else 0.

Track layout.  Each shard becomes one Chrome *process* (``M``
``process_name`` metadata from its ``meta`` header).  Context-manager spans
are emitted as matched ``B``/``E`` pairs on their recording thread's track;
retroactive spans carrying a ``track`` label (the ``BucketedOverlap`` comm
spans) land on a dedicated named track as ``X`` complete events, so the
comm/compute overlap the ``comm_overlap_fraction`` gauge reports is directly
visible — and :func:`overlap_fraction` recomputes it from the drawn spans
alone so the two can be cross-checked.

Nesting repair.  Span starts are wall-clock but durations are monotonic
(NTP steps must not corrupt durations — see ``obs/trace.py``), so a child's
computed end can jitter past its parent's by microseconds.  Before emitting
``B``/``E`` pairs the merger clamps each span into its enclosing interval,
restoring a proper bracket sequence per track.
"""

import argparse
import json
import os
import sys

from tensorflowonspark_tpu.obs import flight

#: synthetic Chrome tid for retro comm-track spans (real thread ids are
#: os-assigned and never this large on Linux, whose pid space caps at 2^22)
COMM_TID = 9_000_000
WINDOW_TID = 9_000_001

_TRACK_TIDS = {"comm": COMM_TID, "comm_window": WINDOW_TID}
_TRACK_NAMES = {
    COMM_TID: "comm (bucketed all-reduce)",
    WINDOW_TID: "comm overlap windows",
}


def resolve_offset(records):
    """The shard's wall-clock offset onto driver time (seconds to add)."""
    best_off, best_rtt = None, None
    meta_off = 0.0
    for rec in records:
        kind = rec.get("kind")
        if kind == "clock":
            rtt = rec.get("rtt_s")
            if best_rtt is None or (rtt is not None and rtt < best_rtt):
                best_rtt = rtt
                best_off = rec.get("offset_s", 0.0)
        elif kind == "meta":
            meta_off = rec.get("clock_off", meta_off) or 0.0
    return float(best_off if best_off is not None else meta_off)


def _clamp_nesting(spans):
    """Clamp each span's end into its enclosing span so the B/E bracket
    sequence is well formed despite wall/monotonic micro-jitter."""
    spans = sorted(spans, key=lambda s: (s["_b"], -(s["_e"] - s["_b"])))
    stack = []
    for s in spans:
        while stack and s["_b"] >= stack[-1]["_e"]:
            stack.pop()
        if stack and s["_e"] > stack[-1]["_e"]:
            s["_e"] = stack[-1]["_e"]
        if stack and s["_b"] < stack[-1]["_b"]:
            s["_b"] = stack[-1]["_b"]
        stack.append(s)
    return spans


def _shard_events(records, pid, offset):
    """Chrome events for one shard (pid = synthetic process id)."""
    meta = next((r for r in records if r.get("kind") == "meta"), {})
    label = "{}:{} pid={}".format(
        meta.get("host", "?"), meta.get("proc", "?"), meta.get("pid", "?")
    )
    events = [
        {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
         "args": {"name": label}},
    ]
    named_tids = set()
    by_tid = {}
    for rec in records:
        kind = rec.get("kind")
        ts_us = (rec.get("ts", 0.0) + offset) * 1e6
        if kind == "span":
            track = rec.get("track")
            if track:
                tid = _TRACK_TIDS.get(track, COMM_TID)
                if tid not in named_tids:
                    named_tids.add(tid)
                    events.append({
                        "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                        "args": {"name": _TRACK_NAMES.get(tid, track)},
                    })
                events.append({
                    "ph": "X", "name": rec.get("name", "?"), "cat": track,
                    "pid": pid, "tid": tid, "ts": ts_us,
                    "dur": max(0.0, rec.get("dur_s", 0.0)) * 1e6,
                    "args": _span_args(rec),
                })
            else:
                tid = int(rec.get("tid", 0))
                by_tid.setdefault(tid, []).append({
                    "name": rec.get("name", "?"),
                    "_b": ts_us,
                    "_e": ts_us + max(0.0, rec.get("dur_s", 0.0)) * 1e6,
                    "args": _span_args(rec),
                })
        elif kind == "event":
            events.append({
                "ph": "i", "name": rec.get("name", "?"), "cat": "event",
                "pid": pid, "tid": 0, "ts": ts_us, "s": "p",
                "args": _span_args(rec),
            })
        elif kind == "dump":
            events.append({
                "ph": "i", "name": "flight_dump", "cat": "dump",
                "pid": pid, "tid": 0, "ts": ts_us, "s": "p",
                "args": {"reason": rec.get("reason", "?")},
            })
    for tid, spans in by_tid.items():
        for s in _clamp_nesting(spans):
            args = s["args"]
            events.append({"ph": "B", "name": s["name"], "pid": pid, "tid": tid,
                           "ts": s["_b"], "args": args, "_d": s["_e"] - s["_b"]})
            events.append({"ph": "E", "name": s["name"], "pid": pid, "tid": tid,
                           "ts": s["_e"], "_d": s["_e"] - s["_b"]})
    return events


def _span_args(rec):
    args = dict(rec.get("attrs") or {})
    for key in ("trace", "span", "parent", "ok"):
        if rec.get(key) is not None:
            args[key] = rec[key]
    return args


def _sort_key(evt):
    # per-track emit order: E before B at equal ts (close, then open);
    # among Bs the longer span opens first, among Es the shorter closes first
    ph = evt.get("ph")
    dur = evt.get("_d", 0.0)
    if ph == "E":
        return (evt.get("ts", 0.0), 0, dur)
    if ph == "B":
        return (evt.get("ts", 0.0), 1, -dur)
    return (evt.get("ts", 0.0), 1, 0.0)


def merge_directory(root):
    """Merge every shard under ``root``.

    Returns ``(trace, summary)`` — ``trace`` is the Chrome JSON document,
    ``summary`` a per-shard accounting (offsets, record/torn counts, trace
    ids seen).
    """
    events = []
    shards = []
    trace_ids = set()
    for pid, shard_dir in enumerate(flight.list_shards(root), start=1):
        records, torn = flight.read_shard(shard_dir)
        offset = resolve_offset(records)
        for rec in records:
            if rec.get("trace"):
                trace_ids.add(rec["trace"])
        shards.append({
            "shard": os.path.basename(shard_dir),
            "pid": pid,
            "records": len(records),
            "torn": torn,
            "clock_offset_s": offset,
        })
        events.extend(_shard_events(records, pid, offset))
    metas = [e for e in events if e.get("ph") == "M"]
    rest = sorted((e for e in events if e.get("ph") != "M"), key=_sort_key)
    for e in rest:
        e.pop("_d", None)
    trace = {"traceEvents": metas + rest, "displayTimeUnit": "ms"}
    summary = {
        "shards": shards,
        "events": len(metas) + len(rest),
        "trace_ids": sorted(trace_ids),
        "overlap_fraction": overlap_fraction(trace["traceEvents"]),
    }
    return trace, summary


def overlap_fraction(events):
    """Recompute comm/compute overlap from the drawn comm-track spans: the
    fraction of ``comm_allreduce`` busy time lying inside some
    ``comm_window`` interval — the same estimate ``BucketedOverlap`` folds
    into the ``comm_overlap_fraction`` gauge, but derived purely from the
    merged timeline so the gauge can be corroborated visually AND
    numerically.  None when no comm spans were recorded."""
    comm, windows = [], []
    for e in events:
        if e.get("ph") != "X":
            continue
        iv = (e["ts"], e["ts"] + e.get("dur", 0.0))
        if e.get("name") == "comm_allreduce":
            comm.append(iv)
        elif e.get("name") == "comm_window":
            windows.append(iv)
    if not comm:
        return None
    # merge the window set, then intersect
    windows.sort()
    merged = []
    for b, e in windows:
        if merged and b <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], e)
        else:
            merged.append([b, e])
    busy = sum(e - b for b, e in comm)
    hidden = 0.0
    for b, e in comm:
        for wb, we in merged:
            lo, hi = max(b, wb), min(e, we)
            if hi > lo:
                hidden += hi - lo
    return (hidden / busy) if busy > 0 else None


def validate_chrome_trace(trace):
    """Validate the merged document against the Chrome trace-event schema
    subset the CI leg asserts: required keys per event, monotone ``ts`` per
    (pid, tid) track, and matched ``B``/``E`` pairs.  Returns a list of
    problem strings (empty = valid)."""
    problems = []
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    last_ts = {}
    stacks = {}
    for i, e in enumerate(events):
        ph = e.get("ph")
        if ph is None or "name" not in e or "pid" not in e:
            problems.append("event {}: missing required key (ph/name/pid)".format(i))
            continue
        if ph == "M":
            continue
        if "tid" not in e or "ts" not in e:
            problems.append("event {}: missing required key (tid/ts)".format(i))
            continue
        key = (e["pid"], e["tid"])
        if e["ts"] < last_ts.get(key, float("-inf")):
            problems.append(
                "event {}: ts {} not monotone on track {}".format(i, e["ts"], key)
            )
        last_ts[key] = e["ts"]
        if ph == "B":
            stacks.setdefault(key, []).append(e["name"])
        elif ph == "E":
            stack = stacks.setdefault(key, [])
            if not stack:
                problems.append("event {}: E {!r} with empty stack".format(i, e["name"]))
            elif stack[-1] != e["name"]:
                problems.append(
                    "event {}: E {!r} does not match open B {!r}".format(
                        i, e["name"], stack[-1]
                    )
                )
                stack.pop()
            else:
                stack.pop()
        elif ph == "X":
            if e.get("dur", 0) < 0:
                problems.append("event {}: negative dur".format(i))
    for key, stack in stacks.items():
        if stack:
            problems.append("track {}: unclosed B spans {}".format(key, stack))
    return problems


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m tensorflowonspark_tpu.obs.tracemerge",
        description="merge flight-recorder shards into one Chrome trace JSON",
    )
    parser.add_argument("--dir", default=os.environ.get(flight.TRACE_DIR_ENV),
                        help="trace root (default: $TOS_TRACE_DIR)")
    parser.add_argument("--out", default=None,
                        help="output path (default: <dir>/trace.json)")
    parser.add_argument("--check", action="store_true",
                        help="validate the merged trace; exit 1 on schema problems")
    parser.add_argument("--summary", action="store_true",
                        help="print the merge summary JSON to stdout")
    parser.add_argument("--require-span", action="append", default=[],
                        metavar="NAME", help="fail unless a span NAME is present")
    parser.add_argument("--require-event", action="append", default=[],
                        metavar="NAME", help="fail unless an instant event NAME is present")
    parser.add_argument("--require-same-trace", action="store_true",
                        help="fail unless every shard record shares one trace_id")
    args = parser.parse_args(argv)
    if not args.dir:
        parser.error("--dir not given and TOS_TRACE_DIR unset")
    trace, summary = merge_directory(args.dir)
    out = args.out or os.path.join(args.dir, "trace.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(trace, f)
    print("tracemerge: {} events from {} shard(s) -> {}".format(
        summary["events"], len(summary["shards"]), out))
    failures = []
    if args.check:
        failures.extend(validate_chrome_trace(trace))
    names = {(e.get("ph"), e.get("name")) for e in trace["traceEvents"]}
    spans_present = {n for ph, n in names if ph in ("B", "X")}
    events_present = {n for ph, n in names if ph == "i"}
    for want in args.require_span:
        if want not in spans_present:
            failures.append("required span {!r} not present".format(want))
    for want in args.require_event:
        if want not in events_present:
            failures.append("required event {!r} not present".format(want))
    if args.require_same_trace and len(summary["trace_ids"]) != 1:
        failures.append(
            "expected exactly one trace_id, saw {}".format(summary["trace_ids"])
        )
    if args.summary:
        print(json.dumps(summary, sort_keys=True))
    if failures:
        for f in failures:
            print("tracemerge FAILED: {}".format(f), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
