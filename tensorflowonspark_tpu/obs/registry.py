"""Process-local metrics registry: counters, gauges, bounded histograms.

One :class:`Registry` per process is the normal shape (the module-global
:func:`get_registry`), but short-lived Spark tasks create private instances so
repeated tasks on a long-lived executor never double-count when they
accumulate onto the executor channel (see
:func:`tensorflowonspark_tpu.obs.aggregate.accumulate_to_channel`).

Design constraints, in order:

* **Off the hot path.** Training loops call ``Counter.inc()`` per step and the
  feed plane calls it per chunk. A disabled registry must make those calls
  free: one attribute load + a truth test, no allocation (proven by the
  micro-test in tests/test_obs_registry.py).
* **Thread-safe.** Instruments are hit from feeder threads, the serving pool,
  and the snapshot publisher concurrently. Counters/gauges ride a plain lock;
  snapshots are consistent per-instrument (not globally atomic — a snapshot
  taken mid-step may show step N's counter with step N-1's gauge, which is
  fine for monitoring).
* **Bounded.** Histograms hold fixed bucket arrays; events (from
  :mod:`~tensorflowonspark_tpu.obs.trace`) live in a bounded deque. Nothing
  grows with run length.
"""

import collections
import os
import threading
import time

#: default histogram bucket upper bounds (seconds): tuned to span IPC round
#: trips (~1 ms) through reservation assembly and XLA compiles (~minutes)
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    30.0, 60.0, 120.0, 300.0,
)

#: bounded event buffer size (lifecycle spans are low-rate by design)
MAX_EVENTS = int(os.environ.get("TOS_OBS_MAX_EVENTS", "1024"))


class Counter:
    """Monotonically increasing value. ``inc()`` is a no-op (and allocates
    nothing) while the owning registry is disabled."""

    __slots__ = ("name", "help", "_value", "_lock", "_registry")

    def __init__(self, registry, name, help=""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()
        self._registry = registry

    def inc(self, amount=1):
        if not self._registry._enabled:
            return
        with self._lock:
            self._value += amount

    @property
    def value(self):
        with self._lock:
            return self._value

    def _snapshot(self):
        return {"value": self.value, "help": self.help}


class Gauge:
    """Point-in-time value (queue depth, rate, pending count)."""

    __slots__ = ("name", "help", "_value", "_lock", "_registry")

    def __init__(self, registry, name, help=""):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()
        self._registry = registry

    def set(self, value):
        if not self._registry._enabled:
            return
        with self._lock:
            self._value = float(value)

    def inc(self, amount=1):
        if not self._registry._enabled:
            return
        with self._lock:
            self._value += amount

    def dec(self, amount=1):
        self.inc(-amount)

    @property
    def value(self):
        with self._lock:
            return self._value

    def _snapshot(self):
        return {"value": self.value, "help": self.help}


class Histogram:
    """Fixed-bucket histogram of observations (latencies, sizes).

    Buckets are NON-cumulative internally (``_counts[i]`` = observations in
    ``(bounds[i-1], bounds[i]]``; observations above the last bound only land
    in ``count``); the Prometheus exporter renders the cumulative form the
    text format requires.
    """

    __slots__ = ("name", "help", "bounds", "_counts", "_sum", "_count", "_lock", "_registry")

    def __init__(self, registry, name, help="", buckets=DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self._counts = [0] * len(self.bounds)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()
        self._registry = registry

    def observe(self, value):
        if not self._registry._enabled:
            return
        value = float(value)
        with self._lock:
            self._sum += value
            self._count += 1
            # linear scan: bucket lists are short (<=16 default) and the scan
            # is branch-predictable; bisect would allocate nothing either but
            # buys little here
            for i, bound in enumerate(self.bounds):
                if value <= bound:
                    self._counts[i] += 1
                    break

    def time(self):
        """Context manager observing the block's wall duration."""
        return _Timer(self)

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def _snapshot(self):
        with self._lock:
            return {
                "buckets": [[b, c] for b, c in zip(self.bounds, self._counts)],
                "sum": self._sum,
                "count": self._count,
                "help": self.help,
            }


class _Timer:
    __slots__ = ("_hist", "_t0")

    def __init__(self, hist):
        self._hist = hist

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._hist.observe(time.monotonic() - self._t0)
        return False


class Registry:
    """A named collection of instruments + a bounded event buffer.

    ``counter``/``gauge``/``histogram`` are get-or-create: the same name
    always returns the same instrument (a kind clash raises — two layers
    disagreeing about a metric's type is a bug worth failing on).
    """

    def __init__(self, enabled=True):
        self._enabled = bool(enabled)
        self._lock = threading.Lock()
        self._metrics = collections.OrderedDict()  # name -> instrument
        self._events = collections.deque(maxlen=MAX_EVENTS)

    # -- enable/disable ------------------------------------------------------

    def enable(self):
        self._enabled = True

    def disable(self):
        self._enabled = False

    @property
    def enabled(self):
        return self._enabled

    # -- instruments ---------------------------------------------------------

    def _get_or_create(self, kind, name, help, **kwargs):
        with self._lock:
            inst = self._metrics.get(name)
            if inst is None:
                inst = kind(self, name, help=help, **kwargs)
                self._metrics[name] = inst
            elif type(inst) is not kind:
                raise ValueError(
                    "metric {!r} already registered as {} (wanted {})".format(
                        name, type(inst).__name__, kind.__name__
                    )
                )
            return inst

    def counter(self, name, help=""):
        return self._get_or_create(Counter, name, help)

    def gauge(self, name, help=""):
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name, help="", buckets=DEFAULT_BUCKETS):
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    # -- events (written by obs.trace) ---------------------------------------

    def add_event(self, event):
        if not self._enabled:
            return
        # the deque is bounded: appending at capacity silently evicts the
        # oldest event, which must not be invisible — count every drop so
        # operators can tell a quiet run from a clipped event window
        if len(self._events) == self._events.maxlen:
            self.counter(
                "obs_events_dropped_total",
                help="events evicted from the bounded buffer (oldest-first)",
            ).inc()
        self._events.append(event)

    def events(self):
        return list(self._events)

    # -- snapshot ------------------------------------------------------------

    def snapshot(self):
        """JSON-able view of everything: the wire format of the aggregation
        plane and the input of both exporters."""
        counters, gauges, histograms = {}, {}, {}
        with self._lock:
            metrics = list(self._metrics.items())
        for name, inst in metrics:
            if isinstance(inst, Counter):
                counters[name] = inst._snapshot()
            elif isinstance(inst, Gauge):
                gauges[name] = inst._snapshot()
            else:
                histograms[name] = inst._snapshot()
        return {
            "ts": time.time(),
            "counters": counters,
            "gauges": gauges,
            "histograms": histograms,
            "events": list(self._events),
        }

    def reset(self):
        """Drop all instruments and events (tests)."""
        with self._lock:
            self._metrics.clear()
            self._events.clear()


#: the process-global registry; TOS_OBS=0 disables collection process-wide
_global = Registry(enabled=os.environ.get("TOS_OBS", "1") != "0")


def get_registry():
    return _global


def set_enabled(value):
    if value:
        _global.enable()
    else:
        _global.disable()


def enabled():
    return _global._enabled


def counter(name, help=""):
    return _global.counter(name, help=help)


def gauge(name, help=""):
    return _global.gauge(name, help=help)


def histogram(name, help="", buckets=DEFAULT_BUCKETS):
    return _global.histogram(name, help=help, buckets=buckets)


def snapshot():
    return _global.snapshot()
