"""Lifecycle spans: context-manager timing around the runtime's phase
boundaries (reservation, node launch, feed waves, checkpoint save/restore,
serving requests), flushed as structured events into the registry.

A span records wall-clock AND monotonic timestamps — wall time orders events
across processes/hosts in the merged cluster view; the monotonic pair is what
the duration is computed from (NTP steps must not corrupt durations). Each
completed span:

* appends an event dict to the registry's bounded event buffer::

      {"span": name, "ts": wall_start, "dur_s": secs, "ok": bool, **attrs}

* observes its duration into the histogram ``{name}_seconds`` — so spans are
  queryable both as individual events (debugging a slow launch) and as
  distributions (p99 checkpoint-save time), and survive the event buffer's
  bounded window.

When the registry is disabled, :func:`span` returns a shared no-op context
manager: no allocation, nothing recorded.
"""

import threading
import time

from tensorflowonspark_tpu.obs import registry as _registry
from tensorflowonspark_tpu.obs import tracing as _tracing


class _NullSpan:
    """Shared do-nothing span handed out while collection is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **attrs):
        return self


_NULL = _NullSpan()


class Span:
    __slots__ = ("name", "attrs", "_registry", "_t0_wall", "_t0_mono", "_span_id", "_parent_id")

    def __init__(self, name, registry, attrs):
        self.name = name
        self.attrs = attrs
        self._registry = registry

    def set(self, **attrs):
        """Attach attributes mid-span (e.g. the number of nodes reserved)."""
        self.attrs.update(attrs)
        return self

    def __enter__(self):
        # participate in the cluster trace when a context is installed: the
        # thread-local stack gives this span an id + its parent, so nested
        # spans chain causally across every tier for free
        self._span_id, self._parent_id = _tracing.push_span()
        self._t0_wall = time.time()
        self._t0_mono = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.monotonic() - self._t0_mono
        _tracing.pop_span(self._span_id)
        event = {
            "span": self.name,
            "ts": self._t0_wall,
            "dur_s": dur,
            "ok": exc_type is None,
        }
        if self.attrs:
            event.update(self.attrs)
        if self._span_id is not None:
            event["trace"] = _tracing.trace_id()
            event["span_id"] = self._span_id
            _tracing.record(
                {
                    "kind": "span",
                    "name": self.name,
                    "trace": _tracing.trace_id(),
                    "span": self._span_id,
                    "parent": self._parent_id,
                    "ts": self._t0_wall,
                    "dur_s": dur,
                    "ok": exc_type is None,
                    "tid": threading.get_native_id(),
                    "attrs": dict(self.attrs) if self.attrs else {},
                }
            )
        self._registry.add_event(event)
        self._registry.histogram(
            self.name + "_seconds", help="duration of {} spans".format(self.name)
        ).observe(dur)
        return False  # never swallow exceptions


def span(name, registry=None, **attrs):
    """Open a lifecycle span::

        with obs.span("reservation_roundtrip", nodes=4):
            ...

    ``registry`` defaults to the process-global one. Attribute values must be
    JSON-able (they ride the aggregation plane to the driver).
    """
    reg = registry if registry is not None else _registry.get_registry()
    if not reg._enabled:
        return _NULL
    return Span(name, reg, dict(attrs))
