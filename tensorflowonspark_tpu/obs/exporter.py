"""Exporters: Prometheus text format + JSON, over a stdlib HTTP endpoint.

No ``prometheus_client`` dependency — the text exposition format (version
0.0.4) is small enough to render directly, and the repo's no-new-deps
constraint is hard. The renderer takes SNAPSHOTS (the aggregation plane's
wire format), not live registries, so one endpoint can serve a merged cluster
view (``TFCluster.metrics()``) as easily as a single process's registry.

Endpoints (:class:`MetricsHTTPServer`):

* ``GET /metrics``         → Prometheus text format, ``text/plain; version=0.0.4``
* ``GET /metrics.json``    → the raw snapshot dict as JSON (tests, bench.py)
* ``GET /trace``           → this process's flight-recorder shard as JSON
  (``{"records": [...], "torn": N, "shard": path}``) — the raw span/event
  stream :mod:`~tensorflowonspark_tpu.obs.tracemerge` stitches cluster-wide,
  reachable per process while the run is still alive
* ``GET /histograms.json`` → per-histogram quantile summaries
  (``{name: {p50, p99, count, sum}}``) — the step-phase duration
  distributions (``step_fetch_seconds`` … ``step_compute_seconds``) the
  profiler records, without pulling full bucket arrays
* anything else            → 404

Prometheus rendering notes:

* histogram buckets are rendered CUMULATIVE with a final ``+Inf`` bucket equal
  to ``_count`` (the snapshot stores non-cumulative buckets — see
  ``registry.Histogram``);
* metric names are sanitized to ``[a-zA-Z_:][a-zA-Z0-9_:]*``;
* trace events are not rendered (Prometheus has no event type); they remain
  visible through the JSON endpoint.
"""

import json
import logging
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

logger = logging.getLogger(__name__)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def _sanitize(name):
    if _NAME_OK.match(name):
        return name
    name = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not re.match(r"[a-zA-Z_:]", name[:1] or "_"):
        name = "_" + name
    return name


def _fmt(value):
    """Prometheus float formatting: integers render bare, +Inf as ``+Inf``."""
    if value == float("inf"):
        return "+Inf"
    if float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def render_prometheus(snap):
    """Render one snapshot (single-process or merged) as exposition text."""
    lines = []

    def _header(name, help_text, kind):
        if help_text:
            lines.append("# HELP {} {}".format(
                name, help_text.replace("\\", "\\\\").replace("\n", "\\n")
            ))
        lines.append("# TYPE {} {}".format(name, kind))

    for name, c in sorted((snap.get("counters") or {}).items()):
        name = _sanitize(name)
        _header(name, c.get("help", ""), "counter")
        lines.append("{} {}".format(name, _fmt(c.get("value", 0))))
    for name, g in sorted((snap.get("gauges") or {}).items()):
        name = _sanitize(name)
        _header(name, g.get("help", ""), "gauge")
        lines.append("{} {}".format(name, _fmt(g.get("value", 0))))
    for name, h in sorted((snap.get("histograms") or {}).items()):
        name = _sanitize(name)
        _header(name, h.get("help", ""), "histogram")
        cumulative = 0
        for le, n in h.get("buckets") or []:
            cumulative += n
            lines.append('{}_bucket{{le="{}"}} {}'.format(name, _fmt(le), _fmt(cumulative)))
        count = h.get("count", 0)
        lines.append('{}_bucket{{le="+Inf"}} {}'.format(name, _fmt(count)))
        lines.append("{}_sum {}".format(name, _fmt(h.get("sum", 0.0))))
        lines.append("{}_count {}".format(name, _fmt(count)))
    return "\n".join(lines) + "\n"


def render_json(snap):
    return json.dumps(snap, sort_keys=True)


def histogram_quantile(hist_snap, q):
    """Estimate quantile ``q`` from one histogram snapshot by linear
    interpolation inside the containing bucket (the textbook
    ``histogram_quantile`` estimator; observations above the last finite
    bound clamp to that bound)."""
    count = hist_snap.get("count", 0)
    if count <= 0:
        return None
    rank = q * count
    cumulative = 0
    lower = 0.0
    buckets = hist_snap.get("buckets") or []
    for le, n in buckets:
        if cumulative + n >= rank and n > 0:
            frac = (rank - cumulative) / n
            return lower + (le - lower) * min(1.0, max(0.0, frac))
        cumulative += n
        lower = le
    return buckets[-1][0] if buckets else None


def render_quantiles(snap, quantiles=(0.5, 0.99)):
    """Per-histogram quantile summary of a snapshot: the compact view of the
    step-phase duration distributions the profiler records."""
    out = {}
    for name, h in sorted((snap.get("histograms") or {}).items()):
        row = {"count": h.get("count", 0), "sum": h.get("sum", 0.0)}
        for q in quantiles:
            row["p{:g}".format(q * 100).replace(".", "_")] = histogram_quantile(h, q)
        out[name] = row
    return out


def local_trace():
    """This process's flight shard as a JSON-able dict (the /trace body).

    Reads the shard back from disk (not memory) so the endpoint shows
    exactly what a post-mortem merge would see; empty when the tracing
    plane is inert."""
    from tensorflowonspark_tpu.obs import flight

    rec = flight.current(create=False)
    if rec is None:
        return {"records": [], "torn": 0, "shard": None}
    records, torn = flight.read_shard(rec.shard_dir)
    return {"records": records, "torn": torn, "shard": rec.shard_dir}


class MetricsHTTPServer:
    """Tiny threaded HTTP server exposing a snapshot function.

    ``snapshot_fn`` is called per request — pass ``registry.snapshot`` for a
    live process view or ``cluster.metrics`` for the merged driver view::

        srv = MetricsHTTPServer(obs.snapshot, port=9100).start()
        ...
        srv.stop()
    """

    def __init__(self, snapshot_fn, host="", port=0):
        self._snapshot_fn = snapshot_fn
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                try:
                    snap = outer._snapshot_fn()
                    if self.path in ("/metrics", "/"):
                        body = render_prometheus(snap).encode("utf-8")
                        ctype = CONTENT_TYPE
                    elif self.path == "/metrics.json":
                        body = render_json(snap).encode("utf-8")
                        ctype = "application/json"
                    elif self.path == "/histograms.json":
                        body = json.dumps(render_quantiles(snap), sort_keys=True).encode("utf-8")
                        ctype = "application/json"
                    elif self.path == "/trace":
                        body = json.dumps(local_trace(), sort_keys=True).encode("utf-8")
                        ctype = "application/json"
                    else:
                        self.send_error(404)
                        return
                except Exception as e:  # a broken snapshot must not kill the server
                    self.send_error(500, str(e))
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # route to logging, not stderr
                logger.debug("metrics http: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.address = self._httpd.server_address
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="tos-metrics-http", daemon=True
        )
        self._thread.start()
        logger.info("metrics endpoint at http://%s:%s/metrics", *self.address)
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
