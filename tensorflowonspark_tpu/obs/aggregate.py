"""Driver-side aggregation: executor registries → one cluster view.

Transport is the EXISTING per-executor TFManager channel (the same k/v store
the heartbeat and state machine ride): nothing new listens on the network, and
the driver can already reach every node's channel (or falls back per
TFCluster's NAT story — unreachable channels simply contribute no metrics).

Two publication shapes, matching the two process lifetimes in the runtime:

* :class:`SnapshotPublisher` — the long-lived jax child overwrites its full
  registry snapshot under ``obs_snapshot`` every interval. Overwrite is
  idempotent: the child's registry is cumulative, so the newest snapshot
  supersedes older ones.
* :func:`accumulate_to_channel` — short-lived Spark tasks (feed/launch tasks)
  MERGE a private registry into ``obs_feeder`` at task end. Tasks on one
  executor are serialized (the one-concurrent-task-per-executor invariant the
  feed plane already holds), so read-merge-write needs no channel-side lock.
  Tasks must use a PRIVATE registry: the executor process outlives tasks, and
  accumulating the process-global registry twice would double-count.

Snapshots cross the channel as JSON strings — same no-code-execution stance
as the reservation control plane (executors should not be able to unpickle
arbitrary objects into the driver).

Merge semantics (:func:`merge_snapshots`):

* counters: summed (every counter is a rate-able total);
* histograms: bucket-wise summed when bounds agree (snapshots from mixed
  bucket layouts keep the first layout and still sum count/sum);
* gauges: summed across sources — "cluster feed-queue depth" is the sum of
  per-node depths; per-node values stay visible in ``TFCluster.metrics()``'s
  ``nodes`` section;
* events: concatenated, ordered by wall time, bounded to the newest
  ``registry.MAX_EVENTS``.
"""

import json
import logging
import os
import threading

from tensorflowonspark_tpu.obs import registry as _registry

logger = logging.getLogger(__name__)

#: channel key written by the jax child's periodic publisher
CHANNEL_KEY = "obs_snapshot"
#: channel key accumulated by short-lived feeder/launch tasks
FEEDER_KEY = "obs_feeder"
#: channel key overwritten by an elected heartbeat aggregator's private
#: registry (registry.HeartbeatAggregator) — overwrite semantics like
#: CHANNEL_KEY, but a separate lane because the aggregator thread outlives
#: the launch task and must not double-count the child's snapshot
AGGREGATOR_KEY = "obs_aggregator"

#: seconds between child snapshot publications
PUBLISH_INTERVAL = float(os.environ.get("TOS_OBS_PUBLISH_INTERVAL", "2"))


def merge_snapshots(snapshots, gauges="sum"):
    """Merge registry snapshots (dicts, as returned by Registry.snapshot).

    ``gauges="sum"`` is the cross-NODE semantic (cluster queue depth = sum of
    per-node depths); ``gauges="last"`` is the same-node-over-TIME semantic
    used by :func:`accumulate_to_channel` (a fresh feed wave's queue depth
    replaces the previous wave's, it doesn't add to it).
    """
    gauge_last = gauges == "last"
    counters, gauges, histograms, events = {}, {}, {}, []
    ts = 0.0
    for snap in snapshots:
        if not snap:
            continue
        ts = max(ts, snap.get("ts", 0.0))
        for name, c in (snap.get("counters") or {}).items():
            dst = counters.setdefault(name, {"value": 0.0, "help": c.get("help", "")})
            dst["value"] += c.get("value", 0.0)
        for name, g in (snap.get("gauges") or {}).items():
            dst = gauges.setdefault(name, {"value": 0.0, "help": g.get("help", "")})
            if gauge_last:
                dst["value"] = g.get("value", 0.0)
            else:
                dst["value"] += g.get("value", 0.0)
        for name, h in (snap.get("histograms") or {}).items():
            dst = histograms.get(name)
            if dst is None:
                histograms[name] = {
                    "buckets": [list(b) for b in h.get("buckets") or []],
                    "sum": h.get("sum", 0.0),
                    "count": h.get("count", 0),
                    "help": h.get("help", ""),
                }
                continue
            dst["sum"] += h.get("sum", 0.0)
            dst["count"] += h.get("count", 0)
            src_buckets = h.get("buckets") or []
            if [b[0] for b in dst["buckets"]] == [b[0] for b in src_buckets]:
                for i, (_le, n) in enumerate(src_buckets):
                    dst["buckets"][i][1] += n
            # mismatched bucket layouts: keep the first layout; sum/count
            # above stay correct, per-bucket detail is best-effort
        events.extend(snap.get("events") or [])
    events.sort(key=lambda e: e.get("ts", 0.0))
    return {
        "ts": ts,
        "counters": counters,
        "gauges": gauges,
        "histograms": histograms,
        "events": events[-_registry.MAX_EVENTS:],
    }


def publish_to_channel(mgr, registry=None, key=CHANNEL_KEY):
    """Overwrite this process's registry snapshot on the executor channel."""
    reg = registry if registry is not None else _registry.get_registry()
    mgr.set(key, json.dumps(reg.snapshot()))


def accumulate_to_channel(mgr, registry, key=FEEDER_KEY):
    """Merge a (private, per-task) registry into the channel's accumulated
    snapshot. See module docstring for why this must be a private registry."""
    snap = registry.snapshot()
    try:
        existing = mgr.get(key)
        prior = json.loads(existing) if existing else None
    except (ValueError, TypeError):
        prior = None  # corrupt/foreign payload: start over
    merged = merge_snapshots([prior, snap], gauges="last") if prior else snap
    mgr.set(key, json.dumps(merged))


def read_channel_snapshots(mgr, keys=(CHANNEL_KEY, FEEDER_KEY, AGGREGATOR_KEY)):
    """All snapshots one executor channel holds (child + feeder +
    heartbeat-aggregator lanes)."""
    snaps = []
    for key in keys:
        try:
            raw = mgr.get(key)
            if raw:
                snaps.append(json.loads(raw))
        except (ValueError, TypeError):
            continue
    return snaps


class SnapshotPublisher:
    """Daemon thread publishing the jax child's registry every
    ``interval`` seconds (and once at :meth:`stop`), with the same
    die-quietly-on-dead-channel policy as the heartbeat thread."""

    def __init__(self, mgr, registry=None, interval=None, key=CHANNEL_KEY):
        self._mgr = mgr
        self._registry = registry if registry is not None else _registry.get_registry()
        self._interval = PUBLISH_INTERVAL if interval is None else float(interval)
        self._key = key
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        if not self._registry._enabled:
            return self  # disabled: publish nothing, spin nothing
        self._thread = threading.Thread(
            target=self._run, name="tos-obs-publisher", daemon=True
        )
        self._thread.start()
        return self

    def _run(self):
        failures = 0
        while not self._stop.wait(self._interval):
            try:
                publish_to_channel(self._mgr, self._registry, self._key)
                failures = 0
            except Exception:
                failures += 1
                if failures >= 5:
                    return  # channel stayed dead: executor is going away
        try:  # final flush so short runs publish at least once
            publish_to_channel(self._mgr, self._registry, self._key)
        except Exception:
            pass

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
