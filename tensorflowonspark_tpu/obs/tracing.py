"""Cluster-wide trace context: one ``trace_id`` from driver to decode worker.

:func:`TFCluster.run <tensorflowonspark_tpu.TFCluster.run>` mints a
``trace_id`` and a root ``span_id`` and threads them through the same
env-propagation lane the chaos plan rides (``cluster_meta["env"]`` →
executor → ``os.environ`` in the spawned jax child → inherited by forked
decode workers and serving replicas).  Every span or event recorded anywhere
in the cluster then carries the same causal identity, so
:mod:`~tensorflowonspark_tpu.obs.tracemerge` can stitch per-process flight
shards (:mod:`~tensorflowonspark_tpu.obs.flight`) into one timeline.

Minting is idempotent: if a trace is already active in the driver process
(an elastic-ladder relaunch calling :func:`TFCluster.run` again), the
existing ``trace_id`` is reused — a recovery ladder is ONE trace, and the
kill, the watchdog's ``lease_expired`` event, and the relaunch all line up
on it.

Span identity is tracked per thread: a thread-local stack gives each span a
fresh 64-bit ``span_id`` and its enclosing span (or the propagated root) as
``parent``.  The stack is maintained by :class:`obs.trace.Span
<tensorflowonspark_tpu.obs.trace.Span>` itself, so every *existing* span
site gains trace identity without being edited.

Clock alignment: each executor measures its wall-clock offset against the
driver from the reservation REG round-trip (the server stamps its reply;
offset = ``server_ts - (t0 + t1) / 2``, NTP-style, best = min-RTT sample —
see :func:`observe_clock`).  The offset is exported via
``TOS_TRACE_CLOCK_OFF`` so same-host children inherit it, and recorded into
the flight shard for the merger.

Span sites
----------

Every span name in the tree must be a string literal, opened via ``with``,
and listed here (enforced by the ``trace-discipline`` tosa rule, the
tracing analogue of chaos-obs-coverage):

``reservation_roundtrip``  driver awaiting all executor reservations
``node_launch``            executor registration + cluster-assembly wait
``node_main``              the jax child's user training/inference fn
``feed_wave``              one executor feed wave (partition batch stream)
``inference_wave``         one executor inference wave
``chaos_fault``            marker span for an injected chaos fault
``step_fetch``             training loop pulling the next host batch
``h2d_transfer``           host→device transfer of a feed window
``step_compute``           one optimizer step (jit dispatch + wait)
``ckpt_snapshot``          checkpoint snapshot handoff to the async engine
``comm_allreduce``         one bucketed all-reduce on the comm thread (retro)
``comm_window``            backprop window a bucket may hide under (retro)
``pipeline_stage``         one 1F1B stage op (fwd/bwd/fused loss) (retro)
``pipeline_transfer``      stage-boundary activation/cotangent hop (retro)
``serving_route``          serving-mesh router handling one client request
``elastic_relaunch``       recovery-ladder relaunch attempt
``elastic_regrow``         scaler-initiated regrow restart (drain → relaunch)
``control_decision``       marker span for a Controller knob move

``comm_allreduce``/``comm_window`` and ``pipeline_stage``/
``pipeline_transfer`` are *retroactive* spans (:func:`record_span`): the
bucketed-overlap comm thread and the 1F1B stage/comm threads record
perf-counter intervals while overlapping compute, and the step publishes
them afterwards with explicit timestamps so the merger can draw the comm
and pipeline tracks without the tracer ever being on the hot path.
"""

import os
import secrets
import threading
import time

from tensorflowonspark_tpu.obs import flight as _flight
from tensorflowonspark_tpu.obs import registry as _registry

#: env lane keys (the same propagation mechanism as TOS_CHAOS_PLAN)
TRACE_ENV = "TOS_TRACE_ID"
PARENT_ENV = "TOS_TRACE_PARENT"
DIR_ENV = _flight.TRACE_DIR_ENV  # TOS_TRACE_DIR
CLOCK_ENV = "TOS_TRACE_CLOCK_OFF"
PROC_ENV = "TOS_TRACE_PROC"


class _State:
    def __init__(self):
        self.trace_id = None
        self.root_parent = None
        self.proc = None
        self.best_rtt = None


_state = _State()
_tls = threading.local()


def _new_id():
    return secrets.token_hex(8)


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


# -- context -----------------------------------------------------------------


def active():
    """True once a trace context is installed in this process."""
    return _state.trace_id is not None


def trace_id():
    return _state.trace_id


def current_span_id():
    """The innermost open span on this thread, else the propagated root."""
    st = _stack()
    return st[-1] if st else _state.root_parent


def mint(proc="driver"):
    """Mint (or reuse) the process trace context and return the env dict to
    thread through the cluster_meta env lane.

    Only :func:`TFCluster.run` calls this.  Re-minting inside an already
    traced process keeps the existing ``trace_id`` (ladder relaunches stay
    on one trace) but always returns a complete propagation env.
    """
    if not active():
        _state.trace_id = os.environ.get(TRACE_ENV) or _new_id() + _new_id()
        _state.root_parent = _new_id()
        _state.proc = proc
        os.environ[TRACE_ENV] = _state.trace_id
        root = os.environ.get(DIR_ENV)
        if root and _registry.enabled():
            _flight.configure(root, proc, trace_id=_state.trace_id)
    env = {TRACE_ENV: _state.trace_id, PARENT_ENV: _state.root_parent or ""}
    root = os.environ.get(DIR_ENV)
    if root:
        env[DIR_ENV] = root
    return env


def install_from_env(proc, env=None):
    """Adopt a propagated trace context in a non-driver tier.

    ``env`` (e.g. the executor-side ``cluster_meta["env"]``) is folded into
    ``os.environ`` first so children spawned later inherit the lane; the
    executor's already-measured ``TOS_TRACE_CLOCK_OFF`` is left alone.
    Returns True when a trace became (or already was) active.
    """
    if env:
        for key in (TRACE_ENV, PARENT_ENV, DIR_ENV):
            if key in env and env[key]:
                os.environ[key] = str(env[key])
    tid = os.environ.get(TRACE_ENV)
    if not tid:
        return False
    if _state.trace_id != tid:
        _state.trace_id = tid
        _state.root_parent = os.environ.get(PARENT_ENV) or None
        _state.best_rtt = None
    _state.proc = proc
    os.environ[PROC_ENV] = proc
    root = os.environ.get(DIR_ENV)
    if root and _registry.enabled():
        rec = _flight.current(create=False)
        if rec is None or rec.proc != proc:
            _flight.configure(
                root, proc, trace_id=tid, clock_offset=clock_offset()
            )
    return True


def propagation_env():
    """The env entries a traced process should pass to anything it spawns."""
    if not active():
        return {}
    env = {TRACE_ENV: _state.trace_id}
    if _state.root_parent:
        env[PARENT_ENV] = _state.root_parent
    for key in (DIR_ENV, CLOCK_ENV):
        if os.environ.get(key):
            env[key] = os.environ[key]
    return env


def reset():
    """Forget the process trace context and recorder (tests)."""
    _state.trace_id = None
    _state.root_parent = None
    _state.proc = None
    _state.best_rtt = None
    _tls.stack = []
    for key in (TRACE_ENV, PARENT_ENV, PROC_ENV, CLOCK_ENV):
        os.environ.pop(key, None)
    _flight.reset()


# -- span plumbing (driven by obs.trace.Span) --------------------------------


def push_span():
    """Allocate a span id, note its parent, and make it current for the
    thread.  Returns ``(span_id, parent_id)`` — (None, None) when no trace
    context is active (spans still work, they just carry no identity)."""
    if not active():
        return None, None
    sid = _new_id()
    parent = current_span_id()
    _stack().append(sid)
    return sid, parent


def pop_span(span_id):
    st = _stack()
    if span_id is not None and st and st[-1] == span_id:
        st.pop()


def record(record):
    """Write one record to the local flight shard, if one is open."""
    rec = _flight.current()
    if rec is not None:
        rec.append(record)


def event(name, **attrs):
    """Record an instant event (e.g. ``lease_expired``, ``child_failed``)
    onto the current trace at the current causal position."""
    if not active() and not os.environ.get(DIR_ENV):
        return
    evt = {
        "kind": "event",
        "name": name,
        "trace": _state.trace_id,
        "span": _new_id(),
        "parent": current_span_id(),
        "ts": time.time(),
    }
    if attrs:
        evt["attrs"] = attrs
    record(evt)


def record_span(name, ts, dur_s, ok=True, track=None, **attrs):
    """Retroactively record a completed span with explicit timestamps.

    Used for intervals measured off-thread (the bucketed-overlap comm
    thread) where a context manager cannot wrap the work.  ``track`` labels
    a dedicated merge-time lane (the comm track)."""
    rec = {
        "kind": "span",
        "name": name,
        "trace": _state.trace_id,
        "span": _new_id(),
        "parent": current_span_id(),
        "ts": float(ts),
        "dur_s": float(dur_s),
        "ok": bool(ok),
        "tid": threading.get_native_id(),
    }
    if track:
        rec["track"] = track
    if attrs:
        rec["attrs"] = attrs
    record(rec)


# -- clock alignment ---------------------------------------------------------


def clock_offset():
    """Seconds to ADD to local wall time to get driver wall time."""
    try:
        return float(os.environ.get(CLOCK_ENV, "0") or 0.0)
    except ValueError:
        return 0.0


def observe_clock(server_ts, t0, t1):
    """Fold one driver-stamped round-trip into the clock-offset estimate.

    ``t0``/``t1`` are local wall clocks around the request; ``server_ts`` is
    the driver's stamp from the reply.  NTP-style midpoint estimate; the
    lowest-RTT sample wins (its midpoint error bound is tightest).  The
    winning offset is exported via ``TOS_TRACE_CLOCK_OFF`` for same-host
    children and journaled into the flight shard for the merger.
    """
    rtt = t1 - t0
    if rtt < 0:
        return None
    if _state.best_rtt is not None and rtt >= _state.best_rtt:
        return None
    _state.best_rtt = rtt
    offset = server_ts - (t0 + t1) / 2.0
    os.environ[CLOCK_ENV] = repr(offset)
    rec = _flight.current()
    if rec is not None:
        rec.set_clock_offset(offset, rtt=rtt)
    return offset


# -- convenience -------------------------------------------------------------


def span(name, registry=None, **attrs):
    """Alias for :func:`tensorflowonspark_tpu.obs.trace.span` (the single
    span implementation — every span participates in tracing when a context
    is active)."""
    from tensorflowonspark_tpu.obs import trace as _trace

    return _trace.span(name, registry=registry, **attrs)
