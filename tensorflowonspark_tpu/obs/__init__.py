"""Cross-layer observability: metrics registry, aggregation, export, tracing.

The paper's design (and the reference repo) is a thin orchestration layer
whose only instrumentation is example-level step timing; everything else —
reservation progress, feed-queue depth, serving sheds, recovery relaunches —
is invisible outside log grep. This package is the measurement substrate the
ROADMAP's production north-star needs, dependency-free (stdlib only) so it is
importable from every process in the runtime: the Spark driver, executor
processes, spawned jax children, and the serving server.

Layers (data flows left to right):

* :mod:`~tensorflowonspark_tpu.obs.registry` — process-local counters /
  gauges / bounded histograms; thread-safe; near-zero overhead when disabled.
* :mod:`~tensorflowonspark_tpu.obs.trace` — lifecycle spans (reservation,
  node launch, feed waves, checkpoint, serving) recorded as structured
  events with wall + monotonic timestamps.
* :mod:`~tensorflowonspark_tpu.obs.tracing` — cluster-wide trace context:
  a ``trace_id``/root ``span_id`` minted by ``TFCluster.run`` and threaded
  through the env lane to every tier, plus NTP-style clock-offset
  estimation from the reservation handshake.
* :mod:`~tensorflowonspark_tpu.obs.flight` — per-process crash-safe JSONL
  ring shards under ``TOS_TRACE_DIR`` (CRC line framing + tmp/rename
  segment commits), dumped on chaos faults, failure classification, and
  unhandled child exit. Merged offline by
  :mod:`~tensorflowonspark_tpu.obs.tracemerge` into one Chrome-trace JSON.
* :mod:`~tensorflowonspark_tpu.obs.aggregate` — executor-side nodes publish
  registry snapshots over the existing TFManager channel; the driver merges
  them into one cluster view (``TFCluster.metrics()``).
* :mod:`~tensorflowonspark_tpu.obs.exporter` — Prometheus text format over a
  tiny stdlib HTTP endpoint, plus a JSON dump for tests and ``bench.py``.

Metric naming follows Prometheus conventions: ``<area>_<what>_<unit>``,
counters end in ``_total``, histograms in ``_seconds`` (see
docs/architecture.md "Observability"). The global registry honors
``TOS_OBS=0`` to disable all collection process-wide.
"""

from tensorflowonspark_tpu.obs.registry import (  # noqa: F401
    Registry,
    counter,
    enabled,
    gauge,
    get_registry,
    histogram,
    set_enabled,
    snapshot,
)
from tensorflowonspark_tpu.obs.trace import span  # noqa: F401
from tensorflowonspark_tpu.obs.flight import dump as flight_dump  # noqa: F401
