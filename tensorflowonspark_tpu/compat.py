"""Version/platform compatibility shims.

Capability-parity with /root/reference/tensorflowonspark/compat.py, whose
three shims smoothed over TF 2.0/2.1 differences. The TPU-native analogues:

* ``export_saved_model`` — the chief-vs-worker export dance
  (reference compat.py:10-17: workers dumped to a throwaway dir) is
  unnecessary with orbax multi-host saves; kept for drop-in source compat.
* ``disable_auto_shard`` — a tf.data concept with no jax equivalent; no-op.
* ``is_gpu_available`` → TPU probe.
"""

import logging

logger = logging.getLogger(__name__)


def export_saved_model(model_or_state, export_dir, is_chief=False):
    """Reference compat.py:10-17. Delegates to the checkpoint layer's export,
    where EVERY process participates (orbax multi-host saves are collective —
    a chief-only save would deadlock the sync barrier); ``is_chief`` is
    accepted purely for source compatibility."""
    from tensorflowonspark_tpu.train import checkpoint

    return checkpoint.export_saved_model(None, export_dir, model_or_state, is_chief=is_chief)


def disable_auto_shard(options):
    """Reference compat.py:20-26; auto-sharding is a tf.data policy that does
    not exist in the jax input path — explicit shard placement replaces it."""
    del options


def is_gpu_available():
    """Reference compat.py:27-31 probed GPUs; the equivalent question on this
    stack is whether TPU chips are attached."""
    from tensorflowonspark_tpu import tpu_info

    return tpu_info.is_tpu_available()


def is_tpu_available():
    from tensorflowonspark_tpu import tpu_info

    return tpu_info.is_tpu_available()
