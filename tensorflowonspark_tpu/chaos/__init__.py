"""Deterministic, seedable fault injection across every plane.

The recovery machinery (watchdog, ``TFCluster.abort``, ``run_with_recovery``,
checkpoint resume, serving shed/retry) is only trustworthy if it is
*continuously exercised*. This package plants named injection sites at the
failure-prone seams — reservation traffic, feed queues, the data loader,
checkpoint IO, the serving socket — and fires faults according to a
:class:`ChaosPlan`: a seeded RNG plus per-site probability / count budget,
so a fault schedule is exactly reproducible from ``(seed, site, call #)``.

Default off, and cheap enough to leave compiled into the hot paths: every
site guards on the module-level ``active`` boolean, so with no plan
installed an injection site costs one attribute read and a falsy branch —
no allocation, no function call (mirroring the disabled
:mod:`~tensorflowonspark_tpu.obs` registry).

Plans propagate to child processes through the ``TOS_CHAOS_PLAN`` env var:
:func:`install` exports it by default, spawned executors / jax children
inherit it, and this module re-installs from the env at import. Every
triggered fault increments ``chaos_faults_injected_total`` (plus a
per-site counter) and records a ``chaos_fault`` span, so injected faults
surface in ``TFCluster.metrics()`` wherever the firing process publishes
its registry.

Typical use (tests, chaos CI leg)::

    plan = (chaos.ChaosPlan(seed=7)
            .site("reservation.client_reset", probability=0.3, max_count=2)
            .site("serving.latency", probability=0.5, delay_s=0.05))
    chaos.install(plan)
    try:
        ...   # run the workload; recovery paths absorb the faults
        assert plan.fired() > 0
    finally:
        chaos.uninstall()

Site vocabulary (see ``docs/architecture.md``):

==============================  ==============================================
site                            effect at the injection point
==============================  ==============================================
``reservation.client_reset``    client request raises ``ConnectionResetError``
``reservation.reg_drop``        server drops the connection before replying
``reservation.slow_accept``     server stalls after accepting a connection
``reservation.late_register``   client sleeps before registering
``feed.stall``                  feeder sleeps before enqueueing a chunk
``feed.slow_consumer``          ``DataFeed`` sleeps before dequeueing
``feed.truncate_chunk``         train feeder drops the tail of one chunk
``data.producer_delay``         loader producer sleeps before emitting
``data.poison``                 loader yields one undecodable record
``data.shard_read``             read-ahead shard open sleeps (``delay_s``) or
                                raises ``IOError`` (``error: true``); errors
                                are retried under ``SHARD_READ_RETRY``
``data.decode_kill``            decode plane SIGKILLs one of its own worker
                                processes mid-round — the lease protocol
                                must re-decode the orphaned slots on the
                                respawned pool without losing or
                                duplicating a row
``data.cache_tear``             decoded-slab cache commit publishes a TORN
                                manifest (truncated half-way, the crash-
                                between-write-and-fsync shape) — verify-on-
                                publish must reject the generation and its
                                records must simply decode again
``data.readahead_stall``        read-ahead shard reader sleeps ``delay_s``
                                per chunk, charged into shard-read time so
                                ``classify_stalls`` sees io_bound and the
                                ``ReadaheadAutotuner`` must deepen
``data.device_link``            autotuned feed sleeps ``delay_s`` inside the
                                timed region of every host->device transfer
                                (probes and windows), so injected latency
                                flows into the link estimate and the window
                                size K must adapt
``data.tokenize_error``         text producer swaps one record for invalid
                                UTF-8 bytes; the tokenizer rejects it and the
                                skip is charged against ``max_bad_records``
                                identically in every pack mode (the length
                                check runs producer-side)
``data.pack_stall``             text packer sleeps ``delay_s`` inside the
                                timed packing region, charged into parse time
                                so ``classify_stalls`` reports the job
                                input-bound (decode_bound)
``checkpoint.corrupt_write``    newest checkpoint left torn on disk (in the
                                async engine: shard bitrot after the
                                manifest, caught by cheap-verify)
``checkpoint.restore_fail``     restore raises ``IOError``
``ckpt.snapshot_stall``         snapshot-to-host copy sleeps before copying
                                (``delay_s``) — the training-thread cost
``ckpt.write_slow``             background checkpoint writer sleeps
                                (``delay_s``) inside the timed write region
``ckpt.commit_tear``            commit dies between shard write and publish:
                                staging dir left unpublished; with
                                ``publish_torn: true`` the rename happens
                                over a half-written manifest instead
``node.kill``                   jax child SIGKILLs itself from the heartbeat
                                loop (``victim``: executor id, ``after_beats``:
                                beats to wait) — a permanent node loss the
                                recovery ladder must blacklist and shrink past
``node.flap``                   heartbeat loop stalls ``delay_s`` (``victim``,
                                ``after_beats`` as above) — a transient loss
                                that should NOT lead to a blacklist
``node.preempt``                jax child SIGTERMs itself from the heartbeat
                                loop (``victim``/``after_beats`` as above) —
                                a preemption *warning*, not a kill: the
                                child's real SIGTERM handler drains async
                                checkpoints, commits a ``preempted`` parting
                                status, and exits clean before the platform
                                kill would land; the ladder must classify it
                                ``preemption`` (no blacklist, no restart
                                budget). Node sites also honor a generic
                                ``once_path`` param: a cross-process one-shot
                                latch file (skip when it exists, create on
                                fire), so a victim respawned by the ladder
                                does not die again on every life
``control.driver_crash``        watchdog drops the in-memory membership
                                registry with no parting commit and recovers
                                it from the journal under a bumped epoch —
                                a driver restart mid-train; live executors
                                must be re-adopted without relaunch
``control.lease_delay``         registry lease renewal sleeps ``delay_s`` —
                                benign control-plane latency that must not
                                expire healthy leases
``control.journal_tear``        registry manifest publish dies half-written
                                (or with ``target: "journal"`` a journal
                                append is torn); recovery must detect the
                                CRC mismatch and fall back to the previous
                                committed manifest plus journal replay
``serving.latency``             predictor sleeps before dispatch
``serving.conn_drop``           server closes the connection mid-request
``serving.overload``            submit sheds with ``Overloaded``
``serving.replica_kill``        mesh monitor SIGKILLs a serving replica
                                mid-load (``victim: <rid>`` targets one);
                                the router fails requests over and the
                                monitor relaunches it
``serving.router_partition``    router loses a replica's connection: the
                                pooled client is dropped and the attempt
                                raises ``ConnectionResetError``, driving
                                failover and the replica's circuit breaker
``serving.swap_torn``           model-generation publish commits a torn
                                manifest; replicas must reject the swap via
                                ``manifest.verify()`` and keep serving the
                                old bundle
``comm.link_delay``             host gradient all-reduce sleeps ``delay_s``
                                before the exchange on rank ``victim`` only
                                (a straggling DCN link); peers must absorb
                                it — bucketed overlap hides the wait behind
                                backprop and the straggler stays visible in
                                the MULTICHIP per-rank step-time spread
``native_io.read_fail``         TFRecord read raises ``IOError``
``store.read_error``            one remote store HTTP request raises
                                ``IOError`` — absorbed by the store's retry
                                budget (``resilience_retries_total`` climbs,
                                the stream stays byte-identical)
``store.remote_stall``          remote store request sleeps ``delay_s`` — the
                                latency lands in shard-read time, so the
                                stall classifier calls the run io_bound and
                                the prefetch autotuner must deepen
``store.prefetch_tear``         staged-shard publish commits a torn
                                ``MANIFEST.json``; verify-on-read must
                                reject and recount the stage and the shard
                                re-fetches cold
==============================  ==============================================
"""

import json
import logging
import os
import random
import threading
import time

from tensorflowonspark_tpu import obs

logger = logging.getLogger(__name__)

#: env var carrying the JSON plan into spawned children
ENV_VAR = "TOS_CHAOS_PLAN"
#: optional file that gets one line appended per fired fault — lets the
#: chaos CI leg assert "faults > 0" across many short-lived processes
LOG_ENV_VAR = "TOS_CHAOS_LOG"

#: single cached boolean read by every injection site; True iff a plan is
#: installed in this process
active = False

_plan = None
_install_lock = threading.Lock()


class ChaosPlan:
    """A reproducible fault schedule: a seed plus per-site specs.

    Each site spec holds a ``probability`` (per arrival at the site), an
    optional ``max_count`` budget (``None`` = unlimited), and free-form
    params interpreted by the site (``delay_s`` for delay faults, etc.).
    Each site draws from its own ``random.Random`` seeded from
    ``(plan seed, site name)``, so schedules are independent of the order
    in which *other* sites fire — crucial for cross-process determinism.
    """

    def __init__(self, seed=0, sites=None):
        self.seed = seed
        self.sites = {}
        self._lock = threading.Lock()
        self._rngs = {}
        self._fired = {}
        for name, spec in (sites or {}).items():
            self.site(name, **spec)

    def site(self, name, probability=1.0, max_count=None, **params):
        """Add (or replace) a site spec; chainable."""
        if not 0.0 <= probability <= 1.0:
            raise ValueError("probability must be in [0, 1]")
        spec = dict(params)
        spec["probability"] = probability
        spec["max_count"] = max_count
        with self._lock:
            self.sites[name] = spec
            self._rngs[name] = random.Random("{}:{}".format(self.seed, name))
            self._fired.setdefault(name, 0)
        return self

    def should_fire(self, name):
        """Roll the site's RNG; returns the spec dict when the fault
        triggers, else None. Respects the site's ``max_count`` budget."""
        spec = self.sites.get(name)
        if spec is None:
            return None
        with self._lock:
            budget = spec["max_count"]
            if budget is not None and self._fired[name] >= budget:
                return None
            if self._rngs[name].random() >= spec["probability"]:
                return None
            self._fired[name] += 1
        return spec

    def fired(self, name=None):
        """Faults fired so far — for one site, or in total."""
        with self._lock:
            if name is not None:
                return self._fired.get(name, 0)
            return sum(self._fired.values())

    def to_json(self):
        return json.dumps({"seed": self.seed, "sites": self.sites}, sort_keys=True)

    @classmethod
    def from_json(cls, text):
        data = json.loads(text)
        return cls(seed=data.get("seed", 0), sites=data.get("sites") or {})

    def __repr__(self):
        return "ChaosPlan(seed={}, sites={})".format(self.seed, sorted(self.sites))


def install(plan, propagate=True):
    """Activate ``plan`` in this process; with ``propagate`` (default) the
    plan is also exported through :data:`ENV_VAR` so processes spawned from
    here inherit it."""
    global _plan, active
    with _install_lock:
        _plan = plan
        active = plan is not None
        if propagate:
            if plan is not None:
                os.environ[ENV_VAR] = plan.to_json()
            else:
                os.environ.pop(ENV_VAR, None)
    if plan is not None:
        logger.info("chaos plan installed: %r", plan)


def uninstall():
    """Deactivate fault injection and clear the propagation env var."""
    install(None, propagate=True)


def plan():
    """The installed :class:`ChaosPlan`, or None."""
    return _plan


def fire(site):
    """Roll ``site`` against the installed plan. Returns the site's spec
    dict when the fault fires (after recording it in obs), else None.

    Injection sites guard the call with ``if chaos.active:`` so the
    disabled path never reaches here.
    """
    p = _plan
    if p is None:
        return None
    spec = p.should_fire(site)
    if spec is None:
        return None
    _record(site)
    return spec


def delay(site):
    """Fire ``site`` as a delay fault: sleep its ``delay_s`` (default
    50 ms) when triggered. Returns True if a delay was injected."""
    spec = fire(site)
    if spec is None:
        return False
    time.sleep(spec.get("delay_s", 0.05))
    return True


def _record(site):
    safe = site.replace(".", "_").replace("-", "_")
    obs.counter("chaos_faults_injected_total", help="faults injected by the chaos plan").inc()
    obs.counter("chaos_fault_{}_total".format(safe), help="chaos faults at {}".format(site)).inc()
    with obs.span("chaos_fault", site=site):
        pass  # marker span: wall-clock point of injection for trace ordering
    # black-box moment: a fault injection flushes this process's flight
    # shard (no-op when the tracing plane is inert), so even a fault that
    # kills the process leaves its final spans on disk
    try:
        obs.flight_dump("chaos:{}".format(site))
    except Exception:  # the dump is best-effort, the fault must still fire
        pass
    logger.warning("chaos: injected fault at %s", site)
    log_path = os.environ.get(LOG_ENV_VAR)
    if log_path:
        try:
            with open(log_path, "a") as f:
                f.write(site + "\n")
        except OSError:  # the assertion file is best-effort
            pass


def _install_from_env():
    text = os.environ.get(ENV_VAR)
    if not text:
        return
    try:
        install(ChaosPlan.from_json(text), propagate=False)
    except (ValueError, KeyError) as e:
        logger.warning("ignoring malformed %s: %s", ENV_VAR, e)


_install_from_env()
