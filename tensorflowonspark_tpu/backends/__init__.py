"""Execution backends.

The framework's driver API is written against the small slice of the Spark
surface it actually uses (``parallelize``/``union``/``foreachPartition``/
``mapPartitions``/``collect``). Two backends provide it:

* :mod:`~tensorflowonspark_tpu.backends.local` — a multi-process local
  "standalone cluster": N long-lived executor processes with one task slot
  each, the same process topology the reference's test harness built with a
  2-worker Spark Standalone cluster (reference test/run_tests.sh:16-19,
  SURVEY.md §4). No pyspark required.
* a real ``pyspark.SparkContext`` — used as-is when available; the framework
  only calls public RDD methods, so any genuine Spark cluster works.
"""


def is_spark_context(sc):
    """True if ``sc`` is a real pyspark SparkContext (duck-typed; pyspark may
    not be installed at all)."""
    mod = type(sc).__module__ or ""
    return mod.startswith("pyspark")
