"""Execution backends.

The framework's driver API is written against the small slice of the Spark
surface it actually uses (``parallelize``/``union``/``foreachPartition``/
``mapPartitions``/``collect``). Two backends provide it:

* :mod:`~tensorflowonspark_tpu.backends.local` — a multi-process local
  "standalone cluster": N long-lived executor processes with one task slot
  each, the same process topology the reference's test harness built with a
  2-worker Spark Standalone cluster (reference test/run_tests.sh:16-19,
  SURVEY.md §4). No pyspark required.
* a real ``pyspark.SparkContext`` — used as-is when available; the framework
  only calls public RDD methods, so any genuine Spark cluster works.
"""


def is_spark_context(sc):
    """True if ``sc`` is a real pyspark SparkContext (duck-typed; pyspark may
    not be installed at all)."""
    mod = type(sc).__module__ or ""
    return mod.startswith("pyspark")


def create_dataframe(sc, rows, columns, num_partitions=None):
    """Build a DataFrame on either backend: the local backend's
    ``createDataFrame`` (LocalDataFrame), or — on a real pyspark
    SparkContext, which has no such method — the session's
    ``createDataFrame`` over a parallelized RDD."""
    if is_spark_context(sc):
        from pyspark.sql import SparkSession

        rdd = (
            sc.parallelize(rows, num_partitions)
            if num_partitions else sc.parallelize(rows)
        )
        return SparkSession(sc).createDataFrame(rdd, list(columns))
    return sc.createDataFrame(rows, list(columns), num_partitions)


def get_spark_context(app_name, num_executors=None, task_timeout=600, sc=None,
                      local_default=1):
    """The examples' context factory: a REAL ``pyspark.SparkContext`` when
    the program is running under Spark, the bundled local backend otherwise.
    Returns ``(sc, num_executors, owned)`` — ``owned`` False when the
    context came from the caller or an already-active pyspark context was
    reused (don't stop what you did not create).

    Pass ``sc`` to inject an existing context of either backend (tests, or
    apps that built their own): it is returned as-is with ``owned=False``.

    "Running under Spark" means pyspark is importable AND one of: an active
    SparkContext already exists (spark-submit re-running the driver),
    ``MASTER``/``SPARK_MASTER`` is set, spark-submit's launch scripts ran
    (``SPARK_ENV_LOADED``), or ``TOS_SPARK=1`` forces it. ``TOS_SPARK=0``
    forces the local backend even with pyspark installed.

    ``num_executors`` is the user's EXPLICIT request (examples pass their
    ``--cluster_size`` flag with ``default=None``) and always wins — with a
    WARNING when it disagrees with the submitted conf. Without it, a real
    context sizes from ``spark.executor.instances`` (the reference
    examples' own rule, e.g. reference examples/mnist/keras/
    mnist_spark.py:29-31), else ``defaultParallelism`` (standalone
    clusters don't set ``instances``), else ``local_default``; the local
    backend uses ``local_default``. The same resolution applies to an
    injected ``sc``.
    """
    import logging
    import os

    logger = logging.getLogger(__name__)
    if sc is not None:
        return sc, _resolve_executor_count(sc, num_executors, local_default, logger), False
    forced = os.environ.get("TOS_SPARK")
    use_spark = False
    if forced != "0":
        try:
            import pyspark

            active = pyspark.SparkContext._active_spark_context is not None
            use_spark = (
                forced == "1"
                or active
                or bool(os.environ.get("MASTER") or os.environ.get("SPARK_MASTER"))
                or bool(os.environ.get("SPARK_ENV_LOADED"))
            )
        except ImportError:
            if forced == "1":
                raise
    if use_spark:
        import pyspark

        existing = pyspark.SparkContext._active_spark_context
        owned = existing is None
        conf = pyspark.SparkConf().setAppName(app_name)
        master = os.environ.get("MASTER") or os.environ.get("SPARK_MASTER")
        if owned and master and not conf.contains("spark.master"):
            conf.setMaster(master)
        sc = existing if existing is not None else pyspark.SparkContext(conf=conf)
        resolved = _resolve_executor_count(sc, num_executors, local_default, logger)
        logger.info(
            "using real pyspark SparkContext (master=%s, %d executors)",
            sc.master, resolved,
        )
        return sc, resolved, owned

    from tensorflowonspark_tpu.backends.local import LocalSparkContext

    n = num_executors or local_default
    return LocalSparkContext(num_executors=n, task_timeout=task_timeout), n, True


def _resolve_executor_count(sc, num_executors, local_default, logger):
    """get_spark_context's sizing rule, shared by the active-context and
    injected-``sc`` paths: explicit request > submitted conf >
    defaultParallelism > local_default."""
    instances = None
    if is_spark_context(sc):
        raw = sc.getConf().get("spark.executor.instances")
        instances = int(raw) if raw else None
    if num_executors:
        if instances and instances != num_executors:
            logger.warning(
                "explicit cluster size %d overrides spark.executor.instances=%d",
                num_executors, instances,
            )
        return num_executors
    if instances:
        return instances
    if is_spark_context(sc):
        return sc.defaultParallelism or local_default
    return local_default
