"""Execution backends.

The framework's driver API is written against the small slice of the Spark
surface it actually uses (``parallelize``/``union``/``foreachPartition``/
``mapPartitions``/``collect``). Two backends provide it:

* :mod:`~tensorflowonspark_tpu.backends.local` — a multi-process local
  "standalone cluster": N long-lived executor processes with one task slot
  each, the same process topology the reference's test harness built with a
  2-worker Spark Standalone cluster (reference test/run_tests.sh:16-19,
  SURVEY.md §4). No pyspark required.
* a real ``pyspark.SparkContext`` — used as-is when available; the framework
  only calls public RDD methods, so any genuine Spark cluster works.
"""


def is_spark_context(sc):
    """True if ``sc`` is a real pyspark SparkContext (duck-typed; pyspark may
    not be installed at all)."""
    mod = type(sc).__module__ or ""
    return mod.startswith("pyspark")


def create_dataframe(sc, rows, columns, num_partitions=None):
    """Build a DataFrame on either backend: the local backend's
    ``createDataFrame`` (LocalDataFrame), or — on a real pyspark
    SparkContext, which has no such method — the session's
    ``createDataFrame`` over a parallelized RDD."""
    if is_spark_context(sc):
        from pyspark.sql import SparkSession

        rdd = (
            sc.parallelize(rows, num_partitions)
            if num_partitions else sc.parallelize(rows)
        )
        return SparkSession(sc).createDataFrame(rdd, list(columns))
    return sc.createDataFrame(rows, list(columns), num_partitions)


def get_spark_context(app_name, num_executors=None, task_timeout=600, sc=None,
                      local_default=1):
    """The examples' context factory: a REAL ``pyspark.SparkContext`` when
    the program is running under Spark, the bundled local backend otherwise.
    Returns ``(sc, num_executors, owned)`` — ``owned`` False when the
    context came from the caller or an already-active pyspark context was
    reused (don't stop what you did not create).

    Pass ``sc`` to inject an existing context of either backend (tests, or
    apps that built their own): it is returned as-is with ``owned=False``.

    "Running under Spark" means pyspark is importable AND one of: an active
    SparkContext already exists (spark-submit re-running the driver),
    ``MASTER``/``SPARK_MASTER`` is set, spark-submit's launch scripts ran
    (``SPARK_ENV_LOADED``), or ``TOS_SPARK=1`` forces it. ``TOS_SPARK=0``
    forces the local backend even with pyspark installed.

    ``num_executors`` is the user's EXPLICIT request (examples pass their
    ``--cluster_size`` flag with ``default=None``). Resolution on the real
    path: ``spark.executor.instances`` from the submitted conf (deployment
    truth — the reference examples' own rule, e.g. reference
    examples/mnist/keras/mnist_spark.py:29-31), else the explicit request
    (which must never be silently overridden), else ``defaultParallelism``
    (standalone clusters don't set ``instances`` — size from the cluster,
    not from an example's argparse default). On the local backend:
    the explicit request, else ``local_default``.
    """
    import logging
    import os

    logger = logging.getLogger(__name__)
    if sc is not None:
        return sc, (num_executors or local_default), False
    forced = os.environ.get("TOS_SPARK")
    use_spark = False
    if forced != "0":
        try:
            import pyspark

            active = pyspark.SparkContext._active_spark_context is not None
            use_spark = (
                forced == "1"
                or active
                or bool(os.environ.get("MASTER") or os.environ.get("SPARK_MASTER"))
                or bool(os.environ.get("SPARK_ENV_LOADED"))
            )
        except ImportError:
            if forced == "1":
                raise
    if use_spark:
        import pyspark

        existing = pyspark.SparkContext._active_spark_context
        owned = existing is None
        conf = pyspark.SparkConf().setAppName(app_name)
        master = os.environ.get("MASTER") or os.environ.get("SPARK_MASTER")
        if owned and master and not conf.contains("spark.master"):
            conf.setMaster(master)
        sc = existing if existing is not None else pyspark.SparkContext(conf=conf)
        instances = sc.getConf().get("spark.executor.instances")
        resolved = (
            int(instances) if instances
            else (num_executors or sc.defaultParallelism or 1)
        )
        logger.info(
            "using real pyspark SparkContext (master=%s, %d executors)",
            sc.master, resolved,
        )
        return sc, resolved, owned

    from tensorflowonspark_tpu.backends.local import LocalSparkContext

    n = num_executors or local_default
    return LocalSparkContext(num_executors=n, task_timeout=task_timeout), n, True
