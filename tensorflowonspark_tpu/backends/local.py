"""Local multi-process execution backend — the Spark stand-in.

Emulates exactly the Spark semantics the framework depends on (SURVEY.md §4:
the reference's hard invariant is *one task slot per executor*, which its test
harness realized as a 2-worker local Standalone cluster with 1 core each):

* N long-lived **executor processes**, each with its own working directory and
  a single task slot — so per-executor state (the IPC channel, the jax child
  process, the executor-state file) survives across tasks, like
  ``SPARK_REUSE_WORKER=1``.
* **Jobs** fan partition tasks out to executors. Launch jobs can *pin*
  partition *i* to executor *i* (Spark achieves the same distribution
  stochastically plus the reference's retry-on-stale-manager trick,
  TFSparkNode.py:173-179); feed jobs go through a shared queue and land on
  whichever executor is free — exercising the reconnect-via-state-file path.
* Lazy RDDs with ``mapPartitions`` composition; actions are
  ``collect``/``foreachPartition``/``count``/``sum``.

This backend is a first-class deployment option for single-host TPU boxes (no
JVM needed) *and* the test harness for the Spark code paths.
"""

import logging
import os
import queue
import shutil
import tempfile
import threading
import time
import traceback
import uuid

import cloudpickle

from tensorflowonspark_tpu import resilience

logger = logging.getLogger(__name__)

# Spawned (never forked): a LocalSparkContext is routinely created from a
# threaded parent (pytest with a prior context's collector thread, jax's
# thread pools), and forking a threaded process deadlocks — the documented
# full-suite hang. Executor children are spawn-clean; the jax child each
# node launch starts is itself spawned (util.spawn_process).
_mp = __import__("multiprocessing").get_context("spawn")

#: module-global registry, inside each executor process, of background
#: child processes started by node-launch tasks (reaped at executor stop)
_executor_children = []


def register_child_process(proc):
    """Called from node-launch tasks to let the executor reap the jax child."""
    _executor_children.append(proc)


def _executor_main(executor_id, workdir, private_q, shared_q, result_q, stop_ev):
    os.chdir(workdir)
    os.environ["TOS_LOCAL_EXECUTOR_ID"] = str(executor_id)
    logger.info("local executor %d up in %s", executor_id, workdir)
    while not stop_ev.is_set():
        task = None
        try:
            task = private_q.get(timeout=0.05)
        except queue.Empty:
            try:
                task = shared_q.get(timeout=0.05)
            except queue.Empty:
                continue
        if task is None:
            break
        job_id, pidx, fn_blob, data_blob = task
        try:
            fn = cloudpickle.loads(fn_blob)
            data = cloudpickle.loads(data_blob)
            result = fn(iter(data), pidx)
            payload = cloudpickle.dumps(list(result) if result is not None else None)
            result_q.put((job_id, pidx, executor_id, "ok", payload))
        except BaseException:
            result_q.put((job_id, pidx, executor_id, "error", traceback.format_exc()))
    # reap background children (the jax processes) on the way out
    for proc in _executor_children:
        if proc.is_alive():
            proc.terminate()
            proc.join(timeout=5)
    logger.info("local executor %d down", executor_id)


class TaskError(RuntimeError):
    """A partition task failed on an executor; carries the remote traceback."""

    def __init__(self, executor_id, partition, remote_traceback):
        super().__init__(
            "task for partition {} failed on executor {}:\n{}".format(
                partition, executor_id, remote_traceback
            )
        )
        self.executor_id = executor_id
        self.partition = partition
        self.remote_traceback = remote_traceback


class _Job:
    def __init__(self, job_id, num_tasks):
        self.job_id = job_id
        self.num_tasks = num_tasks
        self.results = {}
        self.error = None
        self.done = threading.Event()

    def wait(self, timeout=None):
        if not self.done.wait(timeout=timeout):
            raise TimeoutError("job {} did not finish in {}s".format(self.job_id, timeout))
        if self.error is not None:
            raise self.error
        return [self.results[i] for i in range(self.num_tasks)]


class LocalRDD:
    """Minimal lazy RDD: each partition carries its data and its own chain of
    per-partition iterator transforms (so unions of differently-transformed
    RDDs — e.g. the epochs-via-union trick over a mapped RDD — just work)."""

    def __init__(self, sc, parts):
        self._sc = sc
        self._parts = list(parts)  # [(data, fns_tuple), ...]
        self._pinned = False

    # transformations ---------------------------------------------------------

    def mapPartitions(self, fn):
        rdd = LocalRDD(self._sc, [(data, fns + (fn,)) for data, fns in self._parts])
        rdd._pinned = self._pinned
        return rdd

    def mapPartitionsWithIndex(self, fn):
        """``fn(partition_index, iterator)`` like pyspark's. The flag lives on
        a fresh wrapper, never on the caller's function object."""

        def _indexed(pidx, it, _fn=fn):
            return _fn(pidx, it)

        _indexed._wants_index = True
        return self.mapPartitions(_indexed)

    def map(self, fn):
        def _mapper(it, _fn=fn):
            return (_fn(x) for x in it)

        return self.mapPartitions(_mapper)

    def union(self, other):
        return LocalRDD(self._sc, self._parts + other._parts)

    def cache(self):
        return self

    # actions -----------------------------------------------------------------

    def getNumPartitions(self):
        return len(self._parts)

    def foreachPartition(self, fn):
        self.mapPartitions(fn)._execute()
        return None

    def collect(self):
        parts = self._execute()
        return [x for part in parts for x in (part or [])]

    def count(self):
        return len(self.collect())

    def sum(self):
        return sum(self.collect())

    def _execute(self):
        job = self._sc._submit_job(self._parts, pin=self._pinned)
        return job.wait(timeout=self._sc.task_timeout)


def _make_chain(fns):
    def _chain(it, pidx, _fns=fns):
        for f in _fns:
            it = f(pidx, it) if getattr(f, "_wants_index", False) else f(it)
        return it if it is not None else []

    return _chain


class LocalDataFrame:
    """Minimal columnar view over a LocalRDD of row tuples — just enough
    DataFrame surface for the ML pipeline layer (select/columns/rdd/collect),
    mirroring how the reference pipeline uses Spark DataFrames
    (pipeline.py:411-413 ``dataset.select(cols).rdd``)."""

    def __init__(self, rdd, columns):
        self._rdd = rdd
        self.columns = list(columns)

    def select(self, *cols):
        if len(cols) == 1 and isinstance(cols[0], (list, tuple)):
            cols = tuple(cols[0])
        idx = [self.columns.index(c) for c in cols]

        def _project(it, _idx=tuple(idx)):
            return (tuple(row[i] for i in _idx) for row in it)

        return LocalDataFrame(self._rdd.mapPartitions(_project), cols)

    @property
    def rdd(self):
        return self._rdd

    def collect(self):
        return self._rdd.collect()

    def count(self):
        return self._rdd.count()


class LocalDStream:
    """Micro-batch stream handle (the ``pyspark.streaming.DStream`` surface
    the framework uses: ``foreachRDD``)."""

    def __init__(self, ssc):
        self._ssc = ssc
        self._handlers = []

    def foreachRDD(self, fn):
        self._handlers.append(fn)
        return self


class LocalStreamingContext:
    """DStream-equivalent micro-batch driver — the ``StreamingContext``
    stand-in for single-host deployments and tests (the reference fed
    training from Spark Streaming DStreams,
    /root/reference/tensorflowonspark/TFCluster.py:83-85 and
    examples/mnist/estimator/mnist_spark_streaming.py).

    ``queueStream`` mirrors pyspark's: one queued RDD is consumed per batch
    interval; ``feed`` pushes further micro-batches while running.
    """

    def __init__(self, sc, batch_interval=1.0):
        self.sc = sc
        self.batch_interval = batch_interval
        # bounded: a producer outpacing the batch ticker should block at the
        # feed call, not grow the backlog without limit
        self._queue = queue.Queue(maxsize=1024)
        self._streams = []
        self._stop_ev = threading.Event()
        self._thread = None
        self._busy = threading.Lock()  # held while a micro-batch is feeding

    def queueStream(self, rdds=None):
        stream = LocalDStream(self)
        self._streams.append(stream)
        for rdd in rdds or []:
            self._queue.put(rdd)
        return stream

    def feed(self, rdd):
        """Push one more micro-batch into the stream."""
        self._queue.put(rdd)

    def start(self):
        def _run():
            while not self._stop_ev.is_set():
                # dequeue AND handle under one lock hold: a batch popped but
                # not yet feeding must be invisible to stop()'s graceful
                # drain, or it feeds after the end-of-feed markers
                with self._busy:
                    try:
                        rdd = self._queue.get(timeout=self.batch_interval)
                    except queue.Empty:
                        continue
                    for stream in self._streams:
                        for handler in stream._handlers:
                            try:
                                handler(rdd)
                            except Exception:
                                logger.exception("streaming micro-batch handler failed")

        self._thread = threading.Thread(target=_run, name="tos-streaming", daemon=True)
        self._thread.start()

    def stop(self, stopSparkContext=False, stopGraceFully=True):
        if stopGraceFully:
            # drain queued micro-batches AND wait out the in-flight handler —
            # queue emptiness alone would let shutdown's end-of-feed markers
            # cut off a batch that was dequeued but not yet fully fed
            drain = resilience.Backoff(base=0.1, factor=1.0, max_delay=0.1, jitter=0.0)
            for _ in drain.attempts(deadline=resilience.Deadline(60)):
                if self._queue.empty():
                    break
            with self._busy:
                pass
        self._stop_ev.set()
        if self._thread is not None:
            self._thread.join(timeout=30)
        if stopSparkContext:
            self.sc.stop()

    def awaitTermination(self, timeout=None):
        if self._thread is not None:
            self._thread.join(timeout=timeout)


class LocalSparkContext:
    """Driver handle to the local executor pool (the ``sc`` stand-in)."""

    PIN_SUPPORTED = True

    def __init__(self, num_executors=2, workdir_root=None, task_timeout=600):
        self.num_executors = num_executors
        self.defaultParallelism = num_executors
        self.task_timeout = task_timeout
        self.applicationId = "local-" + uuid.uuid4().hex[:8]
        self.defaultFS = "file://"
        self._workdir_root = workdir_root or tempfile.mkdtemp(prefix="tos_local_")
        self._own_workdir = workdir_root is None
        self._result_q = _mp.Queue()
        self._shared_q = _mp.Queue()
        self._stop_ev = _mp.Event()
        self._jobs = {}
        self._jobs_lock = threading.Lock()
        self._job_counter = 0
        self._private_qs = []
        self._procs = []
        for i in range(num_executors):
            wd = os.path.join(self._workdir_root, "executor-{}".format(i))
            os.makedirs(wd, exist_ok=True)
            pq = _mp.Queue()
            proc = _mp.Process(
                target=_executor_main,
                args=(i, wd, pq, self._shared_q, self._result_q, self._stop_ev),
                name="local-executor-{}".format(i),
                daemon=False,
            )
            proc.start()
            self._private_qs.append(pq)
            self._procs.append(proc)
        self._collector = threading.Thread(
            target=self._collect_results, name="tos-local-collector", daemon=True
        )
        self._collector.start()

    # Spark-surface API -------------------------------------------------------

    def parallelize(self, data, numSlices=None, pin_to_executors=False):
        """``pin_to_executors`` may be True (partition i → executor i) or an
        explicit list of executor ids (partition i → executor ids[i])."""
        data = list(data)
        n = numSlices or self.defaultParallelism
        n = max(1, min(n, len(data)) if data else n)
        size, extra = divmod(len(data), n)
        partitions, start = [], 0
        for i in range(n):
            end = start + size + (1 if i < extra else 0)
            partitions.append(data[start:end])
            start = end
        rdd = LocalRDD(self, [(p, ()) for p in partitions])
        rdd._pinned = (
            list(pin_to_executors) if isinstance(pin_to_executors, (list, tuple)) else bool(pin_to_executors)
        )
        return rdd

    def union(self, rdds):
        out = rdds[0]
        for r in rdds[1:]:
            out = out.union(r)
        return out

    def createDataFrame(self, data, columns, numSlices=None):
        """Rows (tuples/lists) + column names → LocalDataFrame."""
        rows = [tuple(r) for r in data]
        return LocalDataFrame(self.parallelize(rows, numSlices), columns)

    def stop(self, cleanup=True):
        self._stop_ev.set()
        for pq in self._private_qs:
            try:
                pq.put(None)
            except (OSError, ValueError):
                pass
        for proc in self._procs:
            proc.join(timeout=10)
            if proc.is_alive():
                logger.warning("killing unresponsive executor %s", proc.name)
                proc.kill()
                proc.join(timeout=5)
        # collector re-checks _stop_ev every 0.2s result-queue timeout
        self._collector.join(timeout=5)
        if cleanup and self._own_workdir:
            shutil.rmtree(self._workdir_root, ignore_errors=True)

    # scheduling --------------------------------------------------------------

    def _submit_job(self, parts, pin=False):
        """``parts``: [(data, fns_tuple), ...]. Each distinct transform chain
        is cloudpickled once per job (a feed job unions the same chain over
        epochs × partitions; re-serializing the closure per partition was the
        dominant driver-side cost)."""
        with self._jobs_lock:
            self._job_counter += 1
            job_id = self._job_counter
            job = _Job(job_id, len(parts))
            self._jobs[job_id] = job
        targets = None
        if pin:
            targets = list(pin) if isinstance(pin, (list, tuple)) else list(range(len(parts)))
            if len(targets) < len(parts) or any(t >= self.num_executors for t in targets):
                raise ValueError(
                    "cannot pin {} partitions onto executors {} (pool size {})".format(
                        len(parts), targets, self.num_executors
                    )
                )
        fn_blobs = {}
        data_blobs = {}  # keyed by id(): epoch-unions repeat the same lists
        for pidx, (data, fns) in enumerate(parts):
            fn_blob = fn_blobs.get(fns)
            if fn_blob is None:
                fn_blob = fn_blobs[fns] = cloudpickle.dumps(_make_chain(fns))
            data_blob = data_blobs.get(id(data))
            if data_blob is None:
                data_blob = data_blobs[id(data)] = cloudpickle.dumps(data)
            task = (job_id, pidx, fn_blob, data_blob)
            if targets is not None:
                self._private_qs[targets[pidx]].put(task)
            else:
                self._shared_q.put(task)
        return job

    def _collect_results(self):
        while True:
            try:
                job_id, pidx, eid, status, payload = self._result_q.get(timeout=0.2)
            except queue.Empty:
                if self._stop_ev.is_set():
                    return
                continue
            with self._jobs_lock:
                job = self._jobs.get(job_id)
            if job is None:
                continue
            if status == "error":
                job.error = TaskError(eid, pidx, payload)
                job.done.set()
            else:
                job.results[pidx] = cloudpickle.loads(payload)
                if len(job.results) == job.num_tasks:
                    job.done.set()
            if job.done.is_set():
                with self._jobs_lock:
                    self._jobs.pop(job_id, None)
