"""Per-executor IPC manager: the same-host feed channel into the jax process.

Capability-parity with /root/reference/tensorflowonspark/TFManager.py — a
``multiprocessing.managers.BaseManager`` exposing named joinable queues and a
key/value state store, in ``'local'`` (unix socket, same host) or ``'remote'``
(TCP, reachable from the driver) mode — re-designed around a single proxied
server-side object instead of module globals, so values returned from proxy
method calls are plain picklable objects rather than nested proxies.

In the TPU runtime this channel carries Spark partition data from the
short-lived Spark python workers into the long-lived per-host jax process,
where it is batched and ``jax.device_put`` onto the local chips (the infeed
analogue of the reference's queue → ``tf.data.from_generator`` path).
"""

import logging
import multiprocessing
import queue
import threading
from multiprocessing.managers import BaseManager

from tensorflowonspark_tpu import chaos

logger = logging.getLogger(__name__)

#: queue names created by default for worker nodes
WORKER_QUEUES = ("input", "output", "error")
#: extra queue for driver-managed roles (reference: ps/evaluator 'control' queue)
CONTROL_QUEUES = ("input", "output", "error", "control")


class _Channel:
    """Server-side state: named joinable queues plus a k/v store.

    Lives inside the manager server process; clients interact through an
    auto-generated proxy, so every method's arguments/returns must be plain
    picklable values.
    """

    def __init__(self, qnames):
        self._queues = {name: queue.Queue() for name in qnames}
        self._kv = {}
        self._lock = threading.Lock()

    # k/v store -------------------------------------------------------------
    def kv_get(self, key, default=None):
        with self._lock:
            return self._kv.get(key, default)

    def kv_set(self, key, value):
        with self._lock:
            self._kv[key] = value

    # queue ops (routed by name to avoid nested proxies) --------------------
    def put(self, qname, item, block=True, timeout=None):
        self._queues[qname].put(item, block=block, timeout=timeout)

    def get(self, qname, block=True, timeout=None):
        return self._queues[qname].get(block=block, timeout=timeout)

    def task_done(self, qname):
        self._queues[qname].task_done()

    def join(self, qname):
        self._queues[qname].join()

    def unfinished(self, qname):
        # Queue.join() can't take a timeout; expose the unfinished-task count
        # so clients can poll with error-checking (reference polled the error
        # queue while joining in a thread, TFSparkNode.py:436-447).
        q = self._queues[qname]
        with q.all_tasks_done:
            return q.unfinished_tasks

    def qsize(self, qname):
        return self._queues[qname].qsize()

    def empty(self, qname):
        return self._queues[qname].empty()

    def queue_names(self):
        return sorted(self._queues)


class _ChannelManager(BaseManager):
    """Client-side manager class; knows the ``get_channel`` typeid only."""


_ChannelManager.register("get_channel")


#: the one _Channel instance inside a channel-server process
_server_channel = None


def _init_server_channel(qnames):
    global _server_channel
    _server_channel = _Channel(qnames)


def _get_server_channel():
    return _server_channel


class _HostManager(BaseManager):
    """Server-side manager class (module-level so the spawn start method can
    pickle its ``_run_server`` target)."""


_HostManager.register("get_channel", callable=_get_server_channel)


class QueueView:
    """A named-queue facade bound to one queue of an :class:`ExecutorIPC`.

    Provides the JoinableQueue-ish surface user code and the feed loops expect
    (put/get/task_done/join/empty/qsize).
    """

    __slots__ = ("_channel", "_name")

    def __init__(self, channel, name):
        self._channel = channel
        self._name = name

    def put(self, item, block=True, timeout=None):
        if chaos.active:
            chaos.delay("feed.stall")
        self._channel.put(self._name, item, block, timeout)

    def get(self, block=True, timeout=None):
        return self._channel.get(self._name, block, timeout)

    def get_nowait(self):
        return self._channel.get(self._name, False, None)

    def task_done(self):
        self._channel.task_done(self._name)

    def join(self):
        self._channel.join(self._name)

    def unfinished(self):
        return self._channel.unfinished(self._name)

    def empty(self):
        return self._channel.empty(self._name)

    def qsize(self):
        return self._channel.qsize(self._name)


class ExecutorIPC:
    """Handle to a (possibly remote) executor IPC channel.

    Wraps the BaseManager plumbing; what the rest of the framework passes
    around as ``mgr`` (reference code passed the raw TFManager).
    """

    def __init__(self, manager, address, authkey, mode):
        self._manager = manager
        self._channel = manager.get_channel()
        self.address = address
        self.authkey = authkey
        self.mode = mode

    # state machine: 'running' | 'terminating' | 'stopped'
    # (reference: TFSparkNode.py:195, TFNode.py:316, TFSparkNode.py:584-585)
    def get(self, key, default=None):
        return self._channel.kv_get(key, default)

    def set(self, key, value):
        self._channel.kv_set(key, value)

    def get_queue(self, qname):
        return QueueView(self._channel, qname)

    def queue_names(self):
        return self._channel.queue_names()

    def shutdown(self):
        try:
            self._manager.shutdown()
        except Exception:  # manager process may already be gone
            pass


def start(authkey, queues=WORKER_QUEUES, mode="local"):
    """Start a new IPC channel server for this executor.

    ``mode='local'`` binds a unix socket (same-host feed path);
    ``mode='remote'`` binds TCP on an ephemeral port so the driver can reach
    driver-managed roles at shutdown (reference TFManager.py:40-65).
    Returns an :class:`ExecutorIPC`.
    """
    if isinstance(authkey, str):
        authkey = authkey.encode("utf-8")
    # spawn context (fork from a threaded caller deadlocks — see
    # util.spawn_process); the channel object is created *inside* the server
    # process by the initializer, every get_channel proxy resolves to it
    ctx = multiprocessing.get_context("spawn")
    address = ("", 0) if mode == "remote" else None
    host = _HostManager(address=address, authkey=authkey, ctx=ctx)
    host.start(initializer=_init_server_channel, initargs=(tuple(queues),))
    # child processes of this process need the same authkey for digest auth
    multiprocessing.current_process().authkey = authkey
    addr = host.address
    if mode == "remote" and isinstance(addr, tuple):
        from tensorflowonspark_tpu import util

        addr = (util.get_ip_address(), addr[1])
    logger.info("started %s IPC channel at %s", mode, addr)
    return ExecutorIPC(host, addr, authkey, mode)


def connect(address, authkey):
    """Connect to an existing channel (same-host unix socket or remote TCP)."""
    if isinstance(authkey, str):
        authkey = authkey.encode("utf-8")
    if isinstance(address, list):
        address = tuple(address)
    multiprocessing.current_process().authkey = authkey
    mgr = _ChannelManager(address=address, authkey=authkey)
    mgr.connect()
    mode = "local" if isinstance(address, str) else "remote"
    return ExecutorIPC(mgr, address, authkey, mode)
