"""Shared resilience policies: backoff, deadlines, retry budgets, breakers.

Every layer of the stack talks to something that can fail transiently — the
reservation server during assembly, the serving socket under load, the
filesystem under a flaky FUSE mount. Before this module each call site
carried its own ad-hoc loop (a fixed ``2 ** attempt`` sleep here, a bare
re-raise there). This module centralizes the policy vocabulary:

- :class:`Backoff` — exponential backoff schedules with configurable
  jitter. Seedable, so tests can assert the exact schedule.
- :class:`Deadline` — an absolute time budget shared across attempts;
  ``sleep()`` never overshoots it.
- :class:`RetryPolicy` — a bounded retry budget combining the two, with an
  ``on_retry`` hook for caller-side accounting.
- :class:`CircuitBreaker` — closed/open/half-open, for callers that should
  stop hammering a peer that is clearly down.

All stdlib; safe to import from any process (driver, executor, jax child).
Retries and give-ups are counted in the :mod:`~tensorflowonspark_tpu.obs`
registry (``resilience_retries_total`` / ``resilience_giveups_total``).
"""

import random
import threading
import time

from tensorflowonspark_tpu import obs


class DeadlineExceeded(Exception):
    """The operation's time budget ran out before it succeeded."""


class RetryBudgetExhausted(Exception):
    """Every attempt allowed by the policy failed; ``__cause__`` is the
    last underlying error."""


class CircuitOpenError(Exception):
    """The circuit breaker is open; the call was not attempted."""


class Backoff:
    """An exponential backoff schedule: ``base * factor**n`` capped at
    ``max_delay``, with a configurable jitter fraction.

    ``jitter`` is the randomized fraction of each delay: ``0.0`` yields the
    deterministic schedule, ``1.0`` is "full jitter" (uniform in
    ``[0, delay]``), values in between keep ``(1 - jitter) * delay`` as a
    floor. Pass ``seed`` to make the jittered schedule reproducible —
    :meth:`delays` re-seeds on every call, so two iterations of the same
    ``Backoff`` produce identical schedules.
    """

    def __init__(self, base=0.5, factor=2.0, max_delay=30.0, jitter=1.0, seed=None):
        if base < 0 or factor < 1.0 or max_delay < 0:
            raise ValueError("base/max_delay must be >= 0 and factor >= 1")
        if not 0.0 <= jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")
        self.base = base
        self.factor = factor
        self.max_delay = max_delay
        self.jitter = jitter
        self.seed = seed

    def delays(self):
        """Yield the (infinite) delay schedule; one generator per burst of
        attempts, re-seeded so schedules are deterministic under a seed."""
        rng = random.Random(self.seed)
        delay = self.base
        while True:
            capped = min(delay, self.max_delay)
            if self.jitter:
                floor = capped * (1.0 - self.jitter)
                yield floor + rng.uniform(0.0, capped - floor)
            else:
                yield capped
            delay = min(delay * self.factor, self.max_delay)

    def attempts(self, deadline=None, sleep=time.sleep):
        """Yield attempt indices ``0, 1, 2, ...``, sleeping this schedule
        *between* attempts (never before the first one).

        With a :class:`Deadline`, the generator stops — instead of
        sleeping — once the budget is spent, and every sleep is clamped so
        it cannot overshoot. That makes ``for/else`` the natural shape for
        poll loops: ``break`` on success, the ``else`` branch is the
        timeout path::

            for _ in Backoff(base=0.1, jitter=0.0).attempts(Deadline(30)):
                if ready():
                    break
            else:
                raise TimeoutError(...)

        Without a deadline the generator is infinite (a paced ticker).
        """
        delays = self.delays()
        n = 0
        while True:
            yield n
            n += 1
            if deadline is not None:
                if deadline.expired():
                    return
                sleep(deadline.clamp(next(delays)))
            else:
                sleep(next(delays))

    def __repr__(self):
        return "Backoff(base={}, factor={}, max_delay={}, jitter={}, seed={})".format(
            self.base, self.factor, self.max_delay, self.jitter, self.seed
        )


class Ticker:
    """A drift-free periodic schedule on the monotonic clock, with jitter.

    Tick *n* is scheduled at ``t0 + n * interval + u_n``, where ``u_n`` is
    uniform in ``± jitter * interval`` (re-drawn per tick). Anchoring every
    tick to ``t0`` instead of "now + interval" keeps the long-run rate exact
    even when tick bodies take time — and the per-tick jitter keeps a fleet
    of N tickers started in the same assembly barrier from firing in
    lockstep (the synchronized-burst problem a heartbeat aggregation tree
    would otherwise amplify). Seedable for deterministic tests; overruns
    skip the sleep rather than sleeping negative.
    """

    def __init__(self, interval, jitter=0.0, seed=None,
                 clock=time.monotonic, sleep=time.sleep):
        if interval <= 0:
            raise ValueError("interval must be > 0")
        if not 0.0 <= jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")
        self.interval = float(interval)
        self.jitter = float(jitter)
        self.seed = seed
        self._clock = clock
        self._sleep = sleep

    def ticks(self, deadline=None):
        """Yield tick indices ``0, 1, 2, ...``, sleeping until each tick's
        scheduled time between yields. The first tick fires immediately.
        With a :class:`Deadline` the generator stops once the budget is
        spent; without one it is infinite."""
        rng = random.Random(self.seed)
        t0 = self._clock()
        n = 0
        while True:
            yield n
            n += 1
            if deadline is not None and deadline.expired():
                return
            offset = rng.uniform(-self.jitter, self.jitter) * self.interval if self.jitter else 0.0
            due = t0 + n * self.interval + offset
            delay = due - self._clock()
            if deadline is not None:
                if deadline.expired():
                    return
                delay = deadline.clamp(delay)
            if delay > 0:
                self._sleep(delay)

    def __repr__(self):
        return "Ticker(interval={}, jitter={}, seed={})".format(
            self.interval, self.jitter, self.seed
        )


class Deadline:
    """An absolute point on the monotonic clock shared across attempts.

    Unlike a per-attempt timeout, a deadline bounds the *total* time a
    caller is willing to wait — retries and backoff sleeps all draw from
    the same budget.
    """

    def __init__(self, timeout, clock=time.monotonic):
        self._clock = clock
        self.timeout = timeout
        self._expires = None if timeout is None else clock() + timeout

    def remaining(self):
        """Seconds left (``None`` = unbounded); never negative."""
        if self._expires is None:
            return None
        return max(0.0, self._expires - self._clock())

    def expired(self):
        return self._expires is not None and self._clock() >= self._expires

    def check(self):
        """Raise :class:`DeadlineExceeded` if the budget is spent."""
        if self.expired():
            raise DeadlineExceeded("deadline of {}s exceeded".format(self.timeout))

    def clamp(self, delay):
        """Trim ``delay`` so a sleep never overshoots the deadline."""
        rem = self.remaining()
        return delay if rem is None else min(delay, rem)


class RetryPolicy:
    """A bounded retry budget: at most ``max_attempts`` calls, sleeping a
    :class:`Backoff` schedule between them, the whole burst optionally
    bounded by a ``timeout`` (a fresh :class:`Deadline` per :meth:`call`).

    Only exceptions in ``retry_on`` are retried; anything else propagates
    immediately. When the budget runs out the last error propagates as-is
    (callers keep their existing exception contracts); when the *deadline*
    expires between attempts, :class:`DeadlineExceeded` is raised from the
    last error.

    ``on_retry(attempt, exc, delay)`` fires before each backoff sleep —
    call sites use it to keep their own counters and log lines.
    """

    def __init__(
        self,
        max_attempts=3,
        backoff=None,
        retry_on=(OSError,),
        timeout=None,
        on_retry=None,
        sleep=time.sleep,
        name=None,
    ):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.max_attempts = max_attempts
        self.backoff = backoff if backoff is not None else Backoff()
        self.retry_on = retry_on
        self.timeout = timeout
        self.on_retry = on_retry
        self._sleep = sleep
        self.name = name

    def call(self, fn, *args, **kwargs):
        """Invoke ``fn(*args, **kwargs)`` under this policy."""
        deadline = Deadline(self.timeout)
        delays = self.backoff.delays()
        last_err = None
        for attempt in range(self.max_attempts):
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                last_err = e
                if attempt >= self.max_attempts - 1:
                    break
                if deadline.expired():
                    obs.counter(
                        "resilience_giveups_total",
                        help="retry bursts that exhausted their budget",
                    ).inc()
                    raise DeadlineExceeded(
                        "{}: deadline exceeded after {} attempts".format(
                            self.name or "retry", attempt + 1
                        )
                    ) from e
                delay = deadline.clamp(next(delays))
                obs.counter(
                    "resilience_retries_total", help="retries performed by shared policies"
                ).inc()
                if self.on_retry is not None:
                    self.on_retry(attempt, e, delay)
                if delay > 0:
                    self._sleep(delay)
        obs.counter(
            "resilience_giveups_total", help="retry bursts that exhausted their budget"
        ).inc()
        raise last_err

    def __call__(self, fn):
        """Decorator form: ``@policy`` wraps ``fn`` in :meth:`call`."""

        def wrapped(*args, **kwargs):
            return self.call(fn, *args, **kwargs)

        wrapped.__name__ = getattr(fn, "__name__", "wrapped")
        wrapped.__doc__ = fn.__doc__
        return wrapped


#: circuit states
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """A minimal circuit breaker for peers that fail persistently.

    Closed (normal) → ``failure_threshold`` consecutive failures open the
    circuit → calls fail fast with :class:`CircuitOpenError` for
    ``reset_timeout`` seconds → the next :meth:`allow` admits exactly ONE
    half-open trial call (concurrent callers keep failing fast until the
    trial resolves) — success closes the circuit, failure reopens it (and
    restarts the timer, without re-counting the trip). Thread-safe; the
    clock is injectable for tests.
    """

    def __init__(self, failure_threshold=5, reset_timeout=30.0, clock=time.monotonic, name=None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.failure_threshold = failure_threshold
        self.reset_timeout = reset_timeout
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = None
        #: True while the single half-open trial call is outstanding
        self._probe_in_flight = False

    @property
    def state(self):
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self):
        # caller holds the lock
        if self._state == OPEN and self._clock() - self._opened_at >= self.reset_timeout:
            self._state = HALF_OPEN
            self._probe_in_flight = False
            obs.counter(
                "circuit_half_open_total",
                help="circuit breaker open -> half-open transitions",
            ).inc()

    def allow(self):
        """True if a call may proceed (transitions open → half-open when
        the reset timeout has elapsed). In HALF_OPEN, exactly one caller is
        admitted as the trial request — the admitting ``allow()`` consumes
        the probe token; concurrent probes are refused until the trial
        reports through :meth:`record_success` / :meth:`record_failure`."""
        with self._lock:
            self._maybe_half_open()
            if self._state == OPEN:
                return False
            if self._state == HALF_OPEN:
                if self._probe_in_flight:
                    return False
                self._probe_in_flight = True
            return True

    def record_success(self):
        with self._lock:
            self._state = CLOSED
            self._failures = 0
            self._opened_at = None
            self._probe_in_flight = False

    def record_failure(self):
        with self._lock:
            self._maybe_half_open()
            if self._state == HALF_OPEN:
                self._trip()
                return
            if self._state == OPEN:
                # a straggler reporting after the circuit already opened
                # (e.g. the losing half of a hedged pair): already counted,
                # no second trip
                return
            self._failures += 1
            if self._failures >= self.failure_threshold:
                self._trip()

    def _trip(self):
        # caller holds the lock
        self._state = OPEN
        self._failures = 0
        self._opened_at = self._clock()
        obs.counter("resilience_circuit_open_total", help="circuit breaker trips").inc()
        # cluster-level alias surfaced in TFCluster.metrics() (the
        # resilience_-prefixed counter predates it and is kept for
        # dashboard compatibility)
        obs.counter("circuit_open_total", help="circuit breaker trips").inc()

    def call(self, fn, *args, **kwargs):
        """Invoke ``fn`` through the breaker; raises
        :class:`CircuitOpenError` without calling when open."""
        if not self.allow():
            raise CircuitOpenError("{}: circuit open".format(self.name or "circuit"))
        try:
            result = fn(*args, **kwargs)
        except Exception:
            self.record_failure()
            raise
        self.record_success()
        return result
