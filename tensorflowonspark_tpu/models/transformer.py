"""Decoder-only transformer LM — the long-context flagship model.

No counterpart exists in the reference (its models are CNNs; SURVEY.md §5
notes sequence parallelism is entirely absent) — this model is the showcase
for the capabilities the TPU build adds: bfloat16 compute on the MXU, rotary
positions, and attention that transparently switches to **ring attention**
over the ``sp`` mesh axis for sequences too long for one chip
(:mod:`tensorflowonspark_tpu.parallel.ring_attention`).

Sharding: ``param_specs`` gives each weight a PartitionSpec combining tensor
parallelism (``tp``: attention heads / MLP hidden sharded) with FSDP
(``fsdp``: remaining large dims), and the model inserts activation sharding
constraints so XLA keeps activations distributed across dp/sp/tp instead of
gathering them.
"""

import dataclasses
import os
import re

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from tensorflowonspark_tpu.models import register
from tensorflowonspark_tpu.parallel.ring_attention import (
    plain_attention,
    ring_attention_sharded,
)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 6
    n_heads: int = 8
    d_ff: int = 2048
    max_seq_len: int = 2048
    dtype: str = "float32"  # compute dtype; params stay float32
    remat: bool = False  # jax.checkpoint each block: FLOPs for HBM
    #: "auto" — ring over sp when the mesh has it, else the pallas flash
    #: kernel on TPU, else plain XLA attention; or force "flash"/"plain"
    attention: str = "auto"
    #: >0 switches every block's MLP to a switch-routed mixture of experts
    #: sharded over the mesh's ``ep`` axis (expert parallelism)
    moe_experts: int = 0
    #: per-expert capacity per token group = factor * group_size / experts
    moe_capacity_factor: float = 1.25
    #: weight of the router load-balancing auxiliary loss
    moe_aux_weight: float = 0.01
    #: dispatch group size (GShard-style): dispatch/combine memory scales as
    #: factor * tokens * group_size — fixed G keeps it LINEAR in sequence
    #: length; rounded down to a divisor of the token count at trace time
    moe_group_size: int = 256

    @property
    def head_dim(self):
        return self.d_model // self.n_heads

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


def _rope(x, positions, base=10000.0):
    """Rotary position embedding over the last (head) dim; x: [B, L, H, D]."""
    d = x.shape[-1]
    half = d // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, :, None].astype(jnp.float32) * freqs  # [B, L, half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


_ATTENTION_IMPLS = ("auto", "flash", "plain", "ring")

#: below this sequence length ``auto`` dispatch uses plain XLA attention on
#: TPU instead of pad-to-128 + flash. Measured on-chip (docs/perf.md r3,
#: B=4 H=8 D=64 bf16 causal, best-of-3 fenced): flash ≥ plain at every
#: L ∈ {256, 512, 2048, 4096} and within relay noise at 1024, so the floor
#: only guards the tiny-sequence regime where padding overhead dominates.
_FLASH_MIN_SEQ = int(os.environ.get("TOS_FLASH_MIN_SEQ", "256"))


def _dispatch_attention(q, k, v, impl, mesh, segment_ids=None):
    """Pick the attention path. ``auto``: ring over ``sp`` when the mesh
    shards the sequence, else the pallas flash kernel on TPU (plain below
    ``TOS_FLASH_MIN_SEQ``), else plain XLA attention. Forcing
    ``plain``/``flash``/``ring`` always wins (``plain`` on an sp mesh is the
    debugging escape hatch — correct, just unsharded math).

    ``segment_ids`` (``int32 [B, L]``, 0 = padding) is the text plane's
    packed-sequence fence — every path turns it into the same
    block-diagonal mask, so packed neighbours never cross-attend.
    """
    if impl not in _ATTENTION_IMPLS:
        raise ValueError(
            "unknown attention impl {!r}; expected one of {}".format(impl, _ATTENTION_IMPLS)
        )
    if impl == "plain":
        return plain_attention(q, k, v, causal=True, segment_ids=segment_ids)
    has_sp = mesh is not None and "sp" in mesh.axis_names
    if impl == "ring" or (impl == "auto" and has_sp):
        return ring_attention_sharded(q, k, v, mesh, causal=True, segment_ids=segment_ids)
    if impl == "flash" or jax.default_backend() == "tpu":
        seq = q.shape[2]
        if impl != "flash" and seq < _FLASH_MIN_SEQ:
            return plain_attention(q, k, v, causal=True, segment_ids=segment_ids)
        from tensorflowonspark_tpu.ops.flash_attention import flash_attention

        pad = (-seq) % 128
        if pad:
            # causal masking means queries < seq never attend to the zero
            # padding appended after them, so pad-run-slice is exact; with
            # segments the appended columns get id 0, which never equals a
            # real (>= 1) segment — exact for the same reason
            q, k, v = (
                jnp.pad(t, ((0, 0), (0, 0), (0, pad), (0, 0))) for t in (q, k, v)
            )
            if segment_ids is not None:
                segment_ids = jnp.pad(segment_ids, ((0, 0), (0, pad)))
        out = flash_attention(
            q, k, v, causal=True, segment_ids=segment_ids,
            interpret=jax.default_backend() != "tpu",
        )
        return out[:, :, :seq] if pad else out
    return plain_attention(q, k, v, causal=True, segment_ids=segment_ids)


class Attention(nn.Module):
    cfg: TransformerConfig
    mesh: object = None  # jax.sharding.Mesh or None

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        dt = cfg.compute_dtype
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (cfg.n_heads, cfg.head_dim), axis=-1, use_bias=False, dtype=dt, name=name
        )
        q, k, v = dense("q")(x), dense("k")(x), dense("v")(x)  # [B, L, H, D]
        q = _rope(q, positions)
        k = _rope(k, positions)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))  # [B, H, L, D]
        out = _dispatch_attention(q, k, v, cfg.attention, self.mesh, segment_ids=segment_ids)
        out = out.transpose(0, 2, 1, 3)  # [B, L, H, D]
        return nn.DenseGeneral(
            cfg.d_model, axis=(-2, -1), use_bias=False, dtype=dt, name="o"
        )(out)


class Mlp(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        dt = self.cfg.compute_dtype
        h = nn.Dense(self.cfg.d_ff, use_bias=False, dtype=dt, name="wi")(x)
        h = nn.gelu(h)
        return nn.Dense(self.cfg.d_model, use_bias=False, dtype=dt, name="wo")(h)


class MoeMlp(nn.Module):
    """Switch-routed (top-1) mixture-of-experts MLP with dense dispatch.

    Expert parallelism the TPU way (absent from the reference — SURVEY.md
    §2.7 row "Expert parallelism"): expert weights carry an ``ep``-sharded
    leading dim and dispatch/combine are einsums against a static-shaped
    [tokens, E, C] mask, so XLA derives the all-to-all over the ``ep`` axis
    from the shardings — no hand-written collective, no dynamic shapes
    (GShard/Switch dense-dispatch formulation, done with einsum + psum-free
    code under pjit).
    """

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        dt = cfg.compute_dtype
        E = cfg.moe_experts
        B, S, D = x.shape
        tokens = B * S
        # GShard-style fixed-size token groups: capacity is per group, so the
        # [G_n, G, E, C] dispatch mask is linear (not quadratic) in tokens;
        # shrink G to a divisor of the static token count at trace time
        group = min(cfg.moe_group_size or tokens, tokens)
        while tokens % group:
            group -= 1
        n_groups = tokens // group
        capacity = max(1, int(cfg.moe_capacity_factor * group / E))

        xg = x.reshape(n_groups, group, D)
        router = nn.Dense(E, use_bias=False, dtype=jnp.float32, name="router")
        gates = jax.nn.softmax(router(xg.astype(jnp.float32)))  # [G_n, G, E]

        expert_idx = jnp.argmax(gates, axis=-1)  # [G_n, G]
        onehot = jax.nn.one_hot(expert_idx, E, dtype=jnp.float32)  # [G_n, G, E]
        gate = jnp.sum(gates * onehot, axis=-1)  # [G_n, G]

        # position of each token within its expert's per-group capacity
        # buffer; tokens beyond capacity drop (switch overflow semantics)
        position = jnp.cumsum(onehot, axis=1) * onehot - 1.0  # [G_n, G, E]
        keep = (position < capacity) & (onehot > 0)
        pos_cap = jnp.clip(position, 0, capacity - 1).astype(jnp.int32)
        dispatch = (
            keep[..., None]
            & (jax.nn.one_hot(pos_cap, capacity, dtype=jnp.bool_))
        )  # [G_n, G, E, C]
        combine = dispatch.astype(jnp.float32) * gate[..., None, None]

        # load-balancing aux loss (Switch eq. 4): E * sum_e f_e * p_e
        frac_tokens = jnp.mean(onehot, axis=(0, 1))
        frac_probs = jnp.mean(gates, axis=(0, 1))
        self.sow("losses", "moe_aux", E * jnp.sum(frac_tokens * frac_probs))

        wi = self.param(
            "wi", nn.initializers.lecun_normal(), (E, D, cfg.d_ff), jnp.float32
        )
        wo = self.param(
            "wo", nn.initializers.lecun_normal(), (E, cfg.d_ff, D), jnp.float32
        )
        expert_in = jnp.einsum(
            "gtec,gtd->gecd", dispatch.astype(dt), xg.astype(dt)
        )  # [G_n, E, C, D] — E is ep-sharded: XLA inserts the all-to-all here
        h = nn.gelu(jnp.einsum("gecd,edf->gecf", expert_in, wi.astype(dt)))
        out_e = jnp.einsum("gecf,efd->gecd", h, wo.astype(dt))  # [G_n, E, C, D]
        yg = jnp.einsum("gtec,gecd->gtd", combine.astype(dt), out_e)
        return yg.reshape(B, S, D)


class Block(nn.Module):
    cfg: TransformerConfig
    mesh: object = None

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        x = x + Attention(self.cfg, self.mesh, name="attn")(
            nn.RMSNorm(dtype=self.cfg.compute_dtype, name="ln1")(x), positions,
            segment_ids,
        )
        mlp = (
            MoeMlp(self.cfg, name="moe")
            if self.cfg.moe_experts > 0
            else Mlp(self.cfg, name="mlp")
        )
        x = x + mlp(nn.RMSNorm(dtype=self.cfg.compute_dtype, name="ln2")(x))
        return x


class Transformer(nn.Module):
    cfg: TransformerConfig
    mesh: object = None

    def _constrain(self, x):
        """Keep activations sharded batch×seq across the mesh. An axis whose
        size does not divide its dim is dropped (degrade-to-replicated, same
        contract as :func:`param_specs`) — real text slabs may carry any
        sequence length; ring attention pads internally."""
        if self.mesh is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P

        names = self.mesh.axis_names
        sizes = dict(zip(names, self.mesh.devices.shape))
        batch, div = [], 1
        for a in ("dp", "fsdp"):
            if a in names and x.shape[0] % (div * sizes[a]) == 0:
                batch.append(a)
                div *= sizes[a]
        batch = tuple(batch) or None
        if batch is not None and len(batch) == 1:
            batch = batch[0]
        seq = "sp" if "sp" in names and x.shape[1] % sizes["sp"] == 0 else None
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, P(batch, seq, None))
        )

    @nn.compact
    def __call__(self, tokens, positions=None, segment_ids=None):
        cfg = self.cfg
        x = nn.Embed(
            cfg.vocab_size, cfg.d_model, dtype=cfg.compute_dtype, name="embed"
        )(tokens)
        x = self._constrain(x)
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1])[None, :], tokens.shape
            )
        block = Block
        if cfg.remat:
            block = nn.remat(Block, static_argnums=())
        for i in range(cfg.n_layers):
            x = block(cfg, self.mesh, name="layer_{}".format(i))(
                x, positions, segment_ids
            )
            x = self._constrain(x)
        x = nn.RMSNorm(dtype=cfg.compute_dtype, name="ln_f")(x)
        logits = nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=cfg.compute_dtype, name="lm_head"
        )(x)
        return logits.astype(jnp.float32)


#: path-regex → PartitionSpec-template rules for tensor parallelism; dims not
#: named here fall back to fsdp placement when an fsdp axis exists.
_TP_RULES = (
    (r"attn/(q|k|v)/kernel$", ("fsdp", "tp", None)),  # [d_model, H, head_dim]
    (r"attn/o/kernel$", ("tp", None, "fsdp")),  # [H, head_dim, d_model]
    (r"mlp/wi/kernel$", ("fsdp", "tp")),  # [d_model, d_ff]
    (r"mlp/wo/kernel$", ("tp", "fsdp")),  # [d_ff, d_model]
    (r"moe/router/kernel$", (None, None)),  # [d_model, E] — replicated
    (r"moe/wi$", ("ep", "fsdp", "tp")),  # [E, d_model, d_ff]
    (r"moe/wo$", ("ep", "tp", "fsdp")),  # [E, d_ff, d_model]
    # vocab-parallel (Megatron-style): sharding d_model here instead forces
    # XLA to fully rematerialize the gather output to reach the activations'
    # P(batch, seq, None) layout (the round-1 dryrun's SPMD warning); with
    # the vocab dim sharded the gather lowers to masked-lookup + psum
    (r"embed/embedding$", ("fsdp", None)),  # [vocab, d_model]
    (r"lm_head/kernel$", ("fsdp", "tp")),  # [d_model, vocab]
)


def param_specs(params, mesh, tp_axis="tp"):
    """PartitionSpecs for the transformer's params over ``mesh``: tp rules
    above, fsdp for what they leave unnamed, replication for the rest. Axes
    not present in the mesh are dropped from the specs, so the same rules
    serve dp-only, dp×tp, fsdp×sp, etc. ``tp_axis`` renames the mesh axis
    the tensor-parallel dims land on (hybrid meshes sometimes spell it
    differently); the rules themselves always say ``"tp"``. An axis whose
    mesh size does not divide the dim it names is dropped for that dim
    (same degrade-to-replicated contract as the fsdp rules), so undersized
    debug models still place."""
    from jax.sharding import PartitionSpec as P

    names = set(mesh.axis_names)
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    specs = {}
    for path, leaf in flat:
        key = "/".join(
            p.key if hasattr(p, "key") else str(p) for p in path
        )
        spec = None
        for pattern, template in _TP_RULES:
            if re.search(pattern, key):
                axes = [tp_axis if a == "tp" else a for a in template]
                spec = P(*(
                    a
                    if a in names and leaf.shape[i] % sizes[a] == 0
                    else None
                    for i, a in enumerate(axes)
                ))
                break
        if spec is None:
            spec = P(*([None] * leaf.ndim))
        specs[key] = spec

    def lookup(path, leaf):
        key = "/".join(p.key if hasattr(p, "key") else str(p) for p in path)
        return specs[key]

    return jax.tree_util.tree_map_with_path(lookup, params)


@register("transformer")
def create_model(mesh=None, **cfg):
    return Transformer(TransformerConfig(**cfg), mesh=mesh)


def make_init_fn(model, sample_len=16):
    def init(rng):
        variables = model.init(rng, jnp.zeros((1, sample_len), jnp.int32))
        # sown collections (MoE aux losses) are per-step ephemera, not state
        return {k: v for k, v in variables.items() if k not in ("losses", "intermediates")}

    return init


def make_loss_fn(model):
    """Next-token LM loss; batch = {"tokens": int32 [B, L]} (optionally with
    {"mask": [B, L]} to exclude padding). MoE models contribute their sown
    router load-balancing losses, weighted by ``cfg.moe_aux_weight``.

    Packed batches from the text plane additionally carry ``segment_ids``
    and ``positions`` (``int32 [B, L]``): segments fence attention
    block-diagonally, per-segment positions keep the rotary phase local,
    and the loss drops targets that cross a pack boundary (the last token
    of one sequence must not be asked to predict the first of the next) or
    fall in padding."""

    def loss_fn(params, batch):
        tokens = batch["tokens"]
        seg = batch.get("segment_ids")
        pos = batch.get("positions")
        logits, mods = model.apply(
            {"params": params}, tokens[:, :-1],
            positions=None if pos is None else pos[:, :-1],
            segment_ids=None if seg is None else seg[:, :-1],
            mutable=["losses"],
        )
        targets = tokens[:, 1:]
        losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        mask = batch.get("mask")
        mask = None if mask is None else mask[:, 1:]
        if seg is not None:
            # a target is valid when its position and the position it is
            # predicted from share a real (non-pad) segment — the last token
            # of one packed sequence never predicts the first of the next
            seg_mask = ((seg[:, 1:] == seg[:, :-1]) & (seg[:, 1:] > 0)).astype(
                losses.dtype
            )
            mask = seg_mask if mask is None else mask * seg_mask
        if mask is not None:
            loss = (losses * mask).sum() / jnp.maximum(mask.sum(), 1)
        else:
            loss = losses.mean()
        metrics = {"perplexity": jnp.exp(loss)}
        aux = jax.tree.leaves(mods.get("losses", {}))
        if aux:
            moe_aux = sum(jnp.asarray(a).mean() for a in aux) / len(aux)
            metrics["moe_aux"] = moe_aux
            loss = loss + model.cfg.moe_aux_weight * moe_aux
        return loss, metrics

    return loss_fn
