"""Decoder-only transformer LM — the long-context flagship model.

No counterpart exists in the reference (its models are CNNs; SURVEY.md §5
notes sequence parallelism is entirely absent) — this model is the showcase
for the capabilities the TPU build adds: bfloat16 compute on the MXU, rotary
positions, and attention that transparently switches to **ring attention**
over the ``sp`` mesh axis for sequences too long for one chip
(:mod:`tensorflowonspark_tpu.parallel.ring_attention`).

Sharding: ``param_specs`` gives each weight a PartitionSpec combining tensor
parallelism (``tp``: attention heads / MLP hidden sharded) with FSDP
(``fsdp``: remaining large dims), and the model inserts activation sharding
constraints so XLA keeps activations distributed across dp/sp/tp instead of
gathering them.
"""

import dataclasses
import re

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from tensorflowonspark_tpu.models import register
from tensorflowonspark_tpu.parallel.ring_attention import (
    plain_attention,
    ring_attention_sharded,
)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 6
    n_heads: int = 8
    d_ff: int = 2048
    max_seq_len: int = 2048
    dtype: str = "float32"  # compute dtype; params stay float32
    remat: bool = False  # jax.checkpoint each block: FLOPs for HBM
    #: "auto" — ring over sp when the mesh has it, else the pallas flash
    #: kernel on TPU, else plain XLA attention; or force "flash"/"plain"
    attention: str = "auto"

    @property
    def head_dim(self):
        return self.d_model // self.n_heads

    @property
    def compute_dtype(self):
        return jnp.dtype(self.dtype)


def _rope(x, positions, base=10000.0):
    """Rotary position embedding over the last (head) dim; x: [B, L, H, D]."""
    d = x.shape[-1]
    half = d // 2
    freqs = base ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    angles = positions[:, :, None].astype(jnp.float32) * freqs  # [B, L, half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(x.dtype)


_ATTENTION_IMPLS = ("auto", "flash", "plain", "ring")


def _dispatch_attention(q, k, v, impl, mesh):
    """Pick the attention path. ``auto``: ring over ``sp`` when the mesh
    shards the sequence, else the pallas flash kernel on TPU, else plain XLA
    attention. Forcing ``plain``/``flash``/``ring`` always wins (``plain`` on
    an sp mesh is the debugging escape hatch — correct, just unsharded math).
    """
    if impl not in _ATTENTION_IMPLS:
        raise ValueError(
            "unknown attention impl {!r}; expected one of {}".format(impl, _ATTENTION_IMPLS)
        )
    if impl == "plain":
        return plain_attention(q, k, v, causal=True)
    has_sp = mesh is not None and "sp" in mesh.axis_names
    if impl == "ring" or (impl == "auto" and has_sp):
        return ring_attention_sharded(q, k, v, mesh, causal=True)
    if impl == "flash" or jax.default_backend() == "tpu":
        from tensorflowonspark_tpu.ops.flash_attention import flash_attention

        seq = q.shape[2]
        pad = (-seq) % 128 if seq > 512 else 0
        if pad:
            # causal masking means queries < seq never attend to the zero
            # padding appended after them, so pad-run-slice is exact
            q, k, v = (
                jnp.pad(t, ((0, 0), (0, 0), (0, pad), (0, 0))) for t in (q, k, v)
            )
        out = flash_attention(
            q, k, v, causal=True, interpret=jax.default_backend() != "tpu"
        )
        return out[:, :, :seq] if pad else out
    return plain_attention(q, k, v, causal=True)


class Attention(nn.Module):
    cfg: TransformerConfig
    mesh: object = None  # jax.sharding.Mesh or None

    @nn.compact
    def __call__(self, x, positions):
        cfg = self.cfg
        dt = cfg.compute_dtype
        dense = lambda name: nn.DenseGeneral(  # noqa: E731
            (cfg.n_heads, cfg.head_dim), axis=-1, use_bias=False, dtype=dt, name=name
        )
        q, k, v = dense("q")(x), dense("k")(x), dense("v")(x)  # [B, L, H, D]
        q = _rope(q, positions)
        k = _rope(k, positions)
        q, k, v = (t.transpose(0, 2, 1, 3) for t in (q, k, v))  # [B, H, L, D]
        out = _dispatch_attention(q, k, v, cfg.attention, self.mesh)
        out = out.transpose(0, 2, 1, 3)  # [B, L, H, D]
        return nn.DenseGeneral(
            cfg.d_model, axis=(-2, -1), use_bias=False, dtype=dt, name="o"
        )(out)


class Mlp(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        dt = self.cfg.compute_dtype
        h = nn.Dense(self.cfg.d_ff, use_bias=False, dtype=dt, name="wi")(x)
        h = nn.gelu(h)
        return nn.Dense(self.cfg.d_model, use_bias=False, dtype=dt, name="wo")(h)


class Block(nn.Module):
    cfg: TransformerConfig
    mesh: object = None

    @nn.compact
    def __call__(self, x, positions):
        x = x + Attention(self.cfg, self.mesh, name="attn")(
            nn.RMSNorm(dtype=self.cfg.compute_dtype, name="ln1")(x), positions
        )
        x = x + Mlp(self.cfg, name="mlp")(
            nn.RMSNorm(dtype=self.cfg.compute_dtype, name="ln2")(x)
        )
        return x


class Transformer(nn.Module):
    cfg: TransformerConfig
    mesh: object = None

    def _constrain(self, x):
        """Keep activations sharded batch×seq across the mesh."""
        if self.mesh is None:
            return x
        from jax.sharding import NamedSharding, PartitionSpec as P

        names = self.mesh.axis_names
        batch = tuple(a for a in ("dp", "fsdp") if a in names) or None
        if batch is not None and len(batch) == 1:
            batch = batch[0]
        seq = "sp" if "sp" in names else None
        return jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, P(batch, seq, None))
        )

    @nn.compact
    def __call__(self, tokens):
        cfg = self.cfg
        x = nn.Embed(
            cfg.vocab_size, cfg.d_model, dtype=cfg.compute_dtype, name="embed"
        )(tokens)
        x = self._constrain(x)
        positions = jnp.broadcast_to(
            jnp.arange(tokens.shape[1])[None, :], tokens.shape
        )
        block = Block
        if cfg.remat:
            block = nn.remat(Block, static_argnums=())
        for i in range(cfg.n_layers):
            x = block(cfg, self.mesh, name="layer_{}".format(i))(x, positions)
            x = self._constrain(x)
        x = nn.RMSNorm(dtype=cfg.compute_dtype, name="ln_f")(x)
        logits = nn.Dense(
            cfg.vocab_size, use_bias=False, dtype=cfg.compute_dtype, name="lm_head"
        )(x)
        return logits.astype(jnp.float32)


#: path-regex → PartitionSpec-template rules for tensor parallelism; dims not
#: named here fall back to fsdp placement when an fsdp axis exists.
_TP_RULES = (
    (r"attn/(q|k|v)/kernel$", ("fsdp", "tp", None)),  # [d_model, H, head_dim]
    (r"attn/o/kernel$", ("tp", None, "fsdp")),  # [H, head_dim, d_model]
    (r"mlp/wi/kernel$", ("fsdp", "tp")),  # [d_model, d_ff]
    (r"mlp/wo/kernel$", ("tp", "fsdp")),  # [d_ff, d_model]
    # vocab-parallel (Megatron-style): sharding d_model here instead forces
    # XLA to fully rematerialize the gather output to reach the activations'
    # P(batch, seq, None) layout (the round-1 dryrun's SPMD warning); with
    # the vocab dim sharded the gather lowers to masked-lookup + psum
    (r"embed/embedding$", ("fsdp", None)),  # [vocab, d_model]
    (r"lm_head/kernel$", ("fsdp", "tp")),  # [d_model, vocab]
)


def param_specs(params, mesh):
    """PartitionSpecs for the transformer's params over ``mesh``: tp rules
    above, fsdp for what they leave unnamed, replication for the rest. Axes
    not present in the mesh are dropped from the specs, so the same rules
    serve dp-only, dp×tp, fsdp×sp, etc."""
    from jax.sharding import PartitionSpec as P

    names = set(mesh.axis_names)

    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    specs = {}
    for path, leaf in flat:
        key = "/".join(
            p.key if hasattr(p, "key") else str(p) for p in path
        )
        spec = None
        for pattern, template in _TP_RULES:
            if re.search(pattern, key):
                spec = P(*(a if a in names else None for a in template))
                break
        if spec is None:
            spec = P(*([None] * leaf.ndim))
        specs[key] = spec

    def lookup(path, leaf):
        key = "/".join(p.key if hasattr(p, "key") else str(p) for p in path)
        return specs[key]

    return jax.tree_util.tree_map_with_path(lookup, params)


@register("transformer")
def create_model(mesh=None, **cfg):
    return Transformer(TransformerConfig(**cfg), mesh=mesh)


def make_init_fn(model, sample_len=16):
    def init(rng):
        return model.init(rng, jnp.zeros((1, sample_len), jnp.int32))

    return init


def make_loss_fn(model):
    """Next-token LM loss; batch = {"tokens": int32 [B, L]} (optionally with
    {"mask": [B, L]} to exclude padding)."""

    def loss_fn(params, batch):
        tokens = batch["tokens"]
        logits = model.apply({"params": params}, tokens[:, :-1])
        targets = tokens[:, 1:]
        losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        mask = batch.get("mask")
        if mask is not None:
            mask = mask[:, 1:]
            loss = (losses * mask).sum() / jnp.maximum(mask.sum(), 1)
        else:
            loss = losses.mean()
        return loss, {"perplexity": jnp.exp(loss)}

    return loss_fn
