"""flax model zoo covering the reference's example workloads (SURVEY.md §2.3)
plus the TPU-era flagship transformer:

* :mod:`~tensorflowonspark_tpu.models.mnist` — MLP/CNN MNIST classifiers
  (reference examples/mnist/keras/mnist_spark.py model).
* :mod:`~tensorflowonspark_tpu.models.resnet` — ResNet-50 v1.5 (ImageNet) and
  ResNet-56 (CIFAR) (reference examples/resnet/resnet_model.py,
  resnet_cifar_model.py).
* :mod:`~tensorflowonspark_tpu.models.segmentation` — U-Net image segmentation
  (reference examples/segmentation/segmentation_spark.py).
* :mod:`~tensorflowonspark_tpu.models.transformer` — decoder-only LM with
  ring-attention sequence parallelism; the long-context flagship.

Every module exposes ``create_model(**cfg)`` plus ``make_*_fn`` builders that
plug into :class:`tensorflowonspark_tpu.train.SyncDataParallel`.
"""

_REGISTRY = {}


def register(name):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn

    return deco


def get_model(name, **cfg):
    """Construct a registered model by name (e.g. 'mnist_cnn', 'resnet50',
    'resnet56', 'unet', 'transformer')."""
    if name not in _REGISTRY:
        # import lazily so get_model('resnet50') works without the caller
        # importing the module first
        from tensorflowonspark_tpu.models import mnist, resnet, segmentation, transformer  # noqa: F401
    if name not in _REGISTRY:
        raise KeyError("unknown model {!r}; known: {}".format(name, sorted(_REGISTRY)))
    return _REGISTRY[name](**cfg)
