"""Image segmentation U-Net.

Capability-parity with the reference's segmentation example
(/root/reference/examples/segmentation/segmentation_spark.py:70-122: a
MobileNetV2-encoder + pix2pix-upsampler "U-Net" on 128×128×3 images with 3
output classes). TPU-first: a clean conv U-Net with GroupNorm (no BN state to
synchronize, friendlier at the small per-chip batch sizes segmentation runs
at) and bfloat16 compute.
"""

import functools

import jax.numpy as jnp
import optax
from flax import linen as nn

from tensorflowonspark_tpu.models import register


class ConvBlock(nn.Module):
    filters: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        x = nn.gelu(nn.GroupNorm(num_groups=8, dtype=self.dtype)(conv(self.filters, (3, 3))(x)))
        x = nn.gelu(nn.GroupNorm(num_groups=8, dtype=self.dtype)(conv(self.filters, (3, 3))(x)))
        return x


class UNet(nn.Module):
    """Encoder/decoder with skip connections; depth-4 like the reference's
    MobileNetV2 feature pyramid (64→4 spatial)."""

    num_classes: int = 3
    base_filters: int = 32
    depth: int = 4
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        del train  # no dropout/BN; signature parity with the other models
        x = x.astype(self.dtype)
        skips = []
        for d in range(self.depth):
            x = ConvBlock(self.base_filters * 2**d, self.dtype, name="enc{}".format(d))(x)
            skips.append(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = ConvBlock(self.base_filters * 2**self.depth, self.dtype, name="bottleneck")(x)
        for d in reversed(range(self.depth)):
            x = nn.ConvTranspose(
                self.base_filters * 2**d, (2, 2), strides=(2, 2), dtype=self.dtype,
                name="up{}".format(d),
            )(x)
            x = jnp.concatenate([x, skips[d]], axis=-1)
            x = ConvBlock(self.base_filters * 2**d, self.dtype, name="dec{}".format(d))(x)
        logits = nn.Conv(self.num_classes, (1, 1), dtype=self.dtype, name="head")(x)
        return logits.astype(jnp.float32)


@register("unet")
def create_model(**cfg):
    return UNet(**cfg)


def make_init_fn(model, image_size=128, channels=3):
    def init(rng):
        return model.init(rng, jnp.zeros((1, image_size, image_size, channels)))

    return init


def make_loss_fn(model):
    """batch: {"image": [N,H,W,C] float, "mask": [N,H,W] int}."""

    def loss_fn(params, batch):
        logits = model.apply({"params": params}, batch["image"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["mask"]
        ).mean()
        iou_proxy = jnp.mean(jnp.argmax(logits, -1) == batch["mask"])
        return loss, {"pixel_accuracy": iou_proxy}

    return loss_fn


def make_predict_fn(model):
    def predict_fn(params, batch):
        return jnp.argmax(model.apply({"params": params}, batch["image"]), -1)

    return predict_fn
