"""MNIST classifiers — the framework's smoke-test workload.

Capability-parity with the reference's example models: the Keras MLP
(/root/reference/examples/mnist/keras/mnist_spark.py:27-31 — Flatten,
Dense(512, relu), Dropout(0.2), Dense(10, softmax)) and a small CNN. Models
compute in ``dtype`` (bfloat16 on TPU keeps the MXU fed) with float32 params.
"""

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn

from tensorflowonspark_tpu.models import register


class MnistMLP(nn.Module):
    """The reference Keras model, flax-style."""

    hidden: int = 512
    num_classes: int = 10
    dropout_rate: float = 0.2
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        x = x.astype(self.dtype).reshape((x.shape[0], -1))
        x = nn.Dense(self.hidden, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=self.dtype)(x).astype(jnp.float32)


class MnistCNN(nn.Module):
    """Conv net variant (for the TENSORFLOW-input-mode examples)."""

    num_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train=False):
        x = x.astype(self.dtype)
        if x.ndim == 3:
            x = x[..., None]
        x = x.reshape((x.shape[0], 28, 28, -1))
        x = nn.relu(nn.Conv(32, (3, 3), dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.relu(nn.Conv(64, (3, 3), dtype=self.dtype)(x))
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(128, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=self.dtype)(x).astype(jnp.float32)


@register("mnist_mlp")
def create_mlp(**cfg):
    return MnistMLP(**cfg)


@register("mnist_cnn")
def create_cnn(**cfg):
    return MnistCNN(**cfg)


def create_model(kind="mlp", **cfg):
    return MnistMLP(**cfg) if kind == "mlp" else MnistCNN(**cfg)


def make_init_fn(model, sample_shape=(1, 28, 28)):
    def init(rng):
        return model.init(rng, jnp.zeros(sample_shape, jnp.float32))

    return init


def make_loss_fn(model, dropout_seed=0):
    """``loss_fn(params, batch, step)`` for SyncDataParallel; batch keys
    ``image`` (N,28,28[,1]) float and ``label`` (N,) int. The ``step``
    keyword is filled in by ``compile_train_step`` with ``state.step`` so the
    dropout mask changes every training step."""

    def loss_fn(params, batch, step=0):
        rng = jax.random.fold_in(jax.random.PRNGKey(dropout_seed), step)
        logits = model.apply(
            {"params": params}, batch["image"], train=True, rngs={"dropout": rng}
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return loss, {"accuracy": acc}

    return loss_fn


def make_predict_fn(model):
    def predict(params, batch):
        logits = model.apply({"params": params}, batch["image"], train=False)
        return jnp.argmax(logits, -1)

    return predict
