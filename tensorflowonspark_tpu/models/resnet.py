"""ResNets — the performance workload (BASELINE.md north star).

Capability-parity with the reference's example models: ResNet-50 v1.5 for
ImageNet (/root/reference/examples/resnet/resnet_model.py — bottleneck blocks,
stride-2 in the 3x3, BN momentum 0.9 eps 1e-5) and ResNet-56 for CIFAR-10
(/root/reference/examples/resnet/resnet_cifar_model.py — 3 stages of 9 basic
blocks). TPU-first differences: bfloat16 compute (params float32) instead of
the reference's fp16+LossScaleOptimizer dance (resnet_imagenet_main.py:182-187
— bf16 needs no loss scaling), and BatchNorm statistics under pjit are global-
batch statistics (sync-BN for free, where the reference's
MultiWorkerMirroredStrategy used per-replica BN).
"""

import functools

import jax.numpy as jnp
import optax
from flax import linen as nn

from tensorflowonspark_tpu.models import register


def _norm_factory(bn_impl, train, dtype):
    """BatchNorm constructor for ``bn_impl``: ``"flax"`` = ``nn.BatchNorm``
    (global sync-BN under pjit), ``"pallas"`` = the fused-kernel
    :class:`~tensorflowonspark_tpu.ops.fused_bn.FusedBatchNorm` (per-shard
    stats — the r5 BN-slice experiment, docs/perf.md)."""
    if bn_impl == "pallas":
        import jax

        from tensorflowonspark_tpu.ops.fused_bn import FusedBatchNorm

        # same convention as the transformer's flash attention: interpret
        # (CPU emulation) everywhere but real TPU
        cls = functools.partial(
            FusedBatchNorm, interpret=jax.default_backend() != "tpu"
        )
    elif bn_impl == "flax":
        cls = nn.BatchNorm
    else:
        raise ValueError("bn_impl must be 'flax' or 'pallas', got {!r}".format(bn_impl))
    return functools.partial(
        cls, use_running_average=not train, momentum=0.9, epsilon=1e-5, dtype=dtype
    )


class BottleneckBlock(nn.Module):
    """ResNet v1.5 bottleneck: 1x1 → 3x3(stride) → 1x1, projection shortcut."""

    filters: int
    strides: int = 1
    dtype: jnp.dtype = jnp.float32
    bn_impl: str = "flax"

    @nn.compact
    def __call__(self, x, train=False):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = _norm_factory(self.bn_impl, train, self.dtype)
        shortcut = x
        if x.shape[-1] != self.filters * 4 or self.strides != 1:
            shortcut = conv(self.filters * 4, (1, 1), strides=self.strides, name="proj")(x)
            shortcut = norm(name="proj_bn")(shortcut)
        y = nn.relu(norm(name="bn1")(conv(self.filters, (1, 1), name="conv1")(x)))
        y = nn.relu(
            norm(name="bn2")(conv(self.filters, (3, 3), strides=self.strides, name="conv2")(y))
        )
        y = norm(name="bn3", scale_init=nn.initializers.zeros)(
            conv(self.filters * 4, (1, 1), name="conv3")(y)
        )
        return nn.relu(y + shortcut)


class BasicBlock(nn.Module):
    """CIFAR ResNet basic block: 3x3 → 3x3."""

    filters: int
    strides: int = 1
    dtype: jnp.dtype = jnp.float32
    bn_impl: str = "flax"

    @nn.compact
    def __call__(self, x, train=False):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = _norm_factory(self.bn_impl, train, self.dtype)
        shortcut = x
        if x.shape[-1] != self.filters or self.strides != 1:
            shortcut = conv(self.filters, (1, 1), strides=self.strides, name="proj")(x)
            shortcut = norm(name="proj_bn")(shortcut)
        y = nn.relu(norm(name="bn1")(conv(self.filters, (3, 3), strides=self.strides, name="conv1")(x)))
        y = norm(name="bn2", scale_init=nn.initializers.zeros)(
            conv(self.filters, (3, 3), name="conv2")(y)
        )
        return nn.relu(y + shortcut)


class ResNet(nn.Module):
    """Stage-configurable ResNet; ``bottleneck`` picks the block type."""

    stage_sizes: tuple
    filters: tuple
    num_classes: int = 1000
    bottleneck: bool = True
    stem: str = "imagenet"  # 7x7/2 + maxpool, "imagenet_s2d", or "cifar" 3x3
    dtype: jnp.dtype = jnp.float32
    bn_impl: str = "flax"

    @nn.compact
    def __call__(self, x, train=False):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        stem_bn = functools.partial(
            _norm_factory(self.bn_impl, train, self.dtype), name="stem_bn"
        )
        x = x.astype(self.dtype)
        if self.stem == "imagenet":
            x = conv(64, (7, 7), strides=2, padding=[(3, 3), (3, 3)], name="stem")(x)
            x = nn.relu(stem_bn()(x))
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        elif self.stem == "imagenet_s2d":
            # MXU-friendly stem (the MLPerf TPU ResNet space-to-depth trick):
            # a 7x7/2 conv on 3 input channels occupies 3 of the systolic
            # array's 128 input lanes; rearranging 2x2 pixel blocks into
            # channels ([B,H,W,3] -> [B,H/2,W/2,12]) turns it into a dense
            # stride-1 4x4 conv on 12 lanes — same downsampling, ~4x the MXU
            # occupancy, comparable receptive field (8 vs 7). Opt-in: the
            # stem weights are shaped differently from the reference's.
            b, h, w, c = x.shape
            if h % 2 or w % 2:
                raise ValueError(
                    "imagenet_s2d stem needs even spatial dims, got {}x{}".format(h, w)
                )
            x = x.reshape(b, h // 2, 2, w // 2, 2, c)
            x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, h // 2, w // 2, 4 * c)
            x = conv(64, (4, 4), strides=1, padding="SAME", name="stem")(x)
            x = nn.relu(stem_bn()(x))
            x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        elif self.stem == "cifar":
            x = conv(self.filters[0], (3, 3), name="stem")(x)
            x = nn.relu(stem_bn()(x))
        else:
            raise ValueError(
                "unknown stem {!r}; expected 'imagenet', 'imagenet_s2d', or "
                "'cifar'".format(self.stem)
            )
        block_cls = BottleneckBlock if self.bottleneck else BasicBlock
        for stage, (n_blocks, filters) in enumerate(zip(self.stage_sizes, self.filters)):
            for i in range(n_blocks):
                strides = 2 if (i == 0 and stage > 0) else 1
                x = block_cls(
                    filters, strides=strides, dtype=self.dtype,
                    bn_impl=self.bn_impl,
                    name="stage{}_block{}".format(stage, i),
                )(x, train=train)
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=self.dtype, name="head")(x).astype(
            jnp.float32
        )


@register("resnet50")
def resnet50(num_classes=1000, dtype=jnp.float32, stem="imagenet", bn_impl="flax"):
    """ResNet-50 v1.5 (reference resnet_model.py layer spec [3,4,6,3]).
    ``stem="imagenet_s2d"`` opts into the space-to-depth stem (TPU MXU
    occupancy — see ResNet.__call__); ``bn_impl="pallas"`` into the fused
    BatchNorm kernels (per-shard stats — docs/perf.md r5)."""
    return ResNet(
        stage_sizes=(3, 4, 6, 3), filters=(64, 128, 256, 512),
        num_classes=num_classes, bottleneck=True, stem=stem, dtype=dtype,
        bn_impl=bn_impl,
    )


@register("resnet56")
def resnet56(num_classes=10, dtype=jnp.float32):
    """ResNet-56 for CIFAR (reference resnet_cifar_model.py: 3 stages × 9
    basic blocks, filters 16/32/64)."""
    return ResNet(
        stage_sizes=(9, 9, 9), filters=(16, 32, 64),
        num_classes=num_classes, bottleneck=False, stem="cifar", dtype=dtype,
    )


@register("resnet18")
def resnet18(num_classes=1000, dtype=jnp.float32):
    return ResNet(
        stage_sizes=(2, 2, 2, 2), filters=(64, 128, 256, 512),
        num_classes=num_classes, bottleneck=False, stem="imagenet", dtype=dtype,
    )


def make_init_fn(model, image_size=224, channels=3):
    def init(rng):
        return model.init(rng, jnp.zeros((1, image_size, image_size, channels)), train=False)

    return init


def make_loss_fn(model, weight_decay=1e-4, label_smoothing=0.0, normalize=None):
    """Mutable loss for SyncDataParallel(compile_train_step(mutable=True)):
    threads batch_stats and applies the reference's L2 regularization
    (resnet_model.py applies wd to conv/dense kernels).

    ``normalize`` — optional device-side preprocess applied to
    ``batch["image"]`` before the model (e.g.
    :func:`tensorflowonspark_tpu.data.imagenet.device_normalize` when the
    feed ships raw uint8 pixels)."""
    import jax

    def loss_fn(params, model_state, batch):
        images = batch["image"] if normalize is None else normalize(batch["image"])
        logits, new_model_state = model.apply(
            {"params": params, **model_state}, images, train=True,
            mutable=["batch_stats"],
        )
        if label_smoothing > 0:
            num_classes = logits.shape[-1]
            onehot = jax.nn.one_hot(batch["label"], num_classes)
            onehot = onehot * (1 - label_smoothing) + label_smoothing / num_classes
            loss = optax.softmax_cross_entropy(logits, onehot).mean()
        else:
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, batch["label"]
            ).mean()
        if weight_decay:
            l2 = sum(
                jnp.sum(jnp.square(p))
                for path, p in jax.tree_util.tree_flatten_with_path(params)[0]
                if path[-1].key == "kernel"
            )
            loss = loss + weight_decay * 0.5 * l2
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return loss, (new_model_state, {"accuracy": acc})

    return loss_fn


def make_eval_fn(model, normalize=None):
    """``eval_fn(params, model_state, batch) -> (correct, count)`` for the
    reference's per-epoch top-1 eval (resnet_imagenet_main.py ran eval via
    model.evaluate; here it is a jitted metric over the eval input path)."""
    def eval_fn(params, model_state, batch):
        images = batch["image"] if normalize is None else normalize(batch["image"])
        logits = model.apply(
            {"params": params, **model_state}, images, train=False
        )
        correct = jnp.sum(jnp.argmax(logits, -1) == batch["label"])
        return correct, batch["label"].shape[0]

    return eval_fn


def make_predict_fn(model, normalize=None):
    def predict_fn(params, model_state, batch):
        images = batch["image"] if normalize is None else normalize(batch["image"])
        logits = model.apply(
            {"params": params, **model_state}, images, train=False
        )
        return jnp.argmax(logits, -1)

    return predict_fn
