"""Step-timing and run-stats utilities.

Parity with the reference's measurement instrumentation, which lived as
example code (/root/reference/examples/resnet/common.py: ``TimeHistory``
callback :177, ``build_stats`` :202-245 with its ``avg_exp_per_second``
formula :241-244); here it is a framework module any training loop can use.
"""

import logging
import time

from tensorflowonspark_tpu import obs

logger = logging.getLogger(__name__)


class TimeHistory:
    """Record per-log-interval throughput during a training loop.

    The reference's Keras callback counted batches between ``on_batch_end``
    hooks; a jax loop calls :meth:`batch_end` itself (after fencing the
    step's result when honest timing matters — see docs/perf.md on relay
    fencing)::

        th = TimeHistory(batch_size, log_steps=20)
        for batch in batches:
            state, metrics = step(state, batch)
            jax.block_until_ready(metrics["loss"])
            th.batch_end()

    ``timestamps`` holds (first_step_time, last_step_time) per completed
    interval — exactly what ``avg_exp_per_second`` needs.
    """

    def __init__(self, batch_size, log_steps=100):
        self.batch_size = int(batch_size)
        self.log_steps = int(log_steps)
        self.global_steps = 0
        self.timestamps = []  # [(interval_start, interval_end), ...]
        self._interval_start = None
        # publish into the process registry: the jax child's SnapshotPublisher
        # ships these to the driver's TFCluster.metrics() view
        self._steps_c = obs.counter("train_steps_total", help="completed training steps")
        self._rate_g = obs.gauge(
            "train_examples_per_sec", help="throughput over the last completed log interval"
        )

    def batch_end(self):
        now = time.time()
        if self._interval_start is None:
            self._interval_start = now
        self.global_steps += 1
        self._steps_c.inc()
        if self.global_steps % self.log_steps == 0:
            self.timestamps.append((self._interval_start, now))
            # per-interval rate needs >=2 log points within the interval;
            # log_steps=1 rates come from consecutive interval ends instead
            if self.log_steps > 1 and now > self._interval_start:
                rate = self.batch_size * (self.log_steps - 1) / (now - self._interval_start)
                self._rate_g.set(rate)
                logger.info("step %d: %.1f examples/sec", self.global_steps, rate)
            elif self.log_steps == 1 and len(self.timestamps) >= 2:
                prev_end = self.timestamps[-2][1]
                if now > prev_end:
                    rate = self.batch_size / (now - prev_end)
                    self._rate_g.set(rate)
                    logger.info("step %d: %.1f examples/sec", self.global_steps, rate)
            self._interval_start = None

    @property
    def avg_examples_per_second(self):
        """The reference's ``avg_exp_per_second`` (common.py:241-244):
        ``batch_size * log_steps * (N-1) / (t_last - t_first)`` over all
        completed intervals — steady-state throughput excluding the first
        interval's compile/warmup skew."""
        if len(self.timestamps) < 2:
            return 0.0
        first = self.timestamps[0][1]
        last = self.timestamps[-1][1]
        if last <= first:
            return 0.0
        return self.batch_size * self.log_steps * (len(self.timestamps) - 1) / (last - first)


def build_stats(loss, metrics=None, time_history=None, eval_results=None):
    """Assemble the end-of-run stats dict (reference ``build_stats``,
    common.py:202-245): final loss, final training metrics, eval results,
    and ``avg_exp_per_second``/``exp_per_second`` from a TimeHistory."""
    stats = {}
    if loss is not None:
        stats["loss"] = float(loss)
    for name, value in (metrics or {}).items():
        try:
            stats[name] = float(value)
        except (TypeError, ValueError):
            continue
    if eval_results:
        for name, value in eval_results.items():
            try:
                stats["eval_" + name] = float(value)
            except (TypeError, ValueError):
                continue  # non-scalar eval values are skipped like metrics
    if time_history is not None:
        stats["step_timestamp_log"] = list(time_history.timestamps)
        stats["train_finish_time"] = (
            time_history.timestamps[-1][1] if time_history.timestamps else None
        )
        stats["avg_exp_per_second"] = time_history.avg_examples_per_second
    return stats
