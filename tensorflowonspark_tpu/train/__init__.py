"""Training strategies, step builders and checkpointing for the TPU runtime.

This package is the replacement for the reference's reliance on
``tf.distribute.*Strategy`` + TF checkpointing (SURVEY.md §2.6/§5): sync data
parallelism is a pjit program over a ``jax.sharding.Mesh`` with XLA collectives
over ICI, and checkpoint/resume is orbax.
"""
