"""Training strategies, step builders and checkpointing for the TPU runtime.

This package is the replacement for the reference's reliance on
``tf.distribute.*Strategy`` + TF checkpointing (SURVEY.md §2.6/§5): sync data
parallelism is a pjit program over a ``jax.sharding.Mesh`` with XLA collectives
over ICI, and checkpoint/resume is orbax.
"""

# Lazy re-exports (PEP 562): keep `import tensorflowonspark_tpu.train` (and
# `from ... import checkpoint`) jax-free; jax loads only when a strategy or
# checkpoint function is actually touched.
_EXPORTS = {
    "SyncDataParallel": "strategy",
    "BucketedOverlap": "strategy",
    "PackedLoopCache": "strategy",
    "TrainState": "strategy",
    "steps_per_worker": "strategy",
    "run_steps": "strategy",
    "checkpoint": None,
    "strategy": None,
    "export": None,
    "metrics": None,
    "export_model": "export",
    "load_model": "export",
    "TimeHistory": "metrics",
    "build_stats": "metrics",
}


def __getattr__(name):
    import importlib

    if name not in _EXPORTS:
        raise AttributeError(name)
    submodule = _EXPORTS[name] or name
    mod = importlib.import_module("tensorflowonspark_tpu.train." + submodule)
    return mod if _EXPORTS[name] is None else getattr(mod, name)


def __dir__():
    return sorted(_EXPORTS)
