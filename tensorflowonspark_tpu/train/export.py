"""Model export bundles — the SavedModel equivalent.

The reference's pipeline could reload *any* trained model because a TF
SavedModel carries its own graph (/root/reference/tensorflowonspark/
pipeline.py:585-644 introspects signatures at load time). A jax checkpoint
carries only arrays, so the bundle format here is: gathered final weights
plus a cloudpickled **predict-fn builder** — code + weights, restorable on
any host (including CPU-only inference executors) without knowing the
architecture in advance.

Deliberately NOT orbax: training checkpoints (train/checkpoint.py) are
collective and sharded — every process of a multi-host world participates —
but an export bundle is the *serving* artifact, written by the chief alone
from fully-gathered host arrays (the reference's chief-exports-SavedModel
dance, compat.py:10-17). Using the collective path here would deadlock a
chief-only export in a jax.distributed world.

**Trust boundary.** A bundle is a *trusted artifact*: ``predict_builder.pkl``
is cloudpickled CODE, executed on load — exactly as a TF SavedModel executes
its graph, but with Python's full power. Only load bundles you produced or
vetted. For untrusted-storage deployments there is a safe lane: weights are
written as ``weights.npz`` (plain arrays, loaded with ``allow_pickle=False``)
whenever the param tree is nested dicts of arrays, and
``load_model(export_dir, trusted_builder=...)`` takes the predict-fn builder
from YOUR code (a callable or ``"module:attr"`` string) so nothing from the
bundle directory is ever unpickled.
"""

import importlib
import logging
import os

import cloudpickle

from tensorflowonspark_tpu import durable

logger = logging.getLogger(__name__)

_BUILDER_FILE = "predict_builder.pkl"
_WEIGHTS_FILE = "weights.pkl"  # fallback for non-dict-tree states (+ read-compat)
_WEIGHTS_NPZ = "weights.npz"  # safe lane: plain arrays, no pickle on load
_CKPT_DIR = "checkpoint"  # legacy orbax-format bundles (read-compat)
#: npz key separator for flattened tree paths; '/' cannot appear in flax
#: param-dict keys but guard anyway at write time
_SEP = "/"
#: npz key suffix marking an exotic-dtype (ml_dtypes) leaf stored as bytes
_DTYPE_TAG = "::dtype="


def export_model(export_dir, predict_builder, params, model_state=None):
    """Write a self-contained inference bundle.

    ``predict_builder`` is a picklable zero-arg callable returning
    ``predict_fn(params, model_state, batch_arrays) -> outputs`` (a dict of
    named arrays or a single array). It is invoked lazily at load time, so jax
    is only imported in the serving process. ``params``/``model_state`` may be
    jax arrays (gathered to host here) or already-numpy trees.
    """
    import numpy as np

    export_dir = os.path.abspath(os.path.expanduser(export_dir))
    os.makedirs(export_dir, exist_ok=True)
    state = {"params": params, "model_state": model_state or {}}
    try:  # gather device arrays; tolerate pure-numpy trees without jax
        import jax

        state = jax.tree.map(np.asarray, jax.device_get(state))
    except ImportError:
        pass
    # an empty model_state is omitted from the npz (load_model reconstructs
    # absent model_state as {}); an empty params tree has no such default and
    # rides the pickle fallback via _flatten_dict_tree's empty-dict rejection
    npz_tree = {k: v for k, v in state.items() if k != "model_state" or v}
    flat = _flatten_dict_tree(npz_tree)
    if flat is not None:
        tmp = os.path.join(export_dir, _WEIGHTS_NPZ + ".tmp")
        with open(tmp, "wb") as f:
            np.savez(f, **flat)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(export_dir, _WEIGHTS_NPZ))
        durable.fsync_dir(export_dir)
        _remove_stale(export_dir, _WEIGHTS_FILE)
    else:
        logger.warning(
            "state tree is not nested dicts of arrays; falling back to "
            "pickled weights (the npz safe-load lane will be unavailable)"
        )
        tmp = os.path.join(export_dir, _WEIGHTS_FILE + ".tmp")
        with open(tmp, "wb") as f:
            cloudpickle.dump(state, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(export_dir, _WEIGHTS_FILE))
        durable.fsync_dir(export_dir)
        _remove_stale(export_dir, _WEIGHTS_NPZ)
    # a re-export into a legacy orbax-era bundle dir must not leave the old
    # checkpoint behind either: load_model prefers file lanes, but a later
    # deletion of the new weights file would silently revive stale params
    _remove_stale(export_dir, _CKPT_DIR)
    with open(os.path.join(export_dir, _BUILDER_FILE), "wb") as f:
        cloudpickle.dump(predict_builder, f)
    logger.info("exported model bundle to %s", export_dir)
    return export_dir


def _remove_stale(export_dir, name):
    """Drop the OTHER weight lane's leftover so load_model can never pair
    this export's builder with a previous export's params."""
    import shutil

    path = os.path.join(export_dir, name)
    try:
        if os.path.isdir(path):
            shutil.rmtree(path)
        elif os.path.exists(path):
            os.remove(path)
    except OSError as e:
        logger.warning("could not remove stale %s: %s", path, e)


def _flatten_dict_tree(tree):
    """Nested dicts of array-likes → {path: ndarray}, or None when the tree
    has non-dict containers / non-string / separator-bearing keys / object
    leaves (those fall back to the pickle lane)."""
    import numpy as np

    out = {}

    def _walk(prefix, node):
        if isinstance(node, dict):
            if not node:
                # npz cannot represent an empty subtree; a reload would drop
                # it and change the structure — pickle lane instead
                raise ValueError(prefix)
            for k, v in node.items():
                if not isinstance(k, str) or _SEP in k or _DTYPE_TAG in k:
                    raise ValueError(k)
                _walk(prefix + (k,), v)
        elif isinstance(node, (list, tuple)):
            # np.asarray would stack these into one ndarray, silently
            # changing the tree's structure on reload — pickle lane instead
            raise ValueError(prefix)
        else:
            arr = np.asarray(node)
            key = _SEP.join(prefix)
            if arr.dtype.kind in "biufcSUMm":
                out[key] = arr
            else:
                # exotic dtype (ml_dtypes bfloat16/fp8 — the flagship LM
                # exports bf16): np.savez would store these as raw void and
                # reload as unusable V2 arrays, so store the bytes with the
                # dtype name tagged in the key and view them back on load
                name = arr.dtype.name
                try:
                    import ml_dtypes

                    getattr(ml_dtypes, name)
                except (ImportError, AttributeError):
                    raise ValueError(prefix)  # unknown dtype: pickle lane
                raw = np.ascontiguousarray(arr).reshape(arr.shape + (1,)).view(np.uint8)
                out[key + _DTYPE_TAG + name] = raw

    try:
        _walk((), tree)
    except ValueError:
        return None
    return out


def _unflatten_dict_tree(flat):
    root = {}
    for path, arr in flat.items():
        if _DTYPE_TAG in path:
            path, name = path.rsplit(_DTYPE_TAG, 1)
            import ml_dtypes

            v = arr.view(getattr(ml_dtypes, name))  # byte view → (..., 1)
            arr = v.reshape(v.shape[:-1])  # drop the synthetic last axis
        parts = path.split(_SEP)
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root


def resolve_builder(spec):
    """``"module:attr"`` (or dotted ``module.attr``) → the builder callable;
    callables pass through."""
    if callable(spec):
        return spec
    mod, sep, attr = spec.partition(":")
    if not sep:
        mod, _, attr = spec.rpartition(".")
    if not mod or not attr:
        raise ValueError(
            "trusted_builder must be callable or 'module:attr', got {!r}".format(spec)
        )
    return getattr(importlib.import_module(mod), attr)


def load_model(export_dir, trusted_builder=None):
    """Load a bundle: returns ``(predict_fn, params, model_state)``.

    ``trusted_builder`` (callable or ``"module:attr"``) supplies the
    predict-fn builder from the CALLER'S code instead of unpickling
    ``predict_builder.pkl`` — combined with the npz weights lane
    (``allow_pickle=False``) nothing from ``export_dir`` is ever unpickled,
    so a tampered bundle can corrupt predictions but cannot execute code.
    Without it, loading a bundle executes pickled code: treat the bundle as
    a trusted artifact (see module docstring).
    """
    import numpy as np

    export_dir = os.path.abspath(os.path.expanduser(export_dir))
    if trusted_builder is not None:
        predict_builder = resolve_builder(trusted_builder)
    else:
        with open(os.path.join(export_dir, _BUILDER_FILE), "rb") as f:
            predict_builder = cloudpickle.load(f)
    npz = os.path.join(export_dir, _WEIGHTS_NPZ)
    weights = os.path.join(export_dir, _WEIGHTS_FILE)
    if os.path.isfile(npz):
        with np.load(npz, allow_pickle=False) as z:
            state = _unflatten_dict_tree({k: z[k] for k in z.files})
    elif os.path.isfile(weights):
        if trusted_builder is not None:
            raise ValueError(
                "bundle {} has pickled weights ({}) — the trusted_builder "
                "safe-load lane requires npz weights (re-export with a "
                "dict-tree state)".format(export_dir, _WEIGHTS_FILE)
            )
        with open(weights, "rb") as f:
            state = cloudpickle.load(f)
    else:  # legacy orbax-format bundle
        if trusted_builder is not None:
            raise ValueError(
                "bundle {} has no npz weights (legacy checkpoint format) — "
                "the trusted_builder safe-load lane deserializes nothing "
                "from the bundle dir; re-export to get npz weights".format(export_dir)
            )
        from tensorflowonspark_tpu.train import checkpoint

        state = checkpoint.restore_checkpoint(os.path.join(export_dir, _CKPT_DIR))
    return predict_builder(), state["params"], state.get("model_state") or {}


def is_model_bundle(path):
    return os.path.isfile(os.path.join(path, _BUILDER_FILE))
