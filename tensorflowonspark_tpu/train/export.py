"""Model export bundles — the SavedModel equivalent.

The reference's pipeline could reload *any* trained model because a TF
SavedModel carries its own graph (/root/reference/tensorflowonspark/
pipeline.py:585-644 introspects signatures at load time). A jax checkpoint
carries only arrays, so the bundle format here is: an orbax checkpoint for
``{params, model_state}`` plus a cloudpickled **predict-fn builder** — code +
weights, restorable on any host (including CPU-only inference executors)
without knowing the architecture in advance.
"""

import logging
import os

import cloudpickle

logger = logging.getLogger(__name__)

_BUILDER_FILE = "predict_builder.pkl"
_CKPT_DIR = "checkpoint"


def export_model(export_dir, predict_builder, params, model_state=None):
    """Write a self-contained inference bundle.

    ``predict_builder`` is a picklable zero-arg callable returning
    ``predict_fn(params, model_state, batch_arrays) -> outputs`` (a dict of
    named arrays or a single array). It is invoked lazily at load time, so jax
    is only imported in the serving process.
    """
    from tensorflowonspark_tpu.train import checkpoint

    export_dir = os.path.abspath(os.path.expanduser(export_dir))
    os.makedirs(export_dir, exist_ok=True)
    state = {"params": params}
    if model_state is not None:
        state["model_state"] = model_state
    checkpoint.save_checkpoint(os.path.join(export_dir, _CKPT_DIR), state)
    with open(os.path.join(export_dir, _BUILDER_FILE), "wb") as f:
        cloudpickle.dump(predict_builder, f)
    logger.info("exported model bundle to %s", export_dir)
    return export_dir


def load_model(export_dir):
    """Load a bundle: returns ``(predict_fn, params, model_state)``."""
    from tensorflowonspark_tpu.train import checkpoint

    export_dir = os.path.abspath(os.path.expanduser(export_dir))
    with open(os.path.join(export_dir, _BUILDER_FILE), "rb") as f:
        predict_builder = cloudpickle.load(f)
    state = checkpoint.restore_checkpoint(os.path.join(export_dir, _CKPT_DIR))
    return predict_builder(), state["params"], state.get("model_state") or {}


def is_model_bundle(path):
    return os.path.isfile(os.path.join(path, _BUILDER_FILE))
