"""Model export bundles — the SavedModel equivalent.

The reference's pipeline could reload *any* trained model because a TF
SavedModel carries its own graph (/root/reference/tensorflowonspark/
pipeline.py:585-644 introspects signatures at load time). A jax checkpoint
carries only arrays, so the bundle format here is: gathered final weights
plus a cloudpickled **predict-fn builder** — code + weights, restorable on
any host (including CPU-only inference executors) without knowing the
architecture in advance.

Deliberately NOT orbax: training checkpoints (train/checkpoint.py) are
collective and sharded — every process of a multi-host world participates —
but an export bundle is the *serving* artifact, written by the chief alone
from fully-gathered host arrays (the reference's chief-exports-SavedModel
dance, compat.py:10-17). Using the collective path here would deadlock a
chief-only export in a jax.distributed world.
"""

import logging
import os

import cloudpickle

logger = logging.getLogger(__name__)

_BUILDER_FILE = "predict_builder.pkl"
_WEIGHTS_FILE = "weights.pkl"
_CKPT_DIR = "checkpoint"  # legacy orbax-format bundles (read-compat)


def export_model(export_dir, predict_builder, params, model_state=None):
    """Write a self-contained inference bundle.

    ``predict_builder`` is a picklable zero-arg callable returning
    ``predict_fn(params, model_state, batch_arrays) -> outputs`` (a dict of
    named arrays or a single array). It is invoked lazily at load time, so jax
    is only imported in the serving process. ``params``/``model_state`` may be
    jax arrays (gathered to host here) or already-numpy trees.
    """
    import numpy as np

    export_dir = os.path.abspath(os.path.expanduser(export_dir))
    os.makedirs(export_dir, exist_ok=True)
    state = {"params": params, "model_state": model_state or {}}
    try:  # gather device arrays; tolerate pure-numpy trees without jax
        import jax

        state = jax.tree.map(np.asarray, jax.device_get(state))
    except ImportError:
        pass
    tmp = os.path.join(export_dir, _WEIGHTS_FILE + ".tmp")
    with open(tmp, "wb") as f:
        cloudpickle.dump(state, f)
    os.replace(tmp, os.path.join(export_dir, _WEIGHTS_FILE))
    with open(os.path.join(export_dir, _BUILDER_FILE), "wb") as f:
        cloudpickle.dump(predict_builder, f)
    logger.info("exported model bundle to %s", export_dir)
    return export_dir


def load_model(export_dir):
    """Load a bundle: returns ``(predict_fn, params, model_state)``."""
    export_dir = os.path.abspath(os.path.expanduser(export_dir))
    with open(os.path.join(export_dir, _BUILDER_FILE), "rb") as f:
        predict_builder = cloudpickle.load(f)
    weights = os.path.join(export_dir, _WEIGHTS_FILE)
    if os.path.isfile(weights):
        with open(weights, "rb") as f:
            state = cloudpickle.load(f)
    else:  # legacy orbax-format bundle
        from tensorflowonspark_tpu.train import checkpoint

        state = checkpoint.restore_checkpoint(os.path.join(export_dir, _CKPT_DIR))
    return predict_builder(), state["params"], state.get("model_state") or {}


def is_model_bundle(path):
    return os.path.isfile(os.path.join(path, _BUILDER_FILE))
