"""Training strategies: the MultiWorkerMirroredStrategy / NCCL replacement.

The reference delegated distributed training to TF strategies chosen by user
code (`MultiWorkerMirroredStrategy` in every TF2 example, e.g.
/root/reference/examples/mnist/keras/mnist_spark.py:11;
`ParameterServerStrategy` for async, mnist_spark_streaming.py:84-89). Here the
strategy is a thin object that owns a mesh and compiles the user's loss into a
sharded train step: batches shard over the data axes, params replicate (pure
DP) or shard along ``fsdp`` (ZeRO-3), and XLA derives the gradient all-reduce /
reduce-scatter over ICI from the shardings — there is no collective to call by
hand and no PS; sync DP over ICI serves both of the reference's modes
(SURVEY.md §2.6).
"""

import logging
import threading
import time

import jax
import jax.numpy as jnp

from tensorflowonspark_tpu.parallel import (
    batch_sharding,
    build_mesh,
    fsdp_param_specs,
    overlay_fsdp_specs,
    replicated,
    shard_batch,
)

logger = logging.getLogger(__name__)


class TrainState:
    """Minimal train-state pytree: step / params / opt_state / model_state.

    ``model_state`` carries non-trained variable collections (e.g. BatchNorm
    ``batch_stats`` — note that under pjit the batch-mean/var are computed over
    the *global* sharded batch, so cross-replica "sync BN" is automatic, unlike
    the reference's per-replica BN under MultiWorkerMirroredStrategy).

    Registered as a pytree so it flows through jit/grad; deliberately not
    carrying apply_fn/tx (functions don't belong in a sharded, checkpointable
    pytree — orbax saves exactly this tuple).
    """

    def __init__(self, step, params, opt_state, model_state=None):
        self.step = step
        self.params = params
        self.opt_state = opt_state
        self.model_state = {} if model_state is None else model_state

    def replace(self, **kw):
        return TrainState(
            kw.get("step", self.step),
            kw.get("params", self.params),
            kw.get("opt_state", self.opt_state),
            kw.get("model_state", self.model_state),
        )

    def tree_flatten(self):
        return (self.step, self.params, self.opt_state, self.model_state), None

    @classmethod
    def tree_unflatten(cls, _aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten
)


class SyncDataParallel:
    """Synchronous data parallelism (optionally fully-sharded) over a mesh.

    ``fsdp=False``: params/opt-state replicated, batch sharded over ``dp`` —
    the exact capability of the reference's collective all-reduce path.
    ``fsdp=True``: params/opt-state sharded along the ``fsdp`` axis (ZeRO-3),
    which the reference could not express at all.

    Usage inside ``main_fun(args, ctx)``::

        strategy = SyncDataParallel(ctx.mesh({"dp": -1}))
        state = strategy.create_state(model_init, optimizer, rng, sample_batch)
        step = strategy.compile_train_step(loss_fn, optimizer)
        for batch in batches:
            state, metrics = step(state, strategy.shard_batch(batch))
    """

    def __init__(self, mesh=None, fsdp=False, min_weight_size=2**14, param_spec_fn=None, tp=False):
        """``param_spec_fn(params_shape, mesh) -> PartitionSpec pytree`` lets a
        model supply its own placement rules (e.g.
        :func:`tensorflowonspark_tpu.models.transformer.param_specs` for
        tensor parallelism); default placement is replicate (pure DP) or the
        generic FSDP rules.

        ``tp`` turns on tensor parallelism over the mesh's ``tp`` axis:
        pass the model's placement rules directly (``tp=transformer.param_specs``)
        or ``tp=True`` alongside an explicit ``param_spec_fn``. Only the model
        knows which dims are column- vs row-parallel, so ``tp`` without rules
        is an error, as is a mesh without a ``tp`` axis. ``fsdp`` composes:
        the model's tp specs win where they touch, the ZeRO-3 overlay shards
        the leftovers (dp×tp and dp×fsdp×tp both come from the same rules)."""
        self.mesh = mesh if mesh is not None else build_mesh()
        self.fsdp = fsdp
        self.min_weight_size = min_weight_size
        if callable(tp):
            if param_spec_fn is not None and param_spec_fn is not tp:
                raise ValueError(
                    "pass the placement rules once: tp=<spec_fn> or "
                    "param_spec_fn=<spec_fn>, not two different functions"
                )
            param_spec_fn, tp = tp, True
        self.tp = bool(tp)
        self.param_spec_fn = param_spec_fn
        if fsdp and "fsdp" not in self.mesh.axis_names:
            raise ValueError(
                "fsdp=True requires a mesh with an 'fsdp' axis; got {}".format(
                    self.mesh.axis_names
                )
            )
        if self.tp:
            if "tp" not in self.mesh.axis_names:
                raise ValueError(
                    "tp=... requires a mesh with a 'tp' axis; got {}".format(
                        self.mesh.axis_names
                    )
                )
            if self.param_spec_fn is None:
                raise ValueError(
                    "tp=True needs the model's placement rules: pass "
                    "tp=<param_spec_fn> (e.g. models.transformer.param_specs) "
                    "or param_spec_fn= explicitly"
                )

    # -- placement ------------------------------------------------------------

    def param_shardings(self, params_shape):
        """NamedShardings for a params pytree (from shapes or real arrays).

        ``param_spec_fn`` and ``fsdp`` compose: the model's own placement
        rules run first, then the generic ZeRO-3 overlay shards any array the
        model left untouched along ``fsdp`` (params are then reduce-scattered
        / all-gathered per step by XLA from the shardings alone). The
        ``fsdp_params_sharded`` gauge reports how many param arrays actually
        ended up sharded, so a mis-sized ``min_weight_size`` (everything
        replicated) is visible in ``TFCluster.metrics()``.
        """
        from jax.sharding import NamedSharding, PartitionSpec

        if self.param_spec_fn is not None:
            specs = self.param_spec_fn(params_shape, self.mesh)
            if self.fsdp:
                specs = overlay_fsdp_specs(
                    params_shape, specs, self.mesh,
                    min_weight_size=self.min_weight_size,
                )
        elif self.fsdp:
            specs = fsdp_param_specs(
                params_shape, self.mesh, min_weight_size=self.min_weight_size
            )
        else:
            rep = PartitionSpec()
            specs = jax.tree.map(lambda _: rep, params_shape)
        if self.fsdp or self.tp:
            from tensorflowonspark_tpu import obs
            from tensorflowonspark_tpu.parallel.sharding import _spec_axes

            spec_leaves = [
                s
                for s in jax.tree.leaves(
                    specs, is_leaf=lambda n: isinstance(n, PartitionSpec)
                )
                if isinstance(s, PartitionSpec)
            ]
            if self.fsdp:
                obs.gauge(
                    "fsdp_params_sharded",
                    help="param arrays sharded along the fsdp axis (ZeRO-3)",
                ).set(sum(1 for s in spec_leaves if "fsdp" in _spec_axes(s)))
            if self.tp:
                obs.gauge(
                    "tp_params_sharded",
                    help="param arrays sharded along the tp axis (tensor parallelism)",
                ).set(sum(1 for s in spec_leaves if "tp" in _spec_axes(s)))
        return jax.tree.map(lambda s: NamedSharding(self.mesh, s), specs)

    def shard_batch(self, batch):
        return shard_batch(batch, self.mesh)

    # -- state ----------------------------------------------------------------

    @staticmethod
    def _split_variables(variables):
        """flax ``init`` returns {'params': ..., 'batch_stats': ..., ...};
        split into (params, model_state). A bare pytree is all params."""
        if isinstance(variables, dict) and "params" in variables:
            params = variables["params"]
            model_state = {k: v for k, v in variables.items() if k != "params"}
            return params, model_state
        return variables, {}

    def create_state(self, init_fn, optimizer, *init_args):
        """Build a sharded TrainState without ever materializing an unsharded
        copy: params/opt-state are initialized *inside* jit with the target
        shardings as out_shardings, so each device only ever allocates its
        shard (critical for FSDP models larger than one host's memory).

        ``init_fn(*init_args)`` returns either a bare params pytree or a flax
        variables dict (``{'params': ..., 'batch_stats': ...}``).
        """

        def _init():
            params, model_state = self._split_variables(init_fn(*init_args))
            return TrainState(
                jnp.zeros((), jnp.int32), params, optimizer.init(params), model_state
            )

        state_shape = jax.eval_shape(_init)
        shardings = TrainState(
            replicated(self.mesh),
            self.param_shardings(state_shape.params),
            self._opt_shardings(state_shape),
            jax.tree.map(lambda _: replicated(self.mesh), state_shape.model_state),
        )
        return jax.jit(_init, out_shardings=shardings)()

    def _opt_shardings(self, state_shape):
        """Opt-state shardings, matched *structurally*: optax states embed
        whole param-shaped subtrees (Adam's mu/nu, momentum's trace), so any
        opt-state subtree whose treedef and leaf shapes mirror the params gets
        the params' sharding tree; everything else (counts, scalars)
        replicates. A by-shape lookup would misplace moments when two
        same-shaped params carry different PartitionSpecs; still, leaves in
        subtrees that do NOT fully mirror the params (e.g. optax.masked
        moments with MaskedNode sentinels) fall back to a per-leaf
        shape-match so large moment arrays keep their sharding instead of
        blowing up replicated."""
        param_shardings = self.param_shardings(state_shape.params)
        params_def = jax.tree.structure(state_shape.params)
        param_leaves = jax.tree.leaves(state_shape.params)
        rep = replicated(self.mesh)
        by_shape = {}
        for p_leaf, s in zip(param_leaves, jax.tree.leaves(param_shardings)):
            by_shape.setdefault((p_leaf.shape, p_leaf.dtype), s)

        def _is_param_like(sub):
            if jax.tree.structure(sub) != params_def:
                return False
            leaves = jax.tree.leaves(sub)
            return all(
                getattr(a, "shape", None) == b.shape
                and getattr(a, "dtype", None) == b.dtype
                for a, b in zip(leaves, param_leaves)
            )

        def _assign(sub):
            if _is_param_like(sub):
                return param_shardings
            return by_shape.get(
                (getattr(sub, "shape", None), getattr(sub, "dtype", None)), rep
            )

        return jax.tree.map(_assign, state_shape.opt_state, is_leaf=_is_param_like)

    # -- compiled steps --------------------------------------------------------

    def compile_train_step(self, loss_fn, optimizer, has_aux=False, mutable=False, donate=True):
        """Compile a loss into a sharded ``step(state, batch) -> (state, metrics)``.

        * ``mutable=False``: ``loss_fn(params, batch) -> loss`` or
          ``(loss, aux_metrics)`` with ``has_aux=True``.
        * ``mutable=True`` (models with batch_stats etc.):
          ``loss_fn(params, model_state, batch) -> (loss, (new_model_state,
          aux_metrics))`` — ``has_aux`` is implied.

        The gradient all-reduce (pure DP) or reduce-scatter+all-gather (FSDP)
        is inserted by XLA from the shardings — the moral equivalent of the
        reference's `all_reduce_alg`/NCCL configuration, with zero user code.

        A ``loss_fn`` that declares a ``step`` keyword receives the current
        ``state.step`` — the supported way to vary per-step randomness
        (dropout rngs) without smuggling counters through the batch.
        """
        import inspect

        import optax

        try:
            wants_step = "step" in inspect.signature(loss_fn).parameters
        except (TypeError, ValueError):
            wants_step = False

        def train_step(state, batch):
            kw = {"step": state.step} if wants_step else {}
            if mutable:
                (loss, (model_state, aux)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(state.params, state.model_state, batch, **kw)
            else:
                out = jax.value_and_grad(loss_fn, has_aux=has_aux)(state.params, batch, **kw)
                (loss, aux), grads = out if has_aux else ((out[0], None), out[1])
                model_state = state.model_state
            updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
            new_state = TrainState(state.step + 1, params, opt_state, model_state)
            metrics = {"loss": loss, "step": new_state.step}
            if aux:
                metrics.update(aux)
            return new_state, metrics

        return jax.jit(train_step, donate_argnums=(0,) if donate else ())

    def compile_train_loop(self, loss_fn, optimizer, num_steps, has_aux=False, mutable=False, donate=True, packed=False):
        """Compile ``loop(state, batches) -> (state, last_metrics)`` running
        ``num_steps`` train steps INSIDE one XLA program via ``lax.scan``.

        ``batches`` is a list/tuple of ``num_steps`` per-step batch pytrees,
        each already device-resident via :meth:`shard_batch` — place them as
        they arrive from the feed so the host→device transfers run
        asynchronously, overlapping the previous loop's compute (see
        :func:`tensorflowonspark_tpu.data.loop_prefetch`). The stack into the
        scan's ``[K, batch, ...]`` carry happens ON DEVICE (an HBM-to-HBM
        copy XLA aliases away under donation), never on the host: a host-side
        ``np.stack`` + one bulk transfer sits on the critical path and loses
        to per-step dispatch, which is why this API takes device arrays.

        One device dispatch per ``num_steps`` steps: on remote/tunneled TPU
        runtimes the per-dispatch host round trip is milliseconds — at small
        step times it dominates, and scanning it away is the difference
        between host-bound and MXU-bound training (no reference analogue: TF
        sessions had the same per-step host loop this removes).

        With ``donate=True`` (default) only the state is donated —
        ``donate=True`` and ``donate="state"`` are the same contract in
        both modes. Batch stacks must not be offered for donation: the
        input stack aliases no output (a uint8/f32 image stack cannot
        alias the param leaves), so donating it only produced XLA's
        "Some donated buffers were not usable: uint8[...]" warning and a
        silent copy — BENCH_r05 chased that warning through the bench
        tail; packed mode was fixed then, and the non-packed loop (the
        examples' real-data path) had kept the batches donation until
        now. The prefetch generators also keep window buffers referenced
        for double-buffering, which donation would invalidate. Pass
        ``donate="batches"`` to force donating the batch list anyway
        (callers that truly consume their device batches and want the
        HBM back a window early).

        ``packed=True`` flips the input contract: ``loop(state, stacked)``
        takes ONE device-resident pytree whose leaves carry a leading
        ``num_steps`` axis (place with
        :func:`tensorflowonspark_tpu.data.packed_prefetch`). For hosts behind
        a high-latency device link, shipping the whole window as one transfer
        amortizes the per-transfer fixed cost K× — measured on this
        environment's relayed TPU the fixed cost is ~250 ms/transfer, which
        dwarfs per-batch pipelining (docs/perf.md).
        """
        step = self.compile_train_step(
            loss_fn, optimizer, has_aux=has_aux, mutable=mutable, donate=False
        )

        def loop(state, batches):
            if packed:
                lead = {leaf.shape[0] for leaf in jax.tree.leaves(batches)}
                if lead != {num_steps}:
                    raise ValueError(
                        "packed window has leading dims {}, loop compiled for {}".format(
                            sorted(lead), num_steps
                        )
                    )
                stacked = batches
            elif len(batches) != num_steps:
                raise ValueError(
                    "got {} batches, loop compiled for {}".format(
                        len(batches), num_steps
                    )
                )
            else:
                stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *batches)

            def body(carry, batch):
                new_state, metrics = step(carry, batch)
                return new_state, metrics

            state, metrics = jax.lax.scan(body, state, stacked)
            # metrics of the LAST step (scan stacks them; take index -1)
            return state, jax.tree.map(lambda m: m[-1], metrics)

        if donate is True:
            donate = "state"
        donate_argnums = {
            "batches": (0, 1), "state": (0,), False: (),
        }[donate]
        return jax.jit(loop, donate_argnums=donate_argnums)

    def compile_eval_step(self, metric_fn):
        """Compile ``metric_fn(params, batch) -> metrics`` for sharded eval."""
        return jax.jit(metric_fn)

    def compile_predict_step(self, apply_fn):
        """Compile ``apply_fn(params, batch) -> predictions``; outputs gather
        to fully-addressable arrays for host-side result queues."""
        return jax.jit(apply_fn, out_shardings=replicated(self.mesh))


class BucketedOverlap:
    """Bucketed gradient sync overlapping collectives with backprop.

    The serial path (:meth:`SyncDataParallel.compile_train_step`) lets XLA
    insert the gradient all-reduce inside the step program, which the CPU
    PJRT client executes strictly in order — a straggler peer stalls the
    whole stream (measured; see :mod:`tensorflowonspark_tpu.parallel.hostreduce`).
    This scheduler splits the step into microbatches and moves gradient
    synchronization onto a dedicated comm thread: as each microbatch's
    backprop program is dispatched, the comm thread fetches its gradients
    (waiting on the device stream *beside* the next microbatch's compute),
    partitions them into byte-bounded buckets, and runs one deterministic
    host all-reduce per bucket through a
    :class:`~tensorflowonspark_tpu.parallel.hostreduce.HostAllReduceGroup`.
    The optimizer applies the accumulated mean once per step.

    ``overlap=False`` runs the *identical* dispatch sequence but joins the
    comm thread after every microbatch — the same programs, fetches, sums
    and reductions in the same order, differing only in host-side fencing,
    so loss trajectories are bit-identical with overlap on or off (the
    packed-window double-buffer fencing discipline, applied to grads).

    Compiled-program budget mirrors :class:`PackedLoopCache`: one grad
    program per microbatch shape and one apply program total, cached
    forever; the per-bucket work is host numpy and never recompiles.

    Donation contract: the grad program donates **nothing** — its outputs
    are referenced by the comm thread until each bucket is fetched, and its
    ``params`` input is shared by every microbatch. Only the apply program
    donates (params, opt_state), which no in-flight collective can
    reference because :meth:`step` drains the comm thread first.

    Scope: data parallelism over params that are replicated across the
    *processes* of the host group — pure dp (each process steps its own
    replica, like the reference's ``MultiWorkerMirroredStrategy``) and dp×tp
    (params sharded along an in-process ``tp`` mesh axis: the grad fetch
    gathers each leaf to host, the dp all-reduce averages the full arrays,
    and the apply program re-shards through pinned output shardings).
    FSDP params are NOT supported — their leaves are partitions of the
    per-process replica, so a host-side dp all-reduce of gathered shards
    would double-count the reduce-scatter XLA already derives from the
    shardings; the constructor rejects that composition by axis name.

    Per-step stats land in :attr:`last_stats` and the
    ``comm_overlap_fraction`` gauge::

        group = HostAllReduceGroup(rank, world)
        sched = BucketedOverlap(strategy, loss_fn, optimizer, group=group)
        state, metrics = sched.step(state, microbatches)
    """

    def __init__(self, strategy, loss_fn, optimizer, group=None,
                 bucket_bytes=1 << 22, overlap=True, has_aux=False):
        import queue

        if getattr(strategy, "fsdp", False):
            raise ValueError(
                "BucketedOverlap cannot sync params sharded along mesh "
                "axes ('fsdp',): each process holds only a partition of "
                "its replica, and FSDP params already sync through XLA's "
                "sharding-derived reduce-scatter/all-gather. Supported "
                "compositions: replicated params (pure dp) and tp-sharded "
                "params (dp x tp) — only the replicated dp axis is "
                "all-reduced host-side."
            )
        self.strategy = strategy
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.group = group
        self.bucket_bytes = int(bucket_bytes)
        self.overlap = overlap
        self.has_aux = has_aux
        self.last_stats = {}
        self._grad_fns = {}
        self._apply_fn = None
        self._buckets = None  # list of (dtype, [leaf indices]) once shapes known
        self._treedef = None
        # bounded: a stalled all-reduce worker should backpressure the
        # dispatch loop, not let gradient buckets pile up unboundedly
        self._jobs = queue.Queue(maxsize=32)
        self._worker = None
        self._worker_err = None

    # -- compiled programs -----------------------------------------------------

    def _grad_fn(self, batch):
        key = tuple(
            (getattr(x, "shape", ()), str(getattr(x, "dtype", "")))
            for x in jax.tree.leaves(batch)
        )
        fn = self._grad_fns.get(key)
        if fn is None:
            # donate nothing: params feed every microbatch, grads are read by
            # the comm thread after dispatch (donation-safety rule fixture:
            # tests/test_tosa_dataflow.py::TestDonationSafety)
            fn = jax.jit(
                jax.value_and_grad(self.loss_fn, has_aux=self.has_aux),
                donate_argnums=(),
            )
            self._grad_fns[key] = fn
        return fn

    def _apply(self, params, opt_state, step):
        if self._apply_fn is None:
            import optax

            def apply(params, opt_state, step, grads, scale):
                grads = jax.tree.map(lambda g: g * scale, grads)
                updates, opt_state = self.optimizer.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return params, opt_state, step + 1

            # pin output shardings to the inputs': the accumulated grads
            # arrive as host arrays, and without the pin a tp-sharded params
            # tree would come back with whatever placement jit infers from
            # the unsharded operands — the next microbatch's grad program
            # would then recompile against moved params
            kw = {}
            try:
                kw["out_shardings"] = (
                    jax.tree.map(lambda x: x.sharding, params),
                    jax.tree.map(lambda x: x.sharding, opt_state),
                    step.sharding,
                )
            except AttributeError:
                pass  # host-numpy state (unit tests): let jit place outputs
            self._apply_fn = jax.jit(apply, donate_argnums=(0, 1), **kw)
        return self._apply_fn

    # -- bucket partition ------------------------------------------------------

    def _partition(self, grad_leaves):
        """Partition flat grad-leaf indices into byte-bounded buckets, one
        dtype per bucket (payloads concatenate raw)."""
        buckets = []
        cur, cur_bytes, cur_dtype = [], 0, None
        order = sorted(
            range(len(grad_leaves)), key=lambda i: str(grad_leaves[i].dtype)
        )
        for i in order:
            leaf = grad_leaves[i]
            dt = str(leaf.dtype)
            if cur and (dt != cur_dtype or cur_bytes + leaf.nbytes > self.bucket_bytes):
                buckets.append((cur_dtype, cur))
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += leaf.nbytes
            cur_dtype = dt
        if cur:
            buckets.append((cur_dtype, cur))
        return buckets

    # -- comm thread -----------------------------------------------------------

    def _comm_loop(self):
        import numpy as np

        while True:
            job = self._jobs.get()
            if job is None:
                return
            grad_leaves, acc, done, stats, record = job
            try:
                for _dtype, idxs in self._buckets:
                    leaves = [grad_leaves[i] for i in idxs]
                    t0 = time.perf_counter()
                    jax.block_until_ready(leaves)  # device stream, not comm
                    t1 = time.perf_counter()
                    stats["device_wait_s"] += t1 - t0
                    record["dw_end"] = t1
                    flat = np.concatenate([np.asarray(x).ravel() for x in leaves])
                    if self.group is not None:
                        flat = self.group.allreduce_mean(flat)
                    off = 0
                    for i in idxs:
                        n = int(np.prod(grad_leaves[i].shape, dtype=np.int64))
                        part = flat[off:off + n].reshape(grad_leaves[i].shape)
                        acc[i] = part if acc[i] is None else acc[i] + part
                        off += n
                    t2 = time.perf_counter()
                    record["comm_spans"].append((t1, t2))
                    stats["comm_busy_s"] += t2 - t1
            except BaseException as e:  # surfaces at the next drain
                self._worker_err = e
            finally:
                done.set()

    def _ensure_worker(self):
        if self._worker is None or not self._worker.is_alive():
            self._worker = threading.Thread(
                target=self._comm_loop, name="grad-comm", daemon=True
            )
            self._worker.start()

    def _check_err(self):
        if self._worker_err is not None:
            err, self._worker_err = self._worker_err, None
            raise RuntimeError("gradient comm thread failed") from err

    # -- the step --------------------------------------------------------------

    def step(self, state, microbatches):
        """One optimizer step over ``microbatches`` (a non-empty list of
        batch pytrees, each already device-resident via
        ``strategy.shard_batch``). Returns ``(state, metrics)`` with the
        loss averaged over microbatches and ranks."""
        import numpy as np

        if not microbatches:
            raise ValueError("step needs at least one microbatch")
        self._ensure_worker()
        self._check_err()
        stats = {"comm_busy_s": 0.0, "device_wait_s": 0.0, "blocked_s": 0.0}
        losses, dones, records = [], [], []
        acc = None
        t_step0 = time.perf_counter()
        for batch in microbatches:
            dispatch_ts = time.perf_counter()
            out = self._grad_fn(batch)(state.params, batch)
            (loss, _aux), grads = out if self.has_aux else ((out[0], None), out[1])
            grad_leaves, treedef = jax.tree.flatten(grads)
            if self._buckets is None:
                self._buckets = self._partition(grad_leaves)
                self._treedef = treedef
                logger.info(
                    "bucketed overlap: %d grad arrays -> %d bucket(s) <= %d bytes",
                    len(grad_leaves), len(self._buckets), self.bucket_bytes,
                )
            if acc is None:
                acc = [None] * len(grad_leaves)
            losses.append(loss)
            done = threading.Event()
            dones.append(done)
            record = {"dispatch_ts": dispatch_ts, "comm_spans": [], "dw_end": 0.0}
            records.append(record)
            self._jobs.put((grad_leaves, acc, done, stats, record))
            if not self.overlap:
                t0 = time.perf_counter()
                done.wait()
                stats["blocked_s"] += time.perf_counter() - t0
        t0 = time.perf_counter()
        for done in dones:
            done.wait()
        stats["blocked_s"] += time.perf_counter() - t0
        self._check_err()

        grads = jax.tree.unflatten(self._treedef, acc)
        scale = jnp.asarray(1.0 / len(microbatches), dtype=jnp.float32)
        params, opt_state, step = self._apply(
            state.params, state.opt_state, state.step
        )(state.params, state.opt_state, state.step, grads, scale)
        new_state = TrainState(step, params, opt_state, state.model_state)
        loss = jnp.mean(jnp.stack(losses))
        if self.group is not None and self.group.world > 1:
            loss_mean = self.group.allreduce_mean(
                np.asarray(loss, dtype=np.float32).reshape(1)
            )[0]
        else:
            loss_mean = loss
        stats["step_s"] = time.perf_counter() - t_step0
        # measured overlap: comm seconds that ran while backprop work from a
        # later microbatch was resident on the device stream. Job i's comm is
        # hidden where its spans fall inside [dispatch of job i+1, last
        # grad-ready time]; before that window nothing later is enqueued,
        # after it the device is idle. overlap=False joins the comm thread
        # before dispatching the next microbatch, so the window is empty and
        # the fraction is exactly 0 — same programs, same order, only fencing.
        window_end = max((r["dw_end"] for r in records), default=0.0)
        hidden = 0.0
        for i, rec in enumerate(records):
            if i + 1 >= len(records):
                break  # last job's comm has nothing behind it to hide under
            window_start = records[i + 1]["dispatch_ts"]
            for s, e in rec["comm_spans"]:
                hidden += max(0.0, min(e, window_end) - max(s, window_start))
        stats["hidden_comm_s"] = hidden
        stats["overlap_fraction"] = (
            min(1.0, hidden / stats["comm_busy_s"])
            if stats["comm_busy_s"] > 0
            else 0.0
        )
        self.last_stats = stats
        from tensorflowonspark_tpu import obs
        from tensorflowonspark_tpu.obs import tracing as obs_tracing

        obs.gauge(
            "comm_overlap_fraction",
            help="fraction of host all-reduce time hidden behind device backprop",
        ).set(stats["overlap_fraction"])
        if obs_tracing.active():
            # publish the comm thread's measured intervals as retroactive
            # spans on the dedicated comm track: perf_counter -> wall via a
            # single anchor, comm_window marking where later backprop could
            # hide each bucket — tracemerge recomputes the overlap fraction
            # from exactly these drawn spans to corroborate the gauge
            anchor = time.time() - time.perf_counter()
            for i, rec in enumerate(records):
                for s, e in rec["comm_spans"]:
                    obs_tracing.record_span(
                        "comm_allreduce", ts=anchor + s, dur_s=e - s,
                        track="comm", microbatch=i,
                    )
                if i + 1 < len(records) and window_end > records[i + 1]["dispatch_ts"]:
                    win0 = records[i + 1]["dispatch_ts"]
                    obs_tracing.record_span(
                        "comm_window", ts=anchor + win0, dur_s=window_end - win0,
                        track="comm_window", microbatch=i,
                    )
        metrics = {"loss": loss_mean, "step": new_state.step}
        return new_state, metrics

    def close(self):
        """Stop the comm thread (the group is the caller's to close)."""
        if self._worker is not None and self._worker.is_alive():
            self._jobs.put(None)
            self._worker.join(timeout=10)
        self._worker = None


class PackedLoopCache:
    """Per-K cache of packed train loops for the adaptive feed.

    The :class:`~tensorflowonspark_tpu.data.autotune.FeedAutotuner` varies
    the packed-window size K at runtime, but
    :meth:`SyncDataParallel.compile_train_loop` compiles for a static
    ``num_steps`` — so each bucket gets its own compiled program, built on
    first use and reused forever after. With the bounded bucket set
    (powers of two) that is at most one XLA compile per bucket for the
    whole run; every compile increments the ``feed_recompiles_total``
    counter so the trade shows up in ``TFCluster.metrics()``.

    Loops are compiled with the packed donation contract (``donate="state"``
    — the window buffers stay owned by the prefetch double buffer; see
    :meth:`SyncDataParallel.compile_train_loop`)::

        cache = PackedLoopCache(strategy, loss_fn, optimizer, mutable=True)
        for window in autotuned_prefetch(pipe, strategy, tuner=tuner):
            state, metrics = cache.run(state, window)
    """

    def __init__(self, strategy, loss_fn, optimizer, has_aux=False, mutable=False):
        self.strategy = strategy
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.has_aux = has_aux
        self.mutable = mutable
        self._loops = {}

    def loop_for(self, num_steps):
        """The compiled packed loop for window size ``num_steps``."""
        compiled = self._loops.get(num_steps)
        if compiled is None:
            from tensorflowonspark_tpu import obs

            obs.counter(
                "feed_recompiles_total",
                help="packed train-loop compilations (bounded by the bucket set)",
            ).inc()
            logger.info("compiling packed train loop for window K=%d", num_steps)
            compiled = self.strategy.compile_train_loop(
                self.loss_fn, self.optimizer, num_steps,
                has_aux=self.has_aux, mutable=self.mutable,
                donate="state", packed=True,
            )
            self._loops[num_steps] = compiled
        return compiled

    def run(self, state, window):
        """Run one :class:`~tensorflowonspark_tpu.data.autotune.AutotunedWindow`
        (or any object with ``.data``/``.k``) through its bucket's loop."""
        return self.loop_for(window.k)(state, window.data)

    @property
    def compiled_sizes(self):
        """The buckets compiled so far (sorted)."""
        return sorted(self._loops)


def run_steps(step_fn, state, batches, engine=None, save_every_n=None, hooks=()):
    """Drive a compiled step over ``batches`` with per-step hooks and
    non-blocking checkpointing. Returns ``(state, last_metrics)``.

    The loop hook for the async checkpoint engine
    (:class:`tensorflowonspark_tpu.ckpt.AsyncCheckpointEngine`): every
    ``save_every_n`` steps (default: the engine's own cadence) the state is
    snapshotted to host — the only checkpoint cost the training thread ever
    pays — and committed in the background; on exit (including an exception
    unwinding through the loop) the engine is **drained** so the final
    snapshot lands before the caller tears anything down.

    Donation-safe by ordering: ``step_fn`` may donate its state argument —
    the snapshot copies the *returned* state to host buffers the engine
    owns before the next iteration donates those device arrays back into
    ``step_fn``, so the background writer never aliases live device memory.

    ``hooks`` are callables ``hook(state, global_step, metrics)`` run after
    every step (eval triggers, LR logging). The global step is tracked
    host-side from one initial ``state.step`` readback — per-step device
    syncs would serialize the dispatch pipeline this loop exists to keep
    full.
    """
    import jax

    if isinstance(state, dict):  # bare-pytree states carry step as a key
        start = state.get("step", 0)
    else:
        start = getattr(state, "step", 0)
    start_step = int(jax.device_get(start))
    cadence = save_every_n if save_every_n is not None else (
        engine.save_every_n if engine is not None else 0
    )
    from tensorflowonspark_tpu import obs

    # per-step phase spans (fetch / compute / snapshot): each lands in the
    # flight shard for the merged step timeline AND in the {phase}_seconds
    # histogram the exporter's /histograms.json summarizes as p50/p99.
    # obs.span hands out a shared no-op span when collection is disabled.
    metrics = None
    it = iter(batches)
    i = 0
    try:
        while True:
            with obs.span("step_fetch", step=start_step + i + 1):
                try:
                    batch = next(it)
                except StopIteration:
                    break
            with obs.span("step_compute", step=start_step + i + 1):
                state, metrics = step_fn(state, batch)
            global_step = start_step + i + 1
            for hook in hooks:
                hook(state, global_step, metrics)
            if engine is not None and cadence and global_step % cadence == 0:
                with obs.span("ckpt_snapshot", step=global_step):
                    engine.save(state, global_step)
            i += 1
    finally:
        if engine is not None:
            engine.drain()
    return state, metrics


def steps_per_worker(total_examples, batch_size, num_workers, safety=0.9):
    """Per-worker step budget for InputMode.SPARK feeding.

    Spark partitions are uneven, so a worker that demands exactly
    ``total/batch/workers`` steps can starve at the epoch tail and hang the
    collective. The reference buried this as example folklore — "limit
    steps to ~90% of expected to account for uneven partitions"
    (/root/reference/examples/mnist/keras/mnist_spark.py:58-64); here it is
    the documented helper.
    """
    per_worker = total_examples // (batch_size * max(num_workers, 1))
    return max(1, int(per_worker * safety))
