"""Checkpoint / export helpers (orbax-backed).

Capability-parity with the reference's checkpoint story, which was fully
delegated to TensorFlow (SURVEY.md §5 "Checkpoint / resume";
/root/reference/tensorflowonspark/compat.py:10-17 chief-vs-worker export dance).
On TPU, orbax is the native checkpointer: async-capable, sharding-aware
(restores distributed arrays directly onto their mesh shards), and
multi-host-safe (only process 0 writes metadata; every host writes its own
shards).
"""

import logging
import os

from tensorflowonspark_tpu import chaos, obs

logger = logging.getLogger(__name__)


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


#: marker key distinguishing a saved TrainState from a user's plain dict that
#: happens to have step/params/opt_state keys
_STATE_SENTINEL = "__train_state__"


def _to_saveable(state):
    """TrainState saves as a named dict so a target-less restore is
    self-describing (a bare custom pytree would come back as a list)."""
    from tensorflowonspark_tpu.train.strategy import TrainState

    if isinstance(state, TrainState):
        # model_state is ALWAYS present (empty dict included) so the saved and
        # target tree structures agree regardless of whether the model carries
        # batch_stats — restoring a stats-bearing checkpoint into a fresh
        # TrainState must not silently drop the stats
        return {
            _STATE_SENTINEL: 1,
            "step": state.step,
            "params": state.params,
            "opt_state": state.opt_state,
            "model_state": state.model_state,
        }
    return state


def _from_saved(tree, target):
    from tensorflowonspark_tpu.train.strategy import TrainState

    if isinstance(target, TrainState) or (
        target is None and isinstance(tree, dict) and _STATE_SENTINEL in tree
    ):
        return TrainState(
            tree["step"], tree["params"], tree["opt_state"], tree.get("model_state")
        )
    return tree


def save_checkpoint(path, state, force=True):
    """Save a pytree ``state`` (params/opt-state/step) to ``path``.

    Unlike the reference's chief-only TF checkpointing, orbax wants *every*
    process to call save in a multi-host setup; non-primary hosts write their
    own array shards (the reference instead had workers save to a throwaway
    'worker_model' dir, compat.py:15-17 — that dance is unnecessary here).
    """
    path = os.path.abspath(os.path.expanduser(path))
    ckptr = _checkpointer()
    ckptr.save(path, _to_saveable(state), force=force)
    ckptr.wait_until_finished()
    if chaos.active and chaos.fire("checkpoint.corrupt_write"):
        _tear_checkpoint(path)
    logger.info("saved checkpoint to %s", path)
    return path


def _tear_checkpoint(path):
    """Chaos fault ``checkpoint.corrupt_write``: leave the checkpoint torn on
    disk — the shape a host crash mid-write produces. Truncates the largest
    file (the tree metadata / array data; small marker files like
    ``_CHECKPOINT_METADATA`` are optional and orbax restores fine without
    them). ``restore_latest`` must survive it."""
    files = []
    for root, _dirs, names in os.walk(path):
        for name in names:
            sub = os.path.join(root, name)
            try:
                files.append((os.path.getsize(sub), sub))
            except OSError:
                continue
    for _size, sub in sorted(files, reverse=True):
        try:
            with open(sub, "r+b") as f:
                f.truncate(max(0, os.path.getsize(sub) // 2))
            logger.warning("chaos: truncated checkpoint file %s", sub)
            return
        except OSError:
            continue


def restore_checkpoint(path, target=None):
    """Restore a pytree from ``path``; ``target`` gives structure/shardings."""
    path = os.path.abspath(os.path.expanduser(path))
    if chaos.active and chaos.fire("checkpoint.restore_fail"):
        raise IOError("chaos: injected restore failure for {}".format(path))
    ckptr = _checkpointer()
    if target is None:
        state = ckptr.restore(path)
    else:
        try:
            state = ckptr.restore(path, _to_saveable(target))
        except Exception as targeted_err:
            # checkpoints written before model_state was always included
            # mismatch the target's tree structure; retry with the OLD
            # layout as the target (keeping every other leaf's sharding).
            # Any other failure re-raises the original error.
            old_target = _to_saveable(target)
            if not (isinstance(old_target, dict) and "model_state" in old_target):
                raise
            old_target = {k: v for k, v in old_target.items() if k != "model_state"}
            try:
                state = ckptr.restore(path, old_target)
            except Exception:
                raise targeted_err
            logger.warning(
                "restored pre-model_state checkpoint layout from %s", path
            )
    logger.info("restored checkpoint from %s", path)
    return _from_saved(state, target)


def _numbered_checkpoints(model_dir, prefix="ckpt_"):
    """Sorted [(step, path)] of step-numbered checkpoint dirs under
    ``model_dir`` whose names start with ``prefix``."""
    model_dir = os.path.abspath(os.path.expanduser(model_dir))
    if not os.path.isdir(model_dir):
        return []
    steps = []
    for name in os.listdir(model_dir):
        sub = os.path.join(model_dir, name)
        if os.path.isdir(sub) and name.startswith(prefix):
            tail = name.rsplit("_", 1)[-1]
            if tail.isdigit():
                steps.append((int(tail), sub))
    return sorted(steps)


def latest_checkpoint(model_dir, prefix="ckpt_"):
    """Return the newest step-numbered checkpoint dir under ``model_dir``
    (the reference leaned on ``tf.train.latest_checkpoint``,
    pipeline.py:541-544).

    Matches the same ``ckpt_`` prefix ``prune_checkpoints`` deletes, so a
    user-owned numbered sibling (``run_9``, export versions) can neither be
    mistaken for the resume point nor shadow the real one. Pass
    ``prefix=""`` to accept any ``*_<digits>`` layout."""
    steps = _numbered_checkpoints(model_dir, prefix)
    if not steps and prefix:
        # numbered dirs that the prefix gate excluded would otherwise turn
        # into a SILENT fresh start after a layout change — say so
        unmatched = _numbered_checkpoints(model_dir, "")
        if unmatched:
            logger.warning(
                "%s has %d step-numbered dir(s) (e.g. %s) but none match the "
                "%r prefix; resuming from scratch. Pass prefix=\"\" to accept "
                "any *_<digits> layout.",
                model_dir, len(unmatched), os.path.basename(unmatched[-1][1]), prefix,
            )
    return steps[-1][1] if steps else None


def restore_latest(model_dir, target=None, prefix="ckpt_"):
    """Restore the newest *restorable* checkpoint under ``model_dir``.

    Walks step-numbered checkpoints newest-first and returns
    ``(state, path)``; a checkpoint that fails to restore (torn write from a
    crashed host, truncated array file) is skipped with a warning and a
    ``checkpoint_restore_fallbacks_total`` count, and the next-older one is
    tried — the resume contract survives a corrupt newest checkpoint instead
    of dying on it. Returns ``(None, None)`` when nothing is restorable;
    the last restore error re-raises only if every checkpoint failed AND the
    caller had at least one to try (so "no checkpoints yet" stays a clean
    fresh start)."""
    steps = _numbered_checkpoints(model_dir, prefix)
    if not steps:
        latest_checkpoint(model_dir, prefix)  # emit the prefix-mismatch warning
        return None, None
    last_err = None
    for _step, path in reversed(steps):
        try:
            return restore_checkpoint(path, target), path
        except Exception as e:
            last_err = e
            obs.counter(
                "checkpoint_restore_fallbacks_total",
                help="checkpoints skipped as unrestorable during resume",
            ).inc()
            logger.warning(
                "checkpoint %s is unrestorable (%s); falling back to an older one",
                path, e,
            )
    raise last_err


def prune_checkpoints(model_dir, keep):
    """Delete all but the newest ``keep`` step-numbered checkpoints (the
    ``tf.train.CheckpointManager(max_to_keep=...)`` capability: params +
    optimizer state add up fast on long runs and only the newest feeds the
    resume contract). Concurrent pruning by multiple saver processes is
    harmless — deletions race only against each other, on dirs nobody reads
    again. Returns the number of checkpoints removed."""
    import shutil

    if keep <= 0:
        return 0
    # same ckpt_ gate as latest_checkpoint: rmtree must never touch sibling
    # numbered dirs the user owns (export versions, run_3, ...)
    ckpts = _numbered_checkpoints(model_dir)
    doomed = ckpts[:-keep]
    for _, path in doomed:
        shutil.rmtree(path, ignore_errors=True)
    return len(doomed)


def export_saved_model(model_dir, export_dir, state, is_chief=True):
    """Export final params for serving/inference.

    The orbax checkpoint *is* the exchange format (params restore anywhere,
    including CPU inference executors); ``is_chief`` is accepted for reference
    API parity (compat.py:10-17) but all hosts participate in a distributed
    save.
    """
    del model_dir  # kept for signature parity with the reference
    return save_checkpoint(export_dir, state)
