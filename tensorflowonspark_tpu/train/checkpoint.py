"""Checkpoint / export helpers (orbax-backed).

Capability-parity with the reference's checkpoint story, which was fully
delegated to TensorFlow (SURVEY.md §5 "Checkpoint / resume";
/root/reference/tensorflowonspark/compat.py:10-17 chief-vs-worker export dance).
On TPU, orbax is the native checkpointer: async-capable, sharding-aware
(restores distributed arrays directly onto their mesh shards), and
multi-host-safe (only process 0 writes metadata; every host writes its own
shards).
"""

import logging
import os

logger = logging.getLogger(__name__)


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


#: marker key distinguishing a saved TrainState from a user's plain dict that
#: happens to have step/params/opt_state keys
_STATE_SENTINEL = "__train_state__"


def _to_saveable(state):
    """TrainState saves as a named dict so a target-less restore is
    self-describing (a bare custom pytree would come back as a list)."""
    from tensorflowonspark_tpu.train.strategy import TrainState

    if isinstance(state, TrainState):
        # model_state is ALWAYS present (empty dict included) so the saved and
        # target tree structures agree regardless of whether the model carries
        # batch_stats — restoring a stats-bearing checkpoint into a fresh
        # TrainState must not silently drop the stats
        return {
            _STATE_SENTINEL: 1,
            "step": state.step,
            "params": state.params,
            "opt_state": state.opt_state,
            "model_state": state.model_state,
        }
    return state


def _from_saved(tree, target):
    from tensorflowonspark_tpu.train.strategy import TrainState

    if isinstance(target, TrainState) or (
        target is None and isinstance(tree, dict) and _STATE_SENTINEL in tree
    ):
        return TrainState(
            tree["step"], tree["params"], tree["opt_state"], tree.get("model_state")
        )
    return tree


def save_checkpoint(path, state, force=True):
    """Save a pytree ``state`` (params/opt-state/step) to ``path``.

    Unlike the reference's chief-only TF checkpointing, orbax wants *every*
    process to call save in a multi-host setup; non-primary hosts write their
    own array shards (the reference instead had workers save to a throwaway
    'worker_model' dir, compat.py:15-17 — that dance is unnecessary here).
    """
    path = os.path.abspath(os.path.expanduser(path))
    ckptr = _checkpointer()
    ckptr.save(path, _to_saveable(state), force=force)
    ckptr.wait_until_finished()
    logger.info("saved checkpoint to %s", path)
    return path


def restore_checkpoint(path, target=None):
    """Restore a pytree from ``path``; ``target`` gives structure/shardings."""
    path = os.path.abspath(os.path.expanduser(path))
    ckptr = _checkpointer()
    if target is None:
        state = ckptr.restore(path)
    else:
        try:
            state = ckptr.restore(path, _to_saveable(target))
        except Exception as targeted_err:
            # checkpoints written before model_state was always included
            # mismatch the target's tree structure; retry with the OLD
            # layout as the target (keeping every other leaf's sharding).
            # Any other failure re-raises the original error.
            old_target = _to_saveable(target)
            if not (isinstance(old_target, dict) and "model_state" in old_target):
                raise
            old_target = {k: v for k, v in old_target.items() if k != "model_state"}
            try:
                state = ckptr.restore(path, old_target)
            except Exception:
                raise targeted_err
            logger.warning(
                "restored pre-model_state checkpoint layout from %s", path
            )
    logger.info("restored checkpoint from %s", path)
    return _from_saved(state, target)


def _numbered_checkpoints(model_dir, prefix="ckpt_"):
    """Sorted [(step, path)] of step-numbered checkpoint dirs under
    ``model_dir`` whose names start with ``prefix``."""
    model_dir = os.path.abspath(os.path.expanduser(model_dir))
    if not os.path.isdir(model_dir):
        return []
    steps = []
    for name in os.listdir(model_dir):
        sub = os.path.join(model_dir, name)
        if os.path.isdir(sub) and name.startswith(prefix):
            tail = name.rsplit("_", 1)[-1]
            if tail.isdigit():
                steps.append((int(tail), sub))
    return sorted(steps)


def latest_checkpoint(model_dir, prefix="ckpt_"):
    """Return the newest step-numbered checkpoint dir under ``model_dir``
    (the reference leaned on ``tf.train.latest_checkpoint``,
    pipeline.py:541-544).

    Matches the same ``ckpt_`` prefix ``prune_checkpoints`` deletes, so a
    user-owned numbered sibling (``run_9``, export versions) can neither be
    mistaken for the resume point nor shadow the real one. Pass
    ``prefix=""`` to accept any ``*_<digits>`` layout."""
    steps = _numbered_checkpoints(model_dir, prefix)
    return steps[-1][1] if steps else None


def prune_checkpoints(model_dir, keep):
    """Delete all but the newest ``keep`` step-numbered checkpoints (the
    ``tf.train.CheckpointManager(max_to_keep=...)`` capability: params +
    optimizer state add up fast on long runs and only the newest feeds the
    resume contract). Concurrent pruning by multiple saver processes is
    harmless — deletions race only against each other, on dirs nobody reads
    again. Returns the number of checkpoints removed."""
    import shutil

    if keep <= 0:
        return 0
    # same ckpt_ gate as latest_checkpoint: rmtree must never touch sibling
    # numbered dirs the user owns (export versions, run_3, ...)
    ckpts = _numbered_checkpoints(model_dir)
    doomed = ckpts[:-keep]
    for _, path in doomed:
        shutil.rmtree(path, ignore_errors=True)
    return len(doomed)


def export_saved_model(model_dir, export_dir, state, is_chief=True):
    """Export final params for serving/inference.

    The orbax checkpoint *is* the exchange format (params restore anywhere,
    including CPU inference executors); ``is_chief`` is accepted for reference
    API parity (compat.py:10-17) but all hosts participate in a distributed
    save.
    """
    del model_dir  # kept for signature parity with the reference
    return save_checkpoint(export_dir, state)
