"""Checkpoint / export helpers (orbax-backed).

Capability-parity with the reference's checkpoint story, which was fully
delegated to TensorFlow (SURVEY.md §5 "Checkpoint / resume";
/root/reference/tensorflowonspark/compat.py:10-17 chief-vs-worker export dance).
On TPU, orbax is the native checkpointer: async-capable, sharding-aware
(restores distributed arrays directly onto their mesh shards), and
multi-host-safe (only process 0 writes metadata; every host writes its own
shards).
"""

import logging
import os

from tensorflowonspark_tpu import chaos, obs
from tensorflowonspark_tpu.ckpt import manifest as ckpt_manifest
from tensorflowonspark_tpu.ckpt.engine import TMP_MARKER

logger = logging.getLogger(__name__)


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


#: marker key distinguishing a saved TrainState from a user's plain dict that
#: happens to have step/params/opt_state keys
_STATE_SENTINEL = "__train_state__"


def _to_saveable(state):
    """TrainState saves as a named dict so a target-less restore is
    self-describing (a bare custom pytree would come back as a list)."""
    from tensorflowonspark_tpu.train.strategy import TrainState

    if isinstance(state, TrainState):
        # model_state is ALWAYS present (empty dict included) so the saved and
        # target tree structures agree regardless of whether the model carries
        # batch_stats — restoring a stats-bearing checkpoint into a fresh
        # TrainState must not silently drop the stats
        return {
            _STATE_SENTINEL: 1,
            "step": state.step,
            "params": state.params,
            "opt_state": state.opt_state,
            "model_state": state.model_state,
        }
    return state


def _from_saved(tree, target):
    from tensorflowonspark_tpu.train.strategy import TrainState

    if isinstance(target, TrainState) or (
        target is None and isinstance(tree, dict) and _STATE_SENTINEL in tree
    ):
        return TrainState(
            tree["step"], tree["params"], tree["opt_state"], tree.get("model_state")
        )
    return tree


def save_checkpoint(path, state, force=True):
    """Save a pytree ``state`` (params/opt-state/step) to ``path``.

    Unlike the reference's chief-only TF checkpointing, orbax wants *every*
    process to call save in a multi-host setup; non-primary hosts write their
    own array shards (the reference instead had workers save to a throwaway
    'worker_model' dir, compat.py:15-17 — that dance is unnecessary here).
    """
    path = os.path.abspath(os.path.expanduser(path))
    ckptr = _checkpointer()
    ckptr.save(path, _to_saveable(state), force=force)
    ckptr.wait_until_finished()
    # manifest AFTER the full write, BEFORE the chaos tear: sync saves get
    # the same cheap-verify integrity story as the async engine's commits
    ckpt_manifest.write_manifest(path)
    if chaos.active and chaos.fire("checkpoint.corrupt_write"):
        _tear_checkpoint(path)
    logger.info("saved checkpoint to %s", path)
    return path


def _tear_checkpoint(path):
    """Chaos fault ``checkpoint.corrupt_write``: leave the checkpoint torn on
    disk — the shape a host crash mid-write produces. Truncates the largest
    file (the tree metadata / array data; small marker files like
    ``_CHECKPOINT_METADATA`` are optional and orbax restores fine without
    them). ``restore_latest`` must survive it."""
    files = []
    for root, _dirs, names in os.walk(path):
        for name in names:
            sub = os.path.join(root, name)
            try:
                files.append((os.path.getsize(sub), sub))
            except OSError:
                continue
    for _size, sub in sorted(files, reverse=True):
        try:
            with open(sub, "r+b") as f:
                f.truncate(max(0, os.path.getsize(sub) // 2))
            logger.warning("chaos: truncated checkpoint file %s", sub)
            return
        except OSError:
            continue


def restore_checkpoint(path, target=None):
    """Restore a pytree from ``path``; ``target`` gives structure/shardings."""
    path = os.path.abspath(os.path.expanduser(path))
    if chaos.active and chaos.fire("checkpoint.restore_fail"):
        raise IOError("chaos: injected restore failure for {}".format(path))
    ckptr = _checkpointer()
    if target is None:
        state = ckptr.restore(path)
    else:
        try:
            state = ckptr.restore(path, _to_saveable(target))
        except Exception as targeted_err:
            # checkpoints written before model_state was always included
            # mismatch the target's tree structure; retry with the OLD
            # layout as the target (keeping every other leaf's sharding).
            # Any other failure re-raises the original error.
            old_target = _to_saveable(target)
            if not (isinstance(old_target, dict) and "model_state" in old_target):
                raise
            old_target = {k: v for k, v in old_target.items() if k != "model_state"}
            try:
                state = ckptr.restore(path, old_target)
            except Exception:
                raise targeted_err
            logger.warning(
                "restored pre-model_state checkpoint layout from %s", path
            )
    logger.info("restored checkpoint from %s", path)
    return _from_saved(state, target)


def _numbered_checkpoints(model_dir, prefix="ckpt_"):
    """Sorted [(step, path)] of step-numbered checkpoint dirs under
    ``model_dir`` whose names start with ``prefix``."""
    model_dir = os.path.abspath(os.path.expanduser(model_dir))
    if not os.path.isdir(model_dir):
        return []
    steps = []
    for name in os.listdir(model_dir):
        sub = os.path.join(model_dir, name)
        if name.startswith(TMP_MARKER):
            # uncommitted staging dir of an async-engine commit in progress
            # (or torn by a crash): never a restore candidate, never pruned
            # here — even under prefix="" its *_<digits> tail would match
            continue
        if os.path.isdir(sub) and name.startswith(prefix):
            tail = name.rsplit("_", 1)[-1]
            if tail.isdigit():
                steps.append((int(tail), sub))
    return sorted(steps)


def latest_checkpoint(model_dir, prefix="ckpt_"):
    """Return the newest step-numbered checkpoint dir under ``model_dir``
    (the reference leaned on ``tf.train.latest_checkpoint``,
    pipeline.py:541-544).

    Matches the same ``ckpt_`` prefix ``prune_checkpoints`` deletes, so a
    user-owned numbered sibling (``run_9``, export versions) can neither be
    mistaken for the resume point nor shadow the real one. Pass
    ``prefix=""`` to accept any ``*_<digits>`` layout."""
    steps = _numbered_checkpoints(model_dir, prefix)
    if not steps and prefix:
        # numbered dirs that the prefix gate excluded would otherwise turn
        # into a SILENT fresh start after a layout change — say so
        unmatched = _numbered_checkpoints(model_dir, "")
        if unmatched:
            logger.warning(
                "%s has %d step-numbered dir(s) (e.g. %s) but none match the "
                "%r prefix; resuming from scratch. Pass prefix=\"\" to accept "
                "any *_<digits> layout.",
                model_dir, len(unmatched), os.path.basename(unmatched[-1][1]), prefix,
            )
    return steps[-1][1] if steps else None


def restore_latest(model_dir, target=None, prefix="ckpt_"):
    """Restore the newest *restorable* checkpoint under ``model_dir``.

    Walks step-numbered checkpoints newest-first and returns
    ``(state, path)``. Manifest-carrying checkpoints (every async-engine
    commit and post-manifest sync save) are **cheap-verified first** —
    stat + CRC32 against ``MANIFEST.json`` — so a torn or bitrotten
    candidate is rejected without paying for (or trusting) a full orbax
    restore attempt; legacy manifest-less checkpoints keep the
    attempt-the-restore contract. Every skipped candidate is logged with
    *which* checkpoint was skipped and *why* (torn manifest, checksum
    mismatch, restore exception) and counted in
    ``checkpoint_restore_fallbacks_total``; a final warning summarizes the
    skips when an older checkpoint wins. Returns ``(None, None)`` when the
    directory has no checkpoints at all; raises only if every candidate
    failed (so "no checkpoints yet" stays a clean fresh start)."""
    steps = _numbered_checkpoints(model_dir, prefix)
    if not steps:
        latest_checkpoint(model_dir, prefix)  # emit the prefix-mismatch warning
        return None, None
    last_err = None
    skipped = []  # (path, reason) — the resume audit trail

    def _skip(path, reason):
        skipped.append((path, reason))
        obs.counter(
            "checkpoint_restore_fallbacks_total",
            help="checkpoints skipped as unrestorable during resume",
        ).inc()
        logger.warning(
            "skipping checkpoint %s: %s; falling back to an older one",
            path, reason,
        )

    for _step, path in reversed(steps):
        ok, reason = ckpt_manifest.verify(path)
        if not ok:
            _skip(path, reason)
            continue
        try:
            state = restore_checkpoint(path, target)
        except Exception as e:
            last_err = e
            _skip(path, "restore failed ({})".format(e))
            continue
        if skipped:
            logger.warning(
                "resumed from %s after skipping %d newer checkpoint(s): %s",
                path, len(skipped),
                "; ".join(
                    "{}: {}".format(os.path.basename(p), r) for p, r in skipped
                ),
            )
        return state, path
    if last_err is not None:
        raise last_err
    raise IOError(
        "no restorable checkpoint under {}: {}".format(
            model_dir,
            "; ".join("{}: {}".format(os.path.basename(p), r) for p, r in skipped),
        )
    )


def prune_checkpoints(model_dir, keep, in_flight=None):
    """Delete all but the newest ``keep`` step-numbered checkpoints (the
    ``tf.train.CheckpointManager(max_to_keep=...)`` capability: params +
    optimizer state add up fast on long runs and only the newest feeds the
    resume contract). Concurrent pruning by multiple saver processes is
    harmless — deletions race only against each other, on dirs nobody reads
    again. Returns the number of checkpoints removed.

    Two guards keep pruning safe against the async engine: uncommitted
    ``tmp.*`` staging dirs are never enumerated (``_numbered_checkpoints``
    skips them), and any path in the engine's in-flight registry
    (:func:`tensorflowonspark_tpu.ckpt.engine.in_flight_paths`, or the
    explicit ``in_flight`` override) is exempt — a checkpoint mid-commit
    must never be deleted out from under its writer, even when a flood of
    newer commits would otherwise age it out."""
    import shutil

    if keep <= 0:
        return 0
    if in_flight is None:
        from tensorflowonspark_tpu.ckpt.engine import in_flight_paths

        in_flight = in_flight_paths()
    busy = {os.path.abspath(os.path.expanduser(p)) for p in in_flight}
    # same ckpt_ gate as latest_checkpoint: rmtree must never touch sibling
    # numbered dirs the user owns (export versions, run_3, ...)
    ckpts = _numbered_checkpoints(model_dir)
    doomed = [(step, path) for step, path in ckpts[:-keep] if path not in busy]
    for _, path in doomed:
        shutil.rmtree(path, ignore_errors=True)
    return len(doomed)


def export_saved_model(model_dir, export_dir, state, is_chief=True):
    """Export final params for serving/inference.

    The orbax checkpoint *is* the exchange format (params restore anywhere,
    including CPU inference executors); ``is_chief`` is accepted for reference
    API parity (compat.py:10-17) but all hosts participate in a distributed
    save.
    """
    del model_dir  # kept for signature parity with the reference
    return save_checkpoint(export_dir, state)
