"""TFRecord file IO + tf.train.Example wire format, dependency-free.

The reference delegated TFRecord IO to a prebuilt Hadoop InputFormat jar
(/root/reference/lib/tensorflow-hadoop-1.0-SNAPSHOT.jar, driven by
dfutil.py:39,63) and the Example proto to TensorFlow. Here both are
implemented directly: the TFRecord framing (length + masked-crc32c records)
and a minimal protobuf codec for the fixed ``Example`` schema — so the TPU
framework reads/writes the interchange format without a TensorFlow or JVM
dependency.

The bulk-ingest hot path has a C++ twin in ``native/tfrecord_io.cc`` (one
FFI call loads+verifies a whole shard), bound via
:mod:`tensorflowonspark_tpu.native_io`; this module is the portable codec
and the write path.

Remote filesystems: paths with a URI scheme (``gs://``, ``hdfs://``,
``s3://``, ``memory://``, ``file://``) are routed through fsspec — the
replacement for the reference's Hadoop-FS-by-way-of-the-jar reach
(reference dfutil.py:39-41,63-65).

Wire format reference: tensorflow/core/lib/io/record_writer.h (framing) and
tensorflow/core/example/example.proto, feature.proto (schema).
"""

import os
import struct

from tensorflowonspark_tpu.store import framing

# -- filesystem routing (local fast path; fsspec for URI schemes) -------------


def is_uri(path):
    return "://" in str(path)


def _fs(path):
    import fsspec

    fs, _token, paths = fsspec.get_fs_token_paths(path)
    return fs, paths[0]


def open_file(path, mode="rb"):
    """Open a local path or any fsspec URI."""
    if is_uri(path):
        fs, p = _fs(path)
        return fs.open(p, mode)
    return open(path, mode)


def makedirs(path):
    if is_uri(path):
        fs, p = _fs(path)
        fs.makedirs(p, exist_ok=True)
    else:
        os.makedirs(path, exist_ok=True)


def rename(src, dst):
    """Same-filesystem rename (the shard commit step).

    Local paths get a true atomic ``os.replace``. On fsspec URIs ``mv`` is
    copy+delete on object stores; if it refuses because the destination
    already exists, a racing speculative/retried committer won — its shard
    is equivalent (same deterministic partition), so the existing file is
    kept and the temp file dropped. The existing destination is never
    deleted first: that would open a window where a committed shard is gone
    and no task remains to rewrite it."""
    if is_uri(src):
        fs, s = _fs(src)
        _fs2, d = _fs(dst)
        try:
            fs.mv(s, d)
        except Exception:
            if not fs.exists(d):
                raise
            try:
                fs.rm(s)
            except Exception:
                pass  # stray temp file; harmless to shard listing
    else:
        os.replace(src, dst)

# -- TFRecord framing ----------------------------------------------------------
# The read-side framing loop lives in store/framing.py (one copy shared with
# native_io and the remote stores); this module keeps the write path and the
# open_file routing that covers fsspec URIs.

_MASK_DELTA = framing._MASK_DELTA
_masked_crc = framing.masked_crc


class TFRecordWriter:
    def __init__(self, path):
        self._f = open_file(path, "wb")

    def write(self, record):
        header = struct.pack("<Q", len(record))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(record)
        self._f.write(struct.pack("<I", _masked_crc(record)))

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_records(path, verify_crc=True):
    """Yield raw record bytes from a TFRecord file (local or fsspec URI)."""
    with open_file(path, "rb") as f:
        yield from framing.read_framed(f, path, verify_crc=verify_crc)


def read_records_chunked(path, chunk_records=1024, verify_crc=True):
    """Yield lists of up to ``chunk_records`` raw records — the streaming
    twin of :func:`read_records`, shaped like
    :func:`tensorflowonspark_tpu.native_io.read_records_chunked` so the
    loader's chunked path works identically with either codec (this one also
    covers fsspec URIs, which the native reader cannot open)."""
    return framing.iter_chunks(
        lambda: framing.FramedChunkReader(
            open_file(path, "rb"), path, verify_crc=verify_crc
        ),
        chunk_records,
    )


# -- minimal protobuf wire codec ----------------------------------------------


def _varint(n):
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf, pos):
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _tag(field, wire_type):
    return _varint((field << 3) | wire_type)


def _len_delimited(field, payload):
    return _tag(field, 2) + _varint(len(payload)) + payload


# -- Example proto -------------------------------------------------------------


def encode_feature(values):
    """One tf.train.Feature: list of bytes → BytesList, ints → Int64List
    (packed varints), floats → FloatList (packed fixed32)."""
    if not values:
        return b""
    v0 = values[0]
    if isinstance(v0, (bytes, bytearray, str)):
        payload = b"".join(
            _len_delimited(1, v if isinstance(v, bytes) else str(v).encode("utf-8"))
            for v in values
        )
        return _len_delimited(1, payload)  # Feature.bytes_list
    if isinstance(v0, (bool,)) or isinstance(v0, int):
        packed = b"".join(_varint(v & 0xFFFFFFFFFFFFFFFF) for v in values)
        return _len_delimited(3, _len_delimited(1, packed))  # Feature.int64_list
    if isinstance(v0, float):
        packed = struct.pack("<{}f".format(len(values)), *values)
        return _len_delimited(2, _len_delimited(1, packed))  # Feature.float_list
    raise TypeError("unsupported feature value type {!r}".format(type(v0)))


def encode_example(features):
    """``{name: list-of-values}`` → serialized tf.train.Example bytes."""
    entries = b""
    for name in sorted(features):
        values = features[name]
        if not isinstance(values, (list, tuple)):
            values = [values]
        entry = _len_delimited(1, name.encode("utf-8")) + _len_delimited(
            2, encode_feature(list(values))
        )
        entries += _len_delimited(1, entry)  # Features.feature map entry
    return _len_delimited(1, entries)  # Example.features


def _decode_packed_varints(buf):
    out, pos = [], 0
    while pos < len(buf):
        v, pos = _read_varint(buf, pos)
        if v >= 1 << 63:  # two's-complement negative int64
            v -= 1 << 64
        out.append(v)
    return out


def _decode_feature(buf):
    """Feature bytes → ('bytes'|'int64'|'float', values)."""
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        field, wt = tag >> 3, tag & 7
        assert wt == 2, "unexpected wire type in Feature"
        length, pos = _read_varint(buf, pos)
        inner = buf[pos : pos + length]
        pos += length
        if field == 1:  # BytesList
            vals, ipos = [], 0
            while ipos < len(inner):
                t, ipos = _read_varint(inner, ipos)
                assert t >> 3 == 1
                ln, ipos = _read_varint(inner, ipos)
                vals.append(bytes(inner[ipos : ipos + ln]))
                ipos += ln
            return "bytes", vals
        if field == 2:  # FloatList
            vals, ipos = [], 0
            while ipos < len(inner):
                t, ipos = _read_varint(inner, ipos)
                assert t >> 3 == 1
                if t & 7 == 2:  # packed
                    ln, ipos = _read_varint(inner, ipos)
                    vals.extend(
                        struct.unpack("<{}f".format(ln // 4), inner[ipos : ipos + ln])
                    )
                    ipos += ln
                else:  # unpacked fixed32
                    vals.append(struct.unpack("<f", inner[ipos : ipos + 4])[0])
                    ipos += 4
            return "float", vals
        if field == 3:  # Int64List
            vals, ipos = [], 0
            while ipos < len(inner):
                t, ipos = _read_varint(inner, ipos)
                assert t >> 3 == 1
                if t & 7 == 2:  # packed
                    ln, ipos = _read_varint(inner, ipos)
                    vals.extend(_decode_packed_varints(inner[ipos : ipos + ln]))
                    ipos += ln
                else:  # unpacked varint
                    v, ipos = _read_varint(inner, ipos)
                    if v >= 1 << 63:
                        v -= 1 << 64
                    vals.append(v)
            return "int64", vals
    return "bytes", []


def decode_example(buf):
    """Serialized Example → ``{name: (kind, values)}``."""
    out = {}
    pos = 0
    while pos < len(buf):
        tag, pos = _read_varint(buf, pos)
        if tag >> 3 != 1 or tag & 7 != 2:
            raise ValueError("not an Example proto")
        length, pos = _read_varint(buf, pos)
        features_buf = buf[pos : pos + length]
        pos += length
        fpos = 0
        while fpos < len(features_buf):
            ftag, fpos = _read_varint(features_buf, fpos)
            assert ftag >> 3 == 1 and ftag & 7 == 2, "bad Features map entry"
            flen, fpos = _read_varint(features_buf, fpos)
            entry = features_buf[fpos : fpos + flen]
            fpos += flen
            epos = 0
            name, feature = None, ("bytes", [])
            while epos < len(entry):
                etag, epos = _read_varint(entry, epos)
                elen, epos = _read_varint(entry, epos)
                payload = entry[epos : epos + elen]
                epos += elen
                if etag >> 3 == 1:
                    name = payload.decode("utf-8")
                elif etag >> 3 == 2:
                    feature = _decode_feature(payload)
            if name is not None:
                out[name] = feature
    return out


# -- directory-level helpers ---------------------------------------------------


def write_shard(path, examples):
    """Write a list of feature-dicts as one TFRecord shard file."""
    parent = path.rsplit("/", 1)[0] if is_uri(path) else os.path.dirname(path)
    makedirs(parent)
    count = 0
    with TFRecordWriter(path) as w:
        for features in examples:
            w.write(encode_example(features))
            count += 1
    return count


def _is_shard_name(name):
    return name.startswith(("part-", "shard-")) and not name.endswith((".crc", ".tmp"))


def list_shards(directory):
    """TFRecord shard files under a directory (reference part-r-* layout);
    accepts local paths and fsspec URIs."""
    if is_uri(directory):
        fs, p = _fs(directory)
        out = []
        for entry in sorted(fs.ls(p, detail=False)):
            if _is_shard_name(entry.rsplit("/", 1)[-1]):
                out.append(fs.unstrip_protocol(entry))
        return out
    out = []
    for name in sorted(os.listdir(directory)):
        if _is_shard_name(name):
            out.append(os.path.join(directory, name))
    return out


def read_examples(path):
    for rec in read_records(path):
        yield decode_example(rec)
