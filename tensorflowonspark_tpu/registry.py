"""Crash-survivable control plane: lease-based membership + heartbeat trees.

Before this module the cluster's membership truth was scattered: the
reservation server held a one-shot assembly snapshot, the watchdog kept a
private ``last_beat`` dict of ad-hoc ``mgr.get("heartbeat")`` polls, and the
recovery ladder threaded blacklist *sets* by hand between attempts. A driver
restart lost all three at once, killing every in-flight job even though the
executors, jax children, and checkpoints were all healthy (ROADMAP open
item 5). This module makes membership a first-class, journaled object with
three tiers:

**Lease-based membership** (:class:`MembershipRegistry`). Every executor
holds a TTL lease granted at registration and renewed each time the driver
observes its heartbeat counter *advance* (a re-read of the same beat value
is not progress — that is exactly how a SIGKILLed child looks). Liveness
(:meth:`~MembershipRegistry.expire_stale`), the blacklist
(:meth:`~MembershipRegistry.blacklist` /
:meth:`~MembershipRegistry.is_blacklisted`) and the role map
(:meth:`~MembershipRegistry.begin_generation`) all read from this one
registry; lease expiry feeds :func:`tensorflowonspark_tpu.elastic.classify_failure`
as a first-class ``lease_expired`` event. A node that never beat at all is
exempt from expiry (slow child startup is the launch timeout's concern, not
a lease violation) — parity with the historical watchdog.

**Heartbeat aggregation trees** (:func:`plan_aggregation_tree` +
:class:`HeartbeatAggregator`). With N executors the driver used to open N
channel connections per watchdog cycle. Instead, ~sqrt(N) executors are
deterministically elected aggregators; each polls its group's channels
every window and publishes one JSON summary (beats, final statuses, error
flags) on its *own* channel under :data:`WINDOW_KEY`, so the steady-state
driver fan-in is O(sqrt N) sockets. The election is a pure function of the
assembled cluster info, so driver and executors agree without another
round-trip. Members whose aggregator goes quiet fall back to direct driver
polls — the tree is an optimization, never a single point of failure.

**Driver-restart survivability**. Every membership transition (join, lease
renew/expire, blacklist, role map, cluster epoch) is journaled under
``journal_dir``: an append-only ``journal.log`` of CRC-framed JSON lines,
compacted into a ``REGISTRY.json`` manifest via the same tmp+fsync+rename
discipline proven by :mod:`tensorflowonspark_tpu.ckpt.manifest` (the
previous manifest is retained as ``REGISTRY.json.prev``, and the journal is
truncated only *after* a successful manifest rename — so a manifest torn
mid-publish always leaves prev-manifest + journal able to reconstruct the
full state). :meth:`MembershipRegistry.recover` replays manifest + journal,
re-adopts live executors whose leases have not yet expired on the wall
clock (they keep training through the driver outage), and resumes under an
**incremented epoch**: any still-running pre-crash driver instance is
fenced — its next durable commit sees the higher on-disk epoch and raises
:class:`StaleEpochError` instead of clobbering the new generation's
journal.

Chaos sites: ``control.lease_delay`` (stall a renewal — benign),
``control.journal_tear`` (tear the manifest publish, or with
``target: "journal"`` a journal append — recovery must fall back to the
previous committed manifest), and ``control.driver_crash`` (consulted by
the TFCluster watchdog: drop the in-memory registry mid-train and recover
from the journal, as a restarted driver would).

Metrics (driver-global unless noted; all in ``TFCluster.metrics()``):
``registry_leases_active`` / ``registry_epoch`` gauges,
``registry_lease_expirations_total`` / ``registry_journal_commits_total``
counters, and ``heartbeat_agg_windows_total`` (counted aggregator-side in a
private registry published over the channel's :data:`AGGREGATOR_KEY` lane).
"""

import json
import logging
import math
import os
import threading
import time
import zlib

from tensorflowonspark_tpu import chaos, durable, obs, resilience
from tensorflowonspark_tpu.obs import aggregate as obs_aggregate
from tensorflowonspark_tpu.obs import registry as obs_registry

logger = logging.getLogger(__name__)

#: committed state snapshot (the durable truth after compaction)
MANIFEST_NAME = "REGISTRY.json"
#: previous committed manifest, kept so a torn publish can fall back
PREV_MANIFEST_NAME = "REGISTRY.json.prev"
#: append-only transition log since the last manifest compaction
JOURNAL_NAME = "journal.log"
#: manifest format version (bump on incompatible layout changes)
VERSION = 1

#: default lease TTL: seconds a member may go without an observed heartbeat
#: advance before its lease expires (same knob as the historical watchdog)
DEFAULT_TTL = float(os.environ.get("TOS_HEARTBEAT_STALE", "30"))

#: journal records between manifest compactions
MANIFEST_EVERY = int(os.environ.get("TOS_REGISTRY_MANIFEST_EVERY", "16"))

#: channel key an aggregator publishes its per-window summary under
WINDOW_KEY = "heartbeat_window"
#: channel obs lane for the aggregator thread's private registry (overwrite
#: semantics, like the jax child's obs_snapshot lane)
AGGREGATOR_KEY = obs_aggregate.AGGREGATOR_KEY

#: seconds per aggregation window (defaults to the heartbeat interval: one
#: summary per beat generation)
WINDOW_SECS = float(
    os.environ.get("TOS_HEARTBEAT_WINDOW", os.environ.get("TOS_HEARTBEAT_INTERVAL", "2"))
)

#: ops that are fsynced at append time (a lost renew only ages a lease;
#: a lost join/expire/blacklist/epoch would corrupt recovery decisions)
_DURABLE_OPS = frozenset({"epoch", "join", "leave", "expire", "blacklist", "forgive", "role"})


class StaleEpochError(Exception):
    """A durable commit was refused because the on-disk manifest carries a
    higher epoch: another (newer) driver generation owns the journal now.
    The fenced writer must stop — its view of the cluster is history."""


# ---------------------------------------------------------------------------
# aggregation-tree election (pure functions shared by driver and executors)
# ---------------------------------------------------------------------------


def aggregation_enabled(num_nodes):
    """Whether the heartbeat aggregation tree is on for ``num_nodes``.

    ``TOS_HEARTBEAT_AGG``: ``"0"`` forces off, ``"1"`` forces on, anything
    else (default) enables it from ``TOS_HEARTBEAT_AGG_MIN`` nodes up.
    """
    mode = os.environ.get("TOS_HEARTBEAT_AGG", "auto")
    if mode == "0":
        return False
    if mode == "1":
        return num_nodes > 0
    return num_nodes >= int(os.environ.get("TOS_HEARTBEAT_AGG_MIN", "2"))


def window_coverage(summary, member_eids):
    """Which of ``member_eids`` one aggregator window summary actually covers.

    Returns ``(statuses, beats, flagged)``, the first two keyed by int
    executor id. A member appearing in NONE of them was unreachable from the
    aggregator (executor process gone) or has not produced a beat yet: it is
    NOT covered, and the driver must fall back to direct-polling it — a
    lease renewal inferred from a summary that carries no data for the
    member would keep a dead executor alive forever.
    """
    statuses_raw = summary.get("status") or {}
    beats_raw = summary.get("beats") or {}
    flagged = set(summary.get("errors") or [])
    statuses, beats = {}, {}
    for eid in member_eids:
        seid = str(eid)
        if seid in statuses_raw:
            statuses[eid] = statuses_raw[seid]
        elif seid in beats_raw:
            beats[eid] = beats_raw[seid]
    return statuses, beats, flagged & set(member_eids)


def plan_aggregation_tree(rows):
    """Elect aggregators: ``{aggregator_executor_id: [member ids...]}``.

    Pure function of the assembled cluster info (rows with a reachable
    channel), so every process computes the same tree without coordination:
    executor ids are sorted and chunked into ~sqrt(N) groups; the lowest id
    of each group aggregates it (itself included).
    """
    eids = sorted(r["executor_id"] for r in rows if r.get("manager_addr"))
    if not eids:
        return {}
    k = max(1, math.isqrt(len(eids)))
    size = -(-len(eids) // k)  # ceil division
    tree = {}
    for start in range(0, len(eids), size):
        group = eids[start:start + size]
        tree[group[0]] = group
    return tree


# ---------------------------------------------------------------------------
# the membership registry
# ---------------------------------------------------------------------------


class MembershipRegistry:
    """The cluster's single membership truth, journaled for driver restarts.

    Thread-safe: the reservation server's REG handler joins members, the
    watchdog renews/expires leases, and the recovery ladder reads/writes the
    blacklist, all concurrently. The wall clock (injectable ``clock``) is
    used for lease ages because journaled timestamps must stay comparable
    across a driver restart — a monotonic clock does not survive a process.

    ``journal_dir=None`` keeps the registry purely in-memory (tests, callers
    that do not want restart survivability); every durable-path method then
    degrades to the in-memory transition alone.
    """

    def __init__(self, ttl=None, journal_dir=None, clock=time.time,
                 manifest_every=None):
        self.ttl = DEFAULT_TTL if ttl is None else float(ttl)
        self.journal_dir = (
            os.path.abspath(os.path.expanduser(journal_dir)) if journal_dir else None
        )
        self._clock = clock
        self._manifest_every = MANIFEST_EVERY if manifest_every is None else int(manifest_every)
        self._lock = threading.Lock()
        self._epoch = 0
        self._seq = 0
        self._members = {}    # eid -> {"job","task","joined_at","renewed_at","beat","state"}
        self._roles = {}      # eid -> [job, task_index]
        self._blacklist = {}  # eid -> reason
        self._target_size = None  # the ladder's journaled plan size
        self._fenced = False
        self._records_since_manifest = 0
        self._manifest_stat = None  # (mtime_ns, size) last seen — cheap fence probe
        if self.journal_dir:
            os.makedirs(self.journal_dir, exist_ok=True)
        self._publish_gauges()

    # -- public read surface -------------------------------------------------

    @property
    def epoch(self):
        with self._lock:
            return self._epoch

    @property
    def target_size(self):
        """The executor count the last generation was planned at (journaled
        with the epoch record, so a restarted driver knows whether the
        ladder had shrunk — and how far regrow has to go). None until a
        generation declares one."""
        with self._lock:
            return self._target_size

    def members(self):
        """eid -> member record (copy), every state included."""
        with self._lock:
            return {eid: dict(m) for eid, m in self._members.items()}

    def live_members(self):
        """eids holding a live (unexpired, unreleased) lease, sorted."""
        with self._lock:
            return sorted(e for e, m in self._members.items() if m["state"] == "live")

    def leases_active(self):
        with self._lock:
            return sum(1 for m in self._members.values() if m["state"] == "live")

    def roles(self):
        """eid -> (job_name, task_index) for every assigned role."""
        with self._lock:
            return {eid: tuple(r) for eid, r in self._roles.items()}

    def role_map(self):
        """``"job:task_index"`` -> eid — the shape ``elastic.classify_failure``
        attributes watchdog messages with."""
        with self._lock:
            return {"{}:{}".format(j, t): eid for eid, (j, t) in self._roles.items()}

    def blacklisted(self):
        with self._lock:
            return sorted(self._blacklist)

    def is_blacklisted(self, executor_id):
        with self._lock:
            return executor_id in self._blacklist

    def lease_age(self, executor_id):
        """Seconds since the member's lease was last renewed, or None."""
        with self._lock:
            m = self._members.get(executor_id)
            return None if m is None else self._clock() - m["renewed_at"]

    # -- transitions ---------------------------------------------------------

    def begin_generation(self, template=None, reason="launch", target_size=None):
        """Open a new cluster generation: epoch += 1, membership cleared,
        roles set from ``template`` (eid -> (job, task_index)). Called once
        per ``TFCluster.run`` attempt — a relaunch is a new generation, and
        the epoch gap is what fences any stale writer from the old one.

        ``target_size`` journals the executor count this generation was
        planned at (defaults to the template size), making the ladder's
        shrink/regrow position durable across a driver restart."""
        with self._lock:
            self._epoch += 1
            self._members = {}
            if template is not None:
                self._roles = {eid: [j, t] for eid, (j, t) in template.items()}
            if target_size is not None:
                self._target_size = int(target_size)
            elif template is not None:
                self._target_size = len(template)
            rec = {"op": "epoch", "epoch": self._epoch, "reason": reason,
                   "roles": {str(e): list(r) for e, r in self._roles.items()}}
            if self._target_size is not None:
                rec["target"] = self._target_size
            self._journal_locked(rec)
            epoch = self._epoch
        self._publish_gauges()
        logger.info("registry: generation epoch=%d (%s)", epoch, reason)
        return epoch

    def assign_role(self, executor_id, job_name, task_index):
        with self._lock:
            self._roles[executor_id] = [job_name, int(task_index)]
            self._journal_locked(
                {"op": "role", "eid": executor_id, "job": job_name, "task": int(task_index)}
            )

    def join(self, executor_id, job_name=None, task_index=None, meta=None):
        """Grant (or idempotently refresh) a membership lease. REG retries
        and driver-side re-adoption both land here, so join must be safe to
        repeat."""
        meta = meta or {}
        job = job_name if job_name is not None else meta.get("job_name")
        task = task_index if task_index is not None else meta.get("task_index")
        with self._lock:
            now = self._clock()
            m = self._members.get(executor_id)
            if m is None:
                m = self._members[executor_id] = {
                    "job": job, "task": task, "joined_at": now,
                    "renewed_at": now, "journaled_at": now, "beat": None,
                    "state": "live",
                }
            else:
                m["state"] = "live"
                m["renewed_at"] = now
                if job is not None:
                    m["job"], m["task"] = job, task
            if job is not None:
                self._roles[executor_id] = [job, int(task or 0)]
            self._journal_locked(
                {"op": "join", "eid": executor_id, "job": job,
                 "task": task, "t": now}
            )
        self._publish_gauges()

    def renew(self, executor_id, beat=None):
        """Renew a lease from an observed heartbeat. Returns True when the
        lease actually renewed — i.e. the beat *advanced* (or no beat value
        is used). Re-reading a dead child's frozen counter renews nothing."""
        if chaos.active:
            chaos.delay("control.lease_delay")
        renewed = False
        with self._lock:
            m = self._members.get(executor_id)
            if m is None or m["state"] == "left":
                return False
            if beat is not None and m["beat"] == beat:
                return False
            now = self._clock()
            first_beat = m["beat"] is None and beat is not None
            m["renewed_at"] = now
            if beat is not None:
                m["beat"] = beat
            if m["state"] == "expired":
                # the node came back (long flap): re-adopt rather than
                # insist on the funeral
                m["state"] = "live"
            renewed = True
            # coalesce renew journaling: one durable record per ttl/4 per
            # member bounds journal growth without aging recovered leases by
            # more than a quarter TTL. The FIRST beat is always journaled —
            # it flips the member from expiry-exempt to expirable, and a
            # recovered driver must not grant infinite grace to a lease that
            # had already started beating
            if first_beat or now - m.get("journaled_at", 0.0) >= self.ttl / 4.0:
                m["journaled_at"] = now
                self._journal_locked(
                    {"op": "renew", "eid": executor_id, "beat": m["beat"], "t": now}
                )
        if renewed:
            self._publish_gauges()
        return renewed

    def leave(self, executor_id, reason="done"):
        """Release a lease cleanly (final child_status observed)."""
        changed = False
        with self._lock:
            m = self._members.get(executor_id)
            if m is not None and m["state"] != "left":
                m["state"] = "left"
                changed = True
                self._journal_locked(
                    {"op": "leave", "eid": executor_id, "reason": str(reason)}
                )
        if changed:
            self._publish_gauges()

    def expire_stale(self):
        """Expire every live lease whose last renewal is older than the TTL.
        Returns ``[(executor_id, age_seconds), ...]`` for the newly expired.

        Members that never produced a beat are exempt: their child may still
        be importing its interpreter, and flagging slow startup is the
        launch timeout's job (historical watchdog parity)."""
        expired = []
        with self._lock:
            now = self._clock()
            for eid, m in self._members.items():
                if m["state"] != "live" or m["beat"] is None:
                    continue
                age = now - m["renewed_at"]
                if age > self.ttl:
                    m["state"] = "expired"
                    expired.append((eid, age))
            for eid, age in expired:
                try:
                    self._journal_locked(
                        {"op": "expire", "eid": eid, "t": now, "age": age}
                    )
                except StaleEpochError:
                    raise
                except Exception as e:
                    # journal durability failed (disk full, unwritable dir):
                    # the in-memory expiry stands and is still RETURNED —
                    # failure detection must not depend on the disk. A later
                    # recovery re-derives the expiry from the lease age.
                    logger.warning(
                        "registry: could not journal expiry of %s: %s", eid, e
                    )
                    break
        if expired:
            obs.counter(
                "registry_lease_expirations_total",
                help="membership leases expired without a heartbeat renewal",
            ).inc(len(expired))
            self._publish_gauges()
        return expired

    def blacklist(self, executor_id, reason=""):
        with self._lock:
            if executor_id in self._blacklist:
                return
            self._blacklist[executor_id] = str(reason)
            self._journal_locked(
                {"op": "blacklist", "eid": executor_id, "reason": str(reason)}
            )

    def forgive(self, executor_id):
        """Remove an executor from the blacklist (the regrow path)."""
        with self._lock:
            if executor_id not in self._blacklist:
                return
            self._blacklist.pop(executor_id)
            self._journal_locked({"op": "forgive", "eid": executor_id})

    def crash(self):
        """Simulate the driver dying mid-flight (``control.driver_crash``):
        drop the in-memory state with NO parting commit — a crash does not
        say goodbye — and fence this instance against further writes."""
        with self._lock:
            self._fenced = True
            self._members = {}

    # -- journal / manifest machinery ---------------------------------------

    def _journal_locked(self, record):
        """Append one transition to the journal (caller holds the lock) and
        compact into a manifest every ``manifest_every`` records. In-memory
        state was already mutated by the caller; with no journal_dir this
        degrades to bookkeeping only."""
        self._seq += 1
        record["seq"] = self._seq
        if self.journal_dir is None:
            return
        self._check_fence_locked()
        payload = json.dumps(record, sort_keys=True)
        if chaos.active:
            spec = chaos.fire("control.journal_tear")
            if spec is not None and spec.get("target") == "journal":
                # simulated crash mid-append: half a line, no newline, and
                # this writer stops journaling (it "died")
                with open(os.path.join(self.journal_dir, JOURNAL_NAME), "a") as f:
                    f.write(self._frame(payload)[: max(1, len(payload) // 2)])
                self._fenced = True
                return
            if spec is not None:
                # tear the *manifest* publish instead: force a compaction
                # that dies mid-rename (see _commit_manifest_locked)
                self._commit_manifest_locked(tear=True)
                return
        jpath = os.path.join(self.journal_dir, JOURNAL_NAME)
        creating = not os.path.exists(jpath)
        with open(jpath, "a") as f:
            f.write(self._frame(payload))
            if record["op"] in _DURABLE_OPS:
                f.flush()
                os.fsync(f.fileno())
                obs.counter(
                    "registry_journal_commits_total",
                    help="durable membership journal/manifest commits",
                ).inc()
        if creating:
            # the first append materializes journal.log itself; without a
            # directory fsync a power cut can lose the file while the writer
            # believed its fsynced records were safe
            durable.fsync_dir(self.journal_dir)
        self._records_since_manifest += 1
        if self._records_since_manifest >= self._manifest_every or record["op"] == "epoch":
            self._commit_manifest_locked()

    @staticmethod
    def _frame(payload):
        """One journal line: crc32-of-payload, space, payload, newline."""
        return "{:08x} {}\n".format(zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF, payload)

    def _state_locked(self):
        return {
            "epoch": self._epoch,
            "seq": self._seq,
            "ttl": self.ttl,
            "target_size": self._target_size,
            "members": {str(e): dict(m) for e, m in self._members.items()},
            "roles": {str(e): list(r) for e, r in self._roles.items()},
            "blacklist": {str(e): r for e, r in self._blacklist.items()},
        }

    def _commit_manifest_locked(self, tear=False):
        """Compact state into ``REGISTRY.json`` with the ckpt manifest
        discipline: previous manifest retained as ``.prev``, new manifest
        written tmp+fsync+rename, journal truncated only AFTER the rename
        lands. ``tear=True`` (chaos) aborts mid-publish: a half-written
        manifest over the final name, journal untouched — recovery must
        detect the CRC mismatch and fall back to prev + journal."""
        self._check_fence_locked()
        state = self._state_locked()
        body = json.dumps(state, sort_keys=True)
        payload = {
            "version": VERSION,
            "crc32": zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF,
            "state": state,
        }
        mpath = os.path.join(self.journal_dir, MANIFEST_NAME)
        if os.path.exists(mpath):
            os.replace(mpath, os.path.join(self.journal_dir, PREV_MANIFEST_NAME))
        text = json.dumps(payload, sort_keys=True)
        if tear:
            with open(mpath, "w") as f:
                f.write(text[: len(text) // 2])
            logger.warning("chaos: control.journal_tear — manifest left torn on disk")
            return
        tmp = mpath + ".tmp"
        with open(tmp, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.rename(tmp, mpath)
        # make the rename itself durable before the truncation below can be:
        # otherwise a power loss may persist an empty journal next to the
        # OLD manifest, silently losing the folded-in transitions
        durable.fsync_dir(self.journal_dir)
        try:
            self._manifest_stat = self._stat_manifest()
        except OSError:
            self._manifest_stat = None
        # the manifest now owns everything up to seq: restart the journal
        with open(os.path.join(self.journal_dir, JOURNAL_NAME), "w"):
            pass
        self._records_since_manifest = 0
        obs.counter(
            "registry_journal_commits_total",
            help="durable membership journal/manifest commits",
        ).inc()

    def _stat_manifest(self):
        st = os.stat(os.path.join(self.journal_dir, MANIFEST_NAME))
        return (st.st_mtime_ns, st.st_size)

    def _check_fence_locked(self):
        """Refuse durable writes once a newer driver generation owns the
        journal. Cheap: one stat per append, a manifest read only when the
        file actually changed under us."""
        if self._fenced:
            raise StaleEpochError(
                "registry writer fenced: epoch {} is no longer current".format(self._epoch)
            )
        try:
            st = self._stat_manifest()
        except OSError:
            return  # no manifest yet: nothing to be stale against
        if st == self._manifest_stat:
            return
        self._manifest_stat = st
        payload, _reason = _read_manifest_file(
            os.path.join(self.journal_dir, MANIFEST_NAME)
        )
        if payload is not None and payload["state"].get("epoch", 0) > self._epoch:
            self._fenced = True
            raise StaleEpochError(
                "registry journal taken over by epoch {} (this writer is epoch {})".format(
                    payload["state"]["epoch"], self._epoch
                )
            )

    # -- recovery ------------------------------------------------------------

    @classmethod
    def recover(cls, journal_dir, ttl=None, clock=time.time, fallback_epoch=0,
                manifest_every=None):
        """Reconstruct the registry after a driver restart.

        Reads the committed manifest (falling back to the previous one when
        the newest is torn — CRC mismatch), replays journal records with
        ``seq`` beyond the manifest, then re-adopts every member whose lease
        is still inside its TTL on the wall clock: those executors keep
        training through the outage. Members past their TTL come back in
        ``expired`` state and surface through the watchdog as
        ``lease_expired``. The recovered registry resumes at
        ``max(journaled epoch, fallback_epoch) + 1`` and immediately commits
        a manifest at that epoch — the fencing record that stops any
        still-running pre-crash writer.
        """
        reg = cls(ttl=ttl, journal_dir=journal_dir, clock=clock,
                  manifest_every=manifest_every)
        state = _load_state(journal_dir) if journal_dir else None
        readopted, expired_on_recover = [], []
        with reg._lock:
            if state is not None:
                reg._seq = int(state.get("seq", 0))
                reg._roles = {int(e): list(r) for e, r in (state.get("roles") or {}).items()}
                reg._blacklist = {int(e): r for e, r in (state.get("blacklist") or {}).items()}
                now = reg._clock()
                for eid_s, m in (state.get("members") or {}).items():
                    eid = int(eid_s)
                    m = dict(m)
                    if m.get("state") == "live":
                        age = now - m.get("renewed_at", 0.0)
                        if m.get("beat") is not None and age > reg.ttl:
                            m["state"] = "expired"
                            expired_on_recover.append(eid)
                        else:
                            readopted.append(eid)
                    reg._members[eid] = m
                reg._epoch = max(int(state.get("epoch", 0)), fallback_epoch) + 1
                if state.get("target_size") is not None:
                    reg._target_size = int(state["target_size"])
            else:
                reg._epoch = fallback_epoch + 1
            restart_rec = {
                "op": "epoch", "epoch": reg._epoch, "reason": "driver-restart",
                "roles": {str(e): list(r) for e, r in reg._roles.items()},
            }
            if reg._target_size is not None:
                restart_rec["target"] = reg._target_size
            reg._journal_locked(restart_rec)
            if reg.journal_dir is not None:
                reg._commit_manifest_locked()  # the fencing record
        if expired_on_recover:
            obs.counter(
                "registry_lease_expirations_total",
                help="membership leases expired without a heartbeat renewal",
            ).inc(len(expired_on_recover))
        reg._publish_gauges()
        logger.info(
            "registry recovered: epoch=%d re-adopted=%s expired=%s blacklist=%s",
            reg.epoch, readopted, expired_on_recover, reg.blacklisted(),
        )
        return reg

    # -- metrics -------------------------------------------------------------

    def _publish_gauges(self):
        obs.gauge(
            "registry_leases_active", help="members holding a live lease"
        ).set(self.leases_active())
        obs.gauge(
            "registry_epoch", help="current cluster membership epoch"
        ).set(self.epoch)

    def __repr__(self):
        return "MembershipRegistry(epoch={}, live={}, blacklist={}, journal={})".format(
            self.epoch, self.live_members(), self.blacklisted(), self.journal_dir
        )


def _read_manifest_file(path):
    """(payload, reason): payload is the parsed, CRC-verified manifest dict
    or None; reason explains a None."""
    if not os.path.isfile(path):
        return None, "absent"
    try:
        with open(path) as f:
            payload = json.load(f)
    except (ValueError, OSError) as e:
        return None, "torn manifest ({})".format(e)
    state = payload.get("state")
    if not isinstance(state, dict):
        return None, "torn manifest (no state)"
    body = json.dumps(state, sort_keys=True)
    if zlib.crc32(body.encode("utf-8")) & 0xFFFFFFFF != payload.get("crc32"):
        return None, "checksum mismatch"
    return payload, "verified"


def _load_state(journal_dir):
    """Committed state + journal replay; None when nothing recoverable.

    The newest manifest is CRC-verified; a torn one falls back to the
    retained previous manifest (journal records since then are still on
    disk — truncation only follows a *successful* publish). Journal lines
    are CRC-framed; replay stops at the first torn/corrupt line (everything
    after a tear is from a writer that should have been dead)."""
    journal_dir = os.path.abspath(os.path.expanduser(journal_dir))
    state = None
    for name in (MANIFEST_NAME, PREV_MANIFEST_NAME):
        payload, reason = _read_manifest_file(os.path.join(journal_dir, name))
        if payload is not None:
            state = payload["state"]
            if name == PREV_MANIFEST_NAME:
                logger.warning(
                    "registry: newest manifest unusable; recovered from %s", name
                )
            break
        if name == MANIFEST_NAME and reason != "absent":
            logger.warning("registry: %s %s; trying previous manifest", MANIFEST_NAME, reason)
    if state is None:
        state = {"epoch": 0, "seq": 0, "members": {}, "roles": {}, "blacklist": {}}
    jpath = os.path.join(journal_dir, JOURNAL_NAME)
    if not os.path.isfile(jpath):
        return state
    applied = 0
    with open(jpath, "r", errors="replace") as f:
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            crc_hex, _, payload = line.partition(" ")
            try:
                ok = int(crc_hex, 16) == zlib.crc32(payload.encode("utf-8")) & 0xFFFFFFFF
            except ValueError:
                ok = False
            if not ok:
                logger.warning("registry: torn journal line after %d replayed; stopping", applied)
                break
            try:
                record = json.loads(payload)
            except ValueError:
                logger.warning("registry: corrupt journal record after %d replayed; stopping", applied)
                break
            if record.get("seq", 0) <= state.get("seq", 0):
                continue  # already folded into the manifest
            _apply_record(state, record)
            state["seq"] = record["seq"]
            applied += 1
    if applied:
        logger.info("registry: replayed %d journal record(s) past the manifest", applied)
    return state


def _apply_record(state, record):
    """Fold one journal record into a manifest-shaped state dict."""
    op = record.get("op")
    members = state.setdefault("members", {})
    eid = str(record.get("eid"))
    if op == "epoch":
        state["epoch"] = record.get("epoch", state.get("epoch", 0))
        if record.get("roles"):
            state["roles"] = dict(record["roles"])
        if record.get("target") is not None:
            state["target_size"] = record["target"]
        state["members"] = {}
    elif op == "role":
        state.setdefault("roles", {})[eid] = [record.get("job"), record.get("task", 0)]
    elif op == "join":
        t = record.get("t", 0.0)
        m = members.get(eid) or {"joined_at": t, "beat": None}
        m.update({
            "job": record.get("job"), "task": record.get("task"),
            "renewed_at": t, "journaled_at": t, "state": "live",
        })
        members[eid] = m
        if record.get("job") is not None:
            state.setdefault("roles", {})[eid] = [record["job"], record.get("task") or 0]
    elif op == "renew":
        m = members.get(eid)
        if m is not None:
            m["renewed_at"] = record.get("t", m.get("renewed_at", 0.0))
            m["journaled_at"] = m["renewed_at"]
            m["beat"] = record.get("beat")
            if m.get("state") == "expired":
                m["state"] = "live"
    elif op == "expire":
        m = members.get(eid)
        if m is not None:
            m["state"] = "expired"
    elif op == "leave":
        m = members.get(eid)
        if m is not None:
            m["state"] = "left"
    elif op == "blacklist":
        state.setdefault("blacklist", {})[eid] = record.get("reason", "")
    elif op == "forgive":
        state.setdefault("blacklist", {}).pop(eid, None)
    # unknown ops from a newer writer are skipped: forward-compatible replay


# ---------------------------------------------------------------------------
# executor-side heartbeat aggregation
# ---------------------------------------------------------------------------


class HeartbeatAggregator:
    """Daemon thread run by an elected aggregator executor: polls its group
    members' channels every window and publishes one summary on its OWN
    channel under :data:`WINDOW_KEY`::

        {"window": n, "ts": wall, "beats": {"<eid>": beat},
         "status": {"<eid>": child_status}, "errors": [eid, ...]}

    ``errors`` flags members with a non-empty error queue — the driver then
    fetches the traceback from exactly those nodes, keeping the steady-state
    fan-in at the aggregator count. Dies quietly when its own channel goes
    away (the executor is being torn down), mirroring the heartbeat thread.
    """

    def __init__(self, mgr, member_rows, authkey, window_secs=None, obs_enabled=True):
        self._mgr = mgr
        self._rows = [dict(r) for r in member_rows]
        self._authkey = authkey
        self._window = WINDOW_SECS if window_secs is None else float(window_secs)
        self._obs_enabled = bool(obs_enabled)
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(
            target=self._run, name="tos-heartbeat-agg", daemon=True
        )
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            # bounded: the poll loop re-checks the stop event every window
            self._thread.join(timeout=self._window + 5.0)
            self._thread = None

    def _run(self):
        from tensorflowonspark_tpu import TFManager

        # private registry: the executor process outlives the cluster run,
        # and this lane must not double-count the process-global registry
        reg = obs_registry.Registry(enabled=self._obs_enabled)
        windows = reg.counter(
            "heartbeat_agg_windows_total",
            help="per-window heartbeat summaries published by aggregators",
        )
        channels = {}
        own_failures = 0
        ticker = resilience.Ticker(self._window, jitter=0.2, seed=os.getpid())
        for n in ticker.ticks():
            if self._stop.is_set():
                return
            beats, status, errors = {}, {}, []
            for row in self._rows:
                eid = row["executor_id"]
                try:
                    mgr = channels.get(eid)
                    if mgr is None:
                        mgr = channels[eid] = TFManager.connect(
                            tuple(row["manager_addr"]), self._authkey
                        )
                    st = mgr.get("child_status")
                    if st is not None:
                        status[str(eid)] = st
                    beat = mgr.get("heartbeat")
                    if beat is not None:
                        beats[str(eid)] = beat
                    if not mgr.get_queue("error").empty():
                        errors.append(eid)
                except Exception:
                    channels.pop(eid, None)  # reconnect next window
            summary = json.dumps(
                {"window": n, "ts": time.time(), "beats": beats,
                 "status": status, "errors": errors}
            )
            if self._stop.is_set():
                return  # stopped mid-gather: a replacement owns WINDOW_KEY now
            try:
                self._mgr.set(WINDOW_KEY, summary)
                windows.inc()
                obs_aggregate.publish_to_channel(self._mgr, reg, key=AGGREGATOR_KEY)
                if self._mgr.get("state") == "stopped":
                    return  # node retired: stop summarizing
                own_failures = 0
            except Exception:
                own_failures += 1
                if own_failures >= 5:
                    return  # own channel stayed dead: executor going away
