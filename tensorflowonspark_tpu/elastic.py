"""Recovery supervisor: failure classification, blacklisting, shrink-to-fit.

:func:`TFCluster.run_with_recovery` closes the detect → abort → relaunch loop,
but a bare retry counter relaunches at **full size** every time: if an executor
is permanently gone (TPU host preempted, bad device, full disk), every attempt
re-reserves the same dead capacity and burns the whole budget failing
identically. This module upgrades that loop into a **recovery ladder**:

1. **Classify** — every failed attempt becomes a :class:`FailureEvent` with a
   kind (``launch`` / ``reservation_timeout`` / ``lease_expired`` /
   ``heartbeat_loss`` / ``node_exit`` / ``node_error`` / ``feed_timeout`` /
   ``preemption`` / ``unknown``) and, where the failure text or exception
   chain allows, the
   executor ids it implicates (:func:`classify_failure`). The :class:`FailureLedger` keeps these in a
   sliding window and enforces the restart budget against the *window*, not
   all time — a cluster that fails once a week is healthy; one that fails
   three times in an hour is not.
2. **Gate** — before a relaunch, a short Spark task per candidate executor
   probes scratch-dir writability, TCP loopback, accelerator visibility and
   (when one survives) the manager channel (``TFSparkNode.preflight``).
   Executors failing the probe — and executors the ledger attributes repeated
   losses to — land on a **blacklist** threaded through
   :func:`TFCluster.build_cluster_template` (roles skip them) and
   :class:`reservation.Server` (late registrations from them are refused).
3. **Shrink to fit** — the next attempt runs at ``num_executors − len(blacklist)``
   (never below ``min_workers`` training participants — the ladder raises
   instead). ``map_fun`` restores the latest checkpoint onto the smaller mesh
   via ``ckpt.reshard_restore`` (PR 6 proved bitwise-correct cross-mesh
   restore), so training *continues* instead of dying. With ``regrow=True``
   blacklisted executors are re-probed at every relaunch — a checkpoint
   boundary by construction — and forgiven when they pass, growing the
   cluster back toward full size.
4. **Regrow mid-run** — shrink-to-fit alone ratchets downward: once the
   cluster is small, nothing restores it until the *next* failure. With
   ``regrow_check_secs > 0`` the ladder also re-probes the condemned
   executors *while the shrunk attempt trains*; when enough come back
   healthy that the :class:`~tensorflowonspark_tpu.control.ClusterScaler`
   (patience-gated, stall-classified — never steal capacity from an
   input-bound run) votes to grow, the driver posts a **preemption
   warning** (:meth:`TFCluster.TFCluster.preempt`). Workers drain their
   async checkpoints, commit a ``preempted`` parting status into the
   membership registry and exit clean — a deliberate restart at a
   checkpoint boundary — and the ladder's normal classify → forgive →
   relaunch machinery resumes onto the larger mesh. A ``preemption``
   failure is *warned* downsizing, not pathology: it never blacklists and
   never consumes the restart budget (:data:`BUDGET_EXEMPT_KINDS`). The
   same classification covers platform preemption notices (the jax child's
   SIGTERM handler runs the identical drain), so a preempted-then-returning
   executor rejoins without a ledger entry. The planned size is journaled
   per generation (``MembershipRegistry.begin_generation(target_size=…)``)
   so the ladder's position on the shrink/regrow ladder survives a driver
   restart.

Driver-side metrics (all visible in ``TFCluster.metrics()``):
``recovery_attempts_total``, ``recovery_shrinks_total``,
``recovery_regrows_total``, ``preemptions_drained_total``,
``recovery_seconds_total`` (wall time spent between failure detection and
relaunch decision), and the ``executors_blacklisted`` gauge.
"""

import logging
import re
import time

from tensorflowonspark_tpu import TFCluster, TFSparkNode, control, obs, reservation
from tensorflowonspark_tpu import registry as membership
from tensorflowonspark_tpu.obs import flight as obs_flight
from tensorflowonspark_tpu.obs import tracing as obs_tracing

logger = logging.getLogger(__name__)

#: failure kinds that implicate a *node* (vs. the control plane or the feed):
#: only these count toward an executor's blacklist score
LOSS_KINDS = frozenset(
    {"heartbeat_loss", "lease_expired", "node_exit", "reservation_timeout"}
)

#: failure kinds that never consume the restart budget: a *warned* loss — the
#: node drained its checkpoints and committed a parting status before exiting
#: — is planned downsizing (platform preemption notice, or the ladder's own
#: regrow restart), not pathology. Only unwarned failures should be able to
#: exhaust ``max_restarts``.
BUDGET_EXEMPT_KINDS = frozenset({"preemption"})

_NODE_RE = re.compile(r"node (\w+):(\d+)")
_EXIT_RE = re.compile(r"failed \(exit (-?\d+)\)")
#: the registry watchdog stamps the executor id directly into the message —
#: attribution without a role_map round-trip
_EXEC_RE = re.compile(r"\(executor (\d+)\)")


class FailureEvent:
    """One classified attempt failure.

    ``kind`` is the failure signature; ``executor_ids`` the executors the
    evidence implicates (may be empty — not every failure is attributable);
    ``message`` the original failure text.
    """

    def __init__(self, kind, executor_ids=(), message=""):
        self.kind = kind
        self.executor_ids = sorted(set(executor_ids))
        self.message = str(message)

    def __repr__(self):
        return "FailureEvent(kind={!r}, executor_ids={})".format(
            self.kind, self.executor_ids
        )


def classify_failure(exc, role_map=None):
    """Classify an attempt failure into a :class:`FailureEvent`.

    Walks the exception chain (``__cause__``/``__context__``) because the
    interesting evidence is often wrapped: a ``reservation.ReservationError``
    carrying ``missing`` executor ids inside a launch ``RuntimeError``, or a
    backend ``TaskError`` carrying ``executor_id`` under the task-failure
    wrapper. ``role_map`` maps ``"job:task_index"`` to executor id so
    watchdog messages ("node worker:1 stopped heartbeating") attribute too.
    """
    role_map = role_map or {}
    chain, seen = [], set()
    e = exc
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        chain.append(e)
        e = e.__cause__ or e.__context__
    text = "\n".join(str(c) for c in chain)

    executor_ids = set()
    missing = []
    for c in chain:
        m = getattr(c, "missing", None)  # reservation.ReservationError
        if m:
            missing = list(m)
        eid = getattr(c, "executor_id", None)  # backends TaskError
        if eid is not None:
            executor_ids.add(eid)
    for job, task in _NODE_RE.findall(text):
        key = "{}:{}".format(job, task)
        if key in role_map:
            executor_ids.add(role_map[key])
    for eid in _EXEC_RE.findall(text):
        executor_ids.add(int(eid))

    if missing or any(isinstance(c, reservation.ReservationError) for c in chain):
        return FailureEvent("reservation_timeout", executor_ids | set(missing), text)
    if "preempted" in text:
        # the child's preemption drain commits a ``preempted`` parting status
        # before exiting, and the watchdog stamps it into the failure text;
        # checked before the lease/heartbeat phrasings because a drained
        # child's exit can surface alongside a late expiry message — the
        # warned signal wins
        return FailureEvent("preemption", executor_ids, text)
    if "lease expired" in text:
        # the registry watchdog's first-class expiry event; checked before
        # the legacy phrasing because its messages contain both
        return FailureEvent("lease_expired", executor_ids, text)
    if "stopped heartbeating" in text:
        return FailureEvent("heartbeat_loss", executor_ids, text)
    if "feed timeout" in text:
        return FailureEvent("feed_timeout", executor_ids, text)
    exit_match = _EXIT_RE.search(text)
    if exit_match:
        # negative exit = killed by signal (SIGKILL/OOM) = the node went away;
        # a positive exit is the user fn failing, which no blacklist fixes
        kind = "node_exit" if int(exit_match.group(1)) < 0 else "node_error"
        return FailureEvent(kind, executor_ids, text)
    if "failed:" in text:  # error-queue traceback via the watchdog/shutdown
        return FailureEvent("node_error", executor_ids, text)
    if executor_ids:  # a TaskError with no recognizable inner signature
        return FailureEvent("launch", executor_ids, text)
    return FailureEvent("unknown", executor_ids, text)


class FailureLedger:
    """Sliding-window record of attempt failures driving the ladder.

    * ``allow_restart()`` — True while the failures inside ``window_secs``
      stay within ``max_restarts`` (the old all-time counter is the special
      case ``window_secs=inf``). *Warned* failures
      (:data:`BUDGET_EXEMPT_KINDS`) are recorded — they still show up in
      ``events()`` and the trace — but never consume the budget.
    * ``suspects()`` — executor ids implicated in at least
      ``blacklist_after`` *loss-kind* failures (:data:`LOSS_KINDS`) inside
      the window. One transient fault never blacklists a node; repeated
      attributed losses do.
    * ``clear(eid)`` — forgive an executor (regrow passed its preflight).

    ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, max_restarts=2, window_secs=3600.0, blacklist_after=2,
                 clock=time.monotonic):
        self.max_restarts = max_restarts
        self.window_secs = float(window_secs)
        self.blacklist_after = blacklist_after
        self._clock = clock
        self._events = []  # (t, FailureEvent), pruned lazily

    def record(self, event):
        self._events.append((self._clock(), event))
        return event

    def _recent(self):
        cutoff = self._clock() - self.window_secs
        self._events = [(t, e) for t, e in self._events if t >= cutoff]
        return self._events

    def failures_in_window(self):
        """Budget-relevant failures inside the window: warned kinds
        (:data:`BUDGET_EXEMPT_KINDS`) drained cleanly and do not count."""
        return sum(
            1 for _, e in self._recent() if e.kind not in BUDGET_EXEMPT_KINDS
        )

    def allow_restart(self):
        return self.failures_in_window() <= self.max_restarts

    def suspects(self):
        """Executor ids with >= ``blacklist_after`` loss-kind failures in
        the window, sorted."""
        counts = {}
        for _, event in self._recent():
            if event.kind not in LOSS_KINDS:
                continue
            for eid in event.executor_ids:
                counts[eid] = counts.get(eid, 0) + 1
        return sorted(e for e, n in counts.items() if n >= self.blacklist_after)

    def clear(self, executor_id):
        """Drop every event implicating ``executor_id`` (and only it) —
        the regrow path's forgiveness after a clean re-probe."""
        self._events = [
            (t, e) for t, e in self._events
            if e.executor_ids != [executor_id]
        ]

    def events(self):
        """The (time, event) pairs currently inside the window."""
        return list(self._recent())


def plan_size(num_executors, blacklist, min_workers=1, overhead=0):
    """Next attempt's executor count: full size minus the blacklist.

    ``overhead`` is the non-training role count (ps/evaluator) so
    ``min_workers`` bounds actual *training participants*. Raises
    ``RuntimeError`` rather than clamping when the surviving capacity cannot
    hold ``min_workers`` — silently training on less capacity than the user's
    floor is worse than failing loudly.
    """
    target = num_executors - len(blacklist)
    if target - overhead < min_workers:
        raise RuntimeError(
            "cannot shrink below min_workers={}: {} executor(s) minus {} "
            "blacklisted leaves {} worker(s)".format(
                min_workers, num_executors, len(blacklist), target - overhead
            )
        )
    return target


def preflight_executors(sc, executor_ids, extra_probe=None):
    """Run the per-executor health gate; returns ``{executor_id: reason}``
    for the executors that failed it.

    Each executor is probed with its own single-partition pinned task so one
    dead executor cannot mask the others' reports (a shared job would abort
    on the first task failure). Requires a backend with executor pinning
    (``sc.PIN_SUPPORTED``) — without it a probe's report cannot be attributed
    to a specific executor, so the gate reports nothing.
    """
    if not getattr(sc, "PIN_SUPPORTED", False):
        logger.info("preflight: backend cannot pin tasks to executors; skipping")
        return {}
    bad = {}
    task = TFSparkNode.preflight(extra_probe=extra_probe)
    for eid in executor_ids:
        try:
            reports = (
                sc.parallelize([eid], 1, pin_to_executors=[eid])
                .mapPartitions(task)
                .collect()
            )
        except Exception as e:
            bad[eid] = "probe task failed: {}".format(e)
            continue
        report = next((r for r in reports if r.get("executor_id") == eid), None)
        if report is None:
            bad[eid] = "no probe report returned"
        elif not report.get("ok"):
            failing = {
                k: v for k, v in (report.get("checks") or {}).items() if v != "ok"
            }
            bad[eid] = "; ".join(
                "{}={}".format(k, v) for k, v in sorted(failing.items())
            )
    if bad:
        logger.warning("preflight failed for executors %s", sorted(bad))
    return bad


def _counter_value(snapshot, name):
    return ((snapshot.get("counters") or {}).get(name) or {}).get("value", 0.0)


def _regrow_poll(sc, cluster, scaler, blacklist, num_executors, target, extra_probe):
    """One checkpoint-boundary regrow check while a shrunk attempt trains.

    Re-probes the condemned executors; when enough come back healthy that
    the scaler votes to grow — patience-gated, and deferred while the
    cluster-wide stall classification says the run is input-bound (more
    devices would only starve harder) — posts a preemption warning to the
    running workers. They drain their async checkpoints, commit a
    ``preempted`` parting status and exit clean, and the ladder's normal
    classify → forgive → relaunch machinery resumes training on the larger
    mesh. Returns True when a regrow restart was requested.
    """
    healthy = sorted(
        set(blacklist) - set(preflight_executors(sc, sorted(blacklist), extra_probe))
    )
    desired = num_executors - (len(blacklist) - len(healthy))
    try:
        snapshot = cluster.metrics() or {}
    except Exception:
        snapshot = {}
    classification = control.classify_stalls(
        _counter_value(snapshot, "data_producer_read_seconds_total"),
        _counter_value(snapshot, "data_producer_parse_seconds_total"),
        _counter_value(snapshot, "data_producer_emit_seconds_total"),
        _counter_value(snapshot, "data_consumer_wait_seconds_total"),
    )
    allowed = scaler.decide(target, desired, classification)
    if allowed <= target:
        return False
    with obs.span(
        "elastic_regrow", current=target, target=allowed,
        healthy=healthy, classification=classification,
    ):
        reached = cluster.preempt(
            "regrow to {} executor(s): {} recovered".format(allowed, healthy)
        )
        logger.info(
            "regrow: preemption warning posted to executors %s (%d -> %d)",
            reached, target, allowed,
        )
    return True


class ElasticResult:
    """Outcome of a completed :func:`run_ladder` run.

    ``metrics`` is the cluster metrics snapshot captured just before the
    final (successful) shutdown — the only moment both the node counters and
    the driver's recovery counters are simultaneously readable.
    """

    def __init__(self, relaunches, num_executors, blacklist, metrics, events):
        self.relaunches = relaunches
        self.num_executors = num_executors
        self.blacklist = frozenset(blacklist)
        self.metrics = metrics
        self.events = list(events)

    def __repr__(self):
        return "ElasticResult(relaunches={}, num_executors={}, blacklist={})".format(
            self.relaunches, self.num_executors, sorted(self.blacklist)
        )


def run_ladder(
    sc,
    map_fun,
    tf_args,
    num_executors,
    max_relaunches=2,
    min_workers=1,
    blacklist_after=2,
    window_secs=3600.0,
    preflight=True,
    regrow=False,
    regrow_check_secs=0.0,
    scaler=None,
    extra_probe=None,
    poll_secs=1.0,
    shutdown_timeout=600,
    completion_timeout=None,
    feed_fn=None,
    ledger=None,
    **run_kwargs,
):
    """The recovery ladder: run → classify the failure → blacklist → shrink →
    relaunch, until the run completes or the ledger's window budget is spent.

    The attempt/teardown semantics match the historical
    ``run_with_recovery`` loop exactly (TENSORFLOW mode waits for worker
    completion; SPARK mode drives ``feed_fn``; every failed attempt is
    ``abort()``-ed *before* deciding whether to relaunch, so on the final
    failure the caller still gets their executors back, and the raised
    ``RuntimeError`` chains the last underlying failure). What the ladder
    adds on top:

    * ``blacklist_after`` loss-kind failures attributed to one executor
      (see :data:`LOSS_KINDS`) blacklist it; a single transient fault still
      relaunches at full size, preserving the pre-ladder behaviour.
    * candidates for the next attempt are preflight-probed
      (:func:`preflight_executors`); probe failures extend the blacklist
      before the relaunch instead of burning an attempt discovering them.
    * the relaunch runs at ``num_executors − len(blacklist)`` — shrink to
      fit — and raises rather than go below ``min_workers`` training
      participants. ``map_fun`` must restore via ``ckpt.reshard_restore``
      (or ``restore_latest`` when sizes match) to continue the trajectory
      on the smaller mesh.
    * ``regrow=True`` re-probes blacklisted executors at every relaunch
      (a checkpoint boundary by construction); executors that pass are
      forgiven (``ledger.clear``) and rejoin the next attempt.
    * ``regrow_check_secs > 0`` (TENSORFLOW mode, with ``regrow``) also
      re-probes *while a shrunk attempt trains*: every interval the ladder
      probes the condemned executors and asks the ``scaler`` (default: a
      :class:`~tensorflowonspark_tpu.control.ClusterScaler` spanning
      ``min_workers + overhead … num_executors``) whether to grow. A grow
      verdict posts a preemption warning — workers drain checkpoints,
      commit a ``preempted`` parting status and exit clean — and the next
      attempt resumes onto the larger mesh via ``ckpt.reshard_restore``.
      ``preemption`` failures (this path, and real platform SIGTERMs) never
      blacklist and never consume the restart budget.

    ``ledger`` is injectable for tests; by default a fresh
    :class:`FailureLedger` with this call's budget/window. Returns an
    :class:`ElasticResult`.
    """
    mode = run_kwargs.get("input_mode", TFCluster.InputMode.SPARK)
    if mode != TFCluster.InputMode.TENSORFLOW and feed_fn is None:
        raise ValueError(
            "run_ladder in SPARK mode needs feed_fn=<your feed loop>; "
            "without a feed, use input_mode=InputMode.TENSORFLOW"
        )
    if mode == TFCluster.InputMode.TENSORFLOW and feed_fn is not None:
        raise ValueError("feed_fn requires input_mode=InputMode.SPARK")
    if ledger is None:
        ledger = FailureLedger(
            max_restarts=max_relaunches,
            window_secs=window_secs,
            blacklist_after=blacklist_after,
        )
    overhead = run_kwargs.get("num_ps", 0) + (1 if run_kwargs.get("eval_node") else 0)
    # ONE membership registry across every attempt: each relaunch is a new
    # generation under a higher epoch, and the blacklist is journaled so a
    # restarted driver inherits the ladder's condemnations, not just the
    # current attempt's roster
    registry = run_kwargs.pop("registry", None)
    if registry is None:
        registry = membership.MembershipRegistry(
            journal_dir=run_kwargs.pop("registry_dir", None)
        )
    else:
        run_kwargs.pop("registry_dir", None)
    if regrow and regrow_check_secs > 0 and scaler is None:
        scaler = control.ClusterScaler(
            num_executors, min_size=min_workers + overhead
        )
    blacklist = set()
    target = num_executors
    relaunches = 0

    while True:
        template = TFCluster.build_cluster_template(
            target,
            run_kwargs.get("num_ps", 0),
            run_kwargs.get("master_node", "chief"),
            run_kwargs.get("eval_node", False),
            blacklist=blacklist,
        )
        role_map = {
            "{}:{}".format(job, idx): eid for eid, (job, idx) in template.items()
        }
        failure = None
        cluster = None
        try:
            cluster = TFCluster.run(
                sc, map_fun, tf_args, target,
                blacklist=sorted(blacklist) or None, registry=registry,
                **run_kwargs
            )
        except Exception as e:
            failure = e
        if cluster is not None:
            snapshot = None
            try:
                if feed_fn is not None:
                    # SPARK mode: drive the caller's feed; a dead node
                    # surfaces as a feed-task exception (queue timeout) or
                    # as a watchdog error raced past the feed's return
                    feed_fn(cluster)
                    cluster.check_errors()
                else:
                    # wait for training to finish, cutting out early on a
                    # detected node failure (watchdog error-queue peek /
                    # heartbeat loss); NOT a launch-thread join — ps/
                    # evaluator tasks park until shutdown, so the launch
                    # job outlives training by design
                    if scaler is not None and regrow_check_secs > 0 and blacklist:
                        # slice the wait so the ladder can re-probe condemned
                        # executors and regrow mid-run (a requested regrow
                        # surfaces as a ``preempted`` failure below)
                        deadline = (
                            time.monotonic() + completion_timeout
                            if completion_timeout else None
                        )
                        while True:
                            slice_secs = regrow_check_secs
                            if deadline is not None:
                                slice_secs = min(
                                    slice_secs,
                                    max(deadline - time.monotonic(), 0.0),
                                )
                            if cluster.wait_for_completion(
                                poll_secs, timeout=slice_secs
                            ):
                                break
                            if deadline is not None and time.monotonic() >= deadline:
                                break
                            if _regrow_poll(
                                sc, cluster, scaler, blacklist,
                                num_executors, target, extra_probe,
                            ):
                                # drain requested: wait for the parting
                                # statuses to land, then let classification
                                # run the relaunch
                                remaining = (
                                    max(deadline - time.monotonic(), 0.0)
                                    if deadline is not None else None
                                )
                                cluster.wait_for_completion(
                                    poll_secs, timeout=remaining
                                )
                                break
                    else:
                        cluster.wait_for_completion(
                            poll_secs, timeout=completion_timeout
                        )
                if not cluster.tf_status.get("error"):
                    # snapshot BEFORE shutdown: node channels (and with them
                    # the child-side counters) do not survive teardown
                    try:
                        snapshot = cluster.metrics()
                    except Exception:
                        snapshot = None
                cluster.shutdown(timeout=shutdown_timeout)
                return ElasticResult(
                    relaunches, target, blacklist, snapshot, ledger.events()
                )
            except Exception as e:
                failure = e

        # -- the ladder: classify → budget-check → blacklist → shrink ---------
        t0 = time.monotonic()
        event = ledger.record(classify_failure(failure, role_map=role_map))
        # black-box moment: the classified failure goes onto the trace (same
        # trace_id as the killed child's last spans and the watchdog's
        # lease_expired event — mint() is idempotent across relaunches) and
        # the driver's flight shard is flushed before the recovery decision
        obs_tracing.event(
            "failure_classified", kind=event.kind,
            executor_ids=sorted(event.executor_ids), attempt=relaunches + 1,
        )
        obs_flight.dump("failure_classified:{}".format(event.kind))
        obs.counter(
            "recovery_attempts_total", help="failed cluster attempts entering recovery"
        ).inc()
        if event.kind == "preemption":
            # driver-side by necessity: the drained child's own counters die
            # with its generation's channels
            obs.counter(
                "preemptions_drained_total",
                help="preemption warnings that drained checkpoints before exit",
            ).inc(max(1, len(event.executor_ids)))
        relaunches += 1
        # tear the failed attempt down BEFORE deciding whether to relaunch:
        # on the final failure the caller still gets their executors back
        if cluster is not None:
            cluster.abort("attempt {} failed: {}".format(relaunches, failure))
        if not ledger.allow_restart():
            obs.counter(
                "recovery_seconds_total",
                help="wall seconds spent in recovery (failure to relaunch decision)",
            ).inc(time.monotonic() - t0)
            raise RuntimeError(
                "training failed after {} relaunch(es): {}".format(
                    relaunches - 1, failure
                )
            ) from failure

        # the relaunch decision is itself a span: the merged timeline shows
        # kill -> lease_expired -> failure_classified -> elastic_relaunch in
        # causal order on one trace
        with obs.span("elastic_relaunch", attempt=relaunches, kind=event.kind):
            if regrow and blacklist:
                # a relaunch resumes from the latest checkpoint, so this IS the
                # checkpoint boundary: re-probe condemned executors and forgive
                # the ones that come back healthy
                recovered = sorted(
                    blacklist - set(preflight_executors(sc, sorted(blacklist), extra_probe))
                )
                for eid in recovered:
                    blacklist.discard(eid)
                    ledger.clear(eid)
                    registry.forgive(eid)
                if recovered:
                    logger.info("regrow: executors %s passed re-probe; unblacklisted",
                                recovered)
            blacklist.update(ledger.suspects())
            for eid in sorted(blacklist):
                registry.blacklist(eid, reason=event.kind)

            # shrink to surviving capacity, then preflight the actual candidates;
            # gate failures shrink further (and can trip the min_workers floor)
            while True:
                new_target = plan_size(
                    num_executors, blacklist, min_workers=min_workers, overhead=overhead
                )
                candidates = sorted(
                    TFCluster.build_cluster_template(
                        new_target,
                        run_kwargs.get("num_ps", 0),
                        run_kwargs.get("master_node", "chief"),
                        run_kwargs.get("eval_node", False),
                        blacklist=blacklist,
                    )
                )
                if not preflight:
                    break
                bad = preflight_executors(sc, candidates, extra_probe)
                if not bad:
                    break
                for eid, reason in sorted(bad.items()):
                    logger.warning("blacklisting executor %s: %s", eid, reason)
                    registry.blacklist(eid, reason="preflight: {}".format(reason))
                blacklist.update(bad)
            if new_target < target:
                obs.counter(
                    "recovery_shrinks_total",
                    help="relaunches that shrank the cluster to surviving capacity",
                ).inc()
            elif new_target > target:
                obs.counter(
                    "recovery_regrows_total",
                    help="relaunches that grew the cluster back toward full size",
                ).inc()
            obs.gauge(
                "executors_blacklisted", help="executors currently blacklisted"
            ).set(len(blacklist))
            obs.counter(
                "recovery_seconds_total",
                help="wall seconds spent in recovery (failure to relaunch decision)",
            ).inc(time.monotonic() - t0)
            logger.warning(
                "cluster attempt %d failed (%s: %s); relaunching with %d executor(s)%s",
                relaunches, event.kind, failure, new_target,
                " (blacklist: {})".format(sorted(blacklist)) if blacklist else "",
            )
            target = new_target
            if scaler is not None:
                # the relaunch is the scaler's actuation landing: reset its
                # patience streaks so the next verdict starts fresh
                scaler.observe(new_target)
