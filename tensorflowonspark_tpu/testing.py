"""Shared harness utilities for multi-process test/dryrun worlds.

One place for the CPU-world bootstrap used by the test suite and the driver
dryrun (__graft_entry__), so fixes to world wiring (platform forcing, gloo
selection, coordinator addressing) cannot drift between copies.
"""

import os


def join_cpu_world(pid, num_procs, coord_port, local_devices=2):
    """Join a local multi-process jax.distributed world on CPU devices.

    Forces the CPU platform (config-API, see util.force_platform), builds the
    reservation-shaped :class:`~tensorflowonspark_tpu.TFSparkNode.TFNodeContext`
    for process ``pid`` of ``num_procs`` with a loopback coordinator, and
    initializes the distributed runtime (gloo collectives). Returns the ctx;
    after this call ``jax.device_count() == num_procs * local_devices``.
    """
    from tensorflowonspark_tpu import util
    from tensorflowonspark_tpu.TFSparkNode import TFNodeContext

    util.force_platform("cpu", num_cpu_devices=local_devices)
    ctx = TFNodeContext(
        executor_id=pid,
        job_name="worker",
        task_index=pid,
        cluster_spec={"worker": ["localhost"] * num_procs},
        defaultFS="file://",
        working_dir=os.getcwd(),
        coordinator_address="127.0.0.1:{}".format(coord_port),
        num_processes=num_procs,
        process_id=pid,
    )
    ctx.initialize_distributed()
    return ctx
