"""tensorflowonspark_tpu — a TPU-native distributed DL framework with the
capabilities of TensorFlowOnSpark.

A Spark (or Spark-like) application turns its executors into a distributed
deep-learning cluster: the driver reserves TPU hosts, each executor bootstraps a
jax process that joins a global device mesh (ICI within a slice, DCN across
slices via ``jax.distributed``), Spark RDD/DataFrame partitions stream into the
TPU hosts through a local IPC feed plane, and training/inference is expressed as
pjit-compiled SPMD programs over ``jax.sharding.Mesh`` axes (dp/fsdp/tp/sp/ep).

Public module layout intentionally mirrors the reference
(``/root/reference/tensorflowonspark``) so users of TensorFlowOnSpark can switch
with minimal changes, while every implementation is TPU-first:

* :mod:`~tensorflowonspark_tpu.TFCluster` — driver-side cluster lifecycle API.
* :mod:`~tensorflowonspark_tpu.TFSparkNode` — executor-side node runtime.
* :mod:`~tensorflowonspark_tpu.TFNode` — in-``main_fun`` helper API (DataFeed).
* :mod:`~tensorflowonspark_tpu.TFManager` — per-executor IPC manager.
* :mod:`~tensorflowonspark_tpu.reservation` — driver-hosted control plane.
* :mod:`~tensorflowonspark_tpu.tpu_info` — TPU topology discovery (gpu_info analogue).
* :mod:`~tensorflowonspark_tpu.pipeline` — ML-pipeline Estimator/Model layer.
* :mod:`~tensorflowonspark_tpu.dfutil` — TFRecord <-> DataFrame utilities.
* :mod:`~tensorflowonspark_tpu.parallel` — mesh / sharding / collectives / ring attention.
* :mod:`~tensorflowonspark_tpu.train` — pjit training strategies + checkpointing.
* :mod:`~tensorflowonspark_tpu.models` — flax model zoo (mnist, resnet, segmentation, transformer).
* :mod:`~tensorflowonspark_tpu.backends` — Spark and local multi-process execution backends.

Importing this package configures NO logging: applications opt in with
:func:`tensorflowonspark_tpu.util.setup_logging` (examples and bench.py call
it; the jax child process calls it on entry). The format carries
process/thread like the reference (/root/reference/tensorflowonspark/__init__.py:3)
because the runtime spans a driver, N executor processes and N jax child
processes.
"""

__version__ = "0.1.0"
