"""Async checkpointing: snapshot-to-host, background commit, resharded restore.

The SPARK-mode recovery story rests on checkpoints (``run_with_recovery``
resumes a killed worker from its newest one), but the blocking save path
taxes every save against step throughput. This package makes frequent
checkpointing nearly free:

* :mod:`~tensorflowonspark_tpu.ckpt.snapshot` — donation-safe
  snapshot-to-host with pooled double buffers (the training thread pays
  only a D2H copy);
* :mod:`~tensorflowonspark_tpu.ckpt.engine` — a single background writer
  (bounded hand-off, newest snapshot supersedes a queued one) performing
  the orbax sharded write and the atomic manifest-committed publish;
* :mod:`~tensorflowonspark_tpu.ckpt.manifest` — ``MANIFEST.json`` written
  last + rename-published, so ``restore_latest`` cheap-verifies integrity
  instead of attempting restores;
* :mod:`~tensorflowonspark_tpu.ckpt.reshard` — restore a checkpoint saved
  on one mesh onto a different mesh / partition spec (elastic recovery).

Lazy re-exports (PEP 562) keep ``import tensorflowonspark_tpu.ckpt``
jax-free — jax loads only when a snapshot or restore actually runs.
"""

_EXPORTS = {
    "AsyncCheckpointEngine": "engine",
    "in_flight_paths": "engine",
    "drain_all": "engine",
    "busy_descriptions": "engine",
    "TMP_MARKER": "engine",
    "SnapshotBuffers": "snapshot",
    "HostSnapshot": "snapshot",
    "snapshot_to_host": "snapshot",
    "MANIFEST_NAME": "manifest",
    "write_manifest": "manifest",
    "read_manifest": "manifest",
    "verify": "manifest",
    "reshard_restore": "reshard",
    "state_shardings": "reshard",
    "engine": None,
    "snapshot": None,
    "manifest": None,
    "reshard": None,
}


def __getattr__(name):
    import importlib

    if name not in _EXPORTS:
        raise AttributeError(name)
    submodule = _EXPORTS[name] or name
    mod = importlib.import_module("tensorflowonspark_tpu.ckpt." + submodule)
    return mod if _EXPORTS[name] is None else getattr(mod, name)


def __dir__():
    return sorted(_EXPORTS)
