"""Snapshot stage: copy the in-flight TrainState to host buffers.

The first half of the CheckFreq split (Mohan et al., FAST'21): decouple
*snapshot* (device → host, on the training thread, cheap) from *persist*
(host → storage, on the writer thread, slow). The training loop only ever
pays the D2H copy; the orbax write happens behind it.

Donation-safe by construction: the snapshot is a **new host buffer** — it
never aliases device memory, so the device state handed back to the step
loop can be donated into the next step while the writer is still
serializing the copy (the same discipline the packed feed established for
window buffers, ``data/autotune.py``). ``jax.device_get`` on a CPU backend
can return a zero-copy *view* of the device buffer, which would break that
guarantee — the copy below therefore always lands in memory this module
owns.

Buffers are pooled double-buffer style (:class:`SnapshotBuffers`): with at
most one save in flight and at most one pending, two resident slots cover
the steady state, so per-snapshot allocation disappears after warm-up on
fixed-shape states (momentary overflow slots are allocated when both are
held and simply dropped on release).
"""

import logging
import threading
import time

import numpy as np

from tensorflowonspark_tpu import chaos, obs

logger = logging.getLogger(__name__)


class HostSnapshot:
    """One host-resident copy of a state pytree, tagged with its step.

    ``tree`` is the original pytree structure with every leaf replaced by
    an owned numpy array (what the writer hands to orbax); ``nbytes`` is
    the host footprint; ``slot`` is the pool slot backing the leaves (None
    for unpooled snapshots)."""

    __slots__ = ("tree", "step", "nbytes", "slot")

    def __init__(self, tree, step, nbytes, slot=None):
        self.tree = tree
        self.step = step
        self.nbytes = nbytes
        self.slot = slot


class _Slot:
    __slots__ = ("leaves", "signature")

    def __init__(self, leaves, signature):
        self.leaves = leaves
        self.signature = signature


def _leaf_to_host(leaf, out=None):
    """Copy one leaf into owned host memory (into ``out`` when shapes
    match). Returns the owned array."""
    import jax

    host = jax.device_get(leaf)
    arr = np.asarray(host)
    if out is not None:
        np.copyto(out, arr)
        return out
    if arr is leaf or isinstance(leaf, np.ndarray):
        # device_get passed a host array through unchanged — own a copy
        return np.array(arr, copy=True)
    if not arr.flags.owndata or not arr.flags.writeable:
        # zero-copy view of a (CPU) device buffer, or jax's cached assembly
        # of a sharded array (owndata but frozen read-only): either way it
        # cannot serve as a reusable pool buffer — materialize an owned,
        # writable copy
        return np.array(arr, copy=True)
    return arr


def snapshot_to_host(state, step=None, slot=None):
    """Copy ``state`` (device or host pytree) into owned host buffers.

    The barrier-free point: called right after a step returns, the copy
    waits only for *that step's* output arrays, not for any subsequently
    enqueued work. Fires the ``ckpt.snapshot_stall`` chaos site and feeds
    ``ckpt_snapshot_seconds_total`` / ``ckpt_bytes_total``.

    Returns a :class:`HostSnapshot`; pass a pool ``slot`` (from
    :class:`SnapshotBuffers`) to reuse its buffers.
    """
    import jax

    t0 = time.monotonic()
    if chaos.active:
        chaos.delay("ckpt.snapshot_stall")
    leaves, treedef = jax.tree.flatten(state)
    outs = slot.leaves if slot is not None else [None] * len(leaves)
    host_leaves = [_leaf_to_host(leaf, out) for leaf, out in zip(leaves, outs)]
    if slot is not None:
        slot.leaves = host_leaves
    tree = jax.tree.unflatten(treedef, host_leaves)
    nbytes = sum(leaf.nbytes for leaf in host_leaves)
    elapsed = time.monotonic() - t0
    obs.counter(
        "ckpt_snapshot_seconds_total",
        help="seconds the training thread spent snapshotting state to host",
    ).inc(elapsed)
    obs.counter(
        "ckpt_bytes_total", help="bytes of state snapshotted to host buffers"
    ).inc(nbytes)
    return HostSnapshot(tree, step, nbytes, slot=slot)


def _leaf_sig(leaf):
    dtype = getattr(leaf, "dtype", None)
    if dtype is None:  # python scalar leaf
        dtype = np.asarray(leaf).dtype
    return (tuple(getattr(leaf, "shape", np.shape(leaf))), np.dtype(dtype).str)


def _signature(state):
    """(treedef, leaf shapes/dtypes) — computed WITHOUT touching leaf data
    (no device sync) so slot matching is free."""
    import jax

    leaves, treedef = jax.tree.flatten(state)
    return (treedef, tuple(_leaf_sig(l) for l in leaves))


class SnapshotBuffers:
    """Bounded pool of reusable host buffer slots (default depth 2: one
    backing the in-flight write, one for the next pending snapshot).

    ``take`` copies the state into a free slot — or a fresh overflow slot
    when the pool is exhausted or the state's shapes changed — and
    ``release`` returns pooled slots for reuse. Thread-safe: ``take`` runs
    on the training thread while ``release`` runs on the writer thread.
    """

    def __init__(self, depth=2):
        self.depth = depth
        self._lock = threading.Lock()
        self._free = []
        self._resident = 0  # pooled slots in existence (free + held)

    def take(self, state, step=None):
        sig = _signature(state)
        slot = None
        with self._lock:
            for i, cand in enumerate(self._free):
                if cand.signature == sig:
                    slot = self._free.pop(i)
                    break
            if slot is None and self._free and self._resident >= self.depth:
                # free slots exist but none match: the state's shapes
                # changed — evict a stale slot so the pool re-fills with
                # the new signature instead of pinning dead buffers
                self._free.pop(0)
                self._resident -= 1
            if slot is None and self._resident < self.depth:
                slot = _Slot([None] * len(sig[1]), sig)
                self._resident += 1
        # overflow (both slots held, or shape change): unpooled snapshot
        return snapshot_to_host(state, step=step, slot=slot)

    def release(self, snap):
        slot = snap.slot
        if slot is None:
            return
        snap.slot = None
        with self._lock:
            if len(self._free) < self.depth:
                self._free.append(slot)
            else:
                self._resident -= 1
