"""The async checkpoint engine: background writer + atomic commit.

Replaces the blocking save path (``train/checkpoint.py:save_checkpoint``
parks the training loop on ``wait_until_finished()``) with the
CheckFreq/Check-N-Run split: the training thread pays only the
snapshot-to-host copy (:mod:`tensorflowonspark_tpu.ckpt.snapshot`); a
single daemon writer thread performs the orbax sharded write and the
manifest-committed publish in the background.

Queueing discipline — **at most one save in flight, newer supersedes
queued**: the hand-off slot holds at most one pending snapshot; a snapshot
arriving while one is still waiting replaces it (the superseded snapshot's
buffers return to the pool, ``ckpt_superseded_total`` counts the drop).
Checkpoints are *recovery points*, not an archive — when the writer falls
behind, persisting the newest state beats persisting every state, and the
training loop never blocks on storage (Check-N-Run's decoupled-frequency
argument).

Commit protocol (crash-atomic on POSIX rename semantics):

1. shards land in ``tmp.<prefix><step>`` next to the final dir,
2. ``MANIFEST.json`` (per-file sizes + CRC32s) is written last,
3. ``os.rename`` publishes ``<prefix><step>``.

A crash or a ``ckpt.commit_tear`` fault at any point leaves either an
unpublished staging dir — invisible to ``restore_latest`` and swept by the
next commit for the same step — or a fully manifest-described checkpoint.
Pruning runs on the writer thread after each commit and consults the
module-level in-flight registry (:func:`in_flight_paths`), so a prune can
never race the checkpoint another engine is still committing.

Chaos sites: ``ckpt.write_slow`` (writer delay inside the timed region),
``ckpt.commit_tear`` (die between shard write and publish; with
``publish_torn: true`` the checkpoint publishes with a torn manifest
instead, exercising the cheap-verify reject path), plus the pre-existing
``checkpoint.corrupt_write`` (shard bitrot *after* the manifest is
written, so the checksum mismatch is detectable).
"""

import logging
import os
import shutil
import threading
import time
import weakref

from tensorflowonspark_tpu import chaos, durable, obs, resilience
from tensorflowonspark_tpu.ckpt import manifest as _manifest
from tensorflowonspark_tpu.ckpt.snapshot import SnapshotBuffers

logger = logging.getLogger(__name__)

#: staging-dir marker: ``tmp.<prefix><step>``. Never matches the ``ckpt_``
#: checkpoint prefix, so enumeration/restore/prune skip staging dirs by
#: construction.
TMP_MARKER = "tmp."

#: all live engines in this process (weak: an abandoned engine must not be
#: kept alive by the registry)
_engines = weakref.WeakSet()
_engines_lock = threading.Lock()


def in_flight_paths():
    """Final checkpoint paths some engine in this process is currently
    committing — the prune guard (``prune_checkpoints`` must never delete
    a checkpoint mid-commit)."""
    with _engines_lock:
        engines = list(_engines)
    return {p for e in engines for p in e.busy_paths()}


def drain_all(timeout=None):
    """Drain every live engine (pending + in-flight saves complete).
    Called from the node runtime on child exit so a worker never abandons
    a checkpoint it already snapshotted. Returns True when all drained;
    on timeout each stuck engine is named (checkpoint dir + pending step)
    so the operator knows *which* resume point was abandoned."""
    with _engines_lock:
        engines = list(_engines)
    deadline = resilience.Deadline(timeout)
    stuck = []
    for engine in engines:
        if not engine.drain(timeout=deadline.remaining()):
            stuck.append(engine.pending_desc() or repr(engine))
    if stuck:
        logger.warning(
            "checkpoint drain timed out (timeout=%s): %s",
            timeout, "; ".join(stuck),
        )
    return not stuck


def busy_descriptions():
    """Human-readable descriptions of every engine with undrained work
    (checkpoint dir + pending/committing step) — for exit-path logging."""
    with _engines_lock:
        engines = list(_engines)
    return [d for d in (e.pending_desc() for e in engines) if d]


class AsyncCheckpointEngine:
    """Non-blocking checkpointing for a training loop.

    ::

        engine = ckpt.AsyncCheckpointEngine(model_dir, keep=3, save_every_n=100)
        for i, batch in enumerate(batches):
            state, metrics = step(state, batch)
            engine.maybe_save(state, start_step + i + 1)
        engine.close()          # drain-on-exit: final save lands

    ``save`` snapshots synchronously (device → pooled host buffers, the
    only cost on the training thread) and returns immediately; the writer
    thread serializes, commits, and prunes. The engine is also a context
    manager (``with`` = ``close()`` on exit, draining first).

    Writer failures never propagate into the training loop mid-run (a
    storage hiccup must not kill a healthy training job) — they are
    logged, counted (``ckpt_write_failures_total``) and surfaced on
    ``engine.error`` / at :meth:`close`.
    """

    def __init__(self, model_dir, keep=None, save_every_n=0, prefix="ckpt_",
                 buffer_depth=2):
        self.model_dir = os.path.abspath(os.path.expanduser(model_dir))
        self.keep = keep
        self.save_every_n = save_every_n
        self.prefix = prefix
        os.makedirs(self.model_dir, exist_ok=True)
        self._buffers = SnapshotBuffers(depth=buffer_depth)
        self._cond = threading.Condition()
        self._pending = None        # HostSnapshot awaiting the writer
        self._writing = False
        self._in_flight_path = None  # final path of the commit in progress
        self._closed = False
        self._last_error = None
        self._saves_accepted = 0
        self._thread = threading.Thread(
            target=self._run, name="tos-ckpt-writer", daemon=True
        )
        self._thread.start()
        with _engines_lock:
            _engines.add(self)

    # -- training-thread API --------------------------------------------------

    def save(self, state, step):
        """Snapshot ``state`` to host and queue it for background commit.

        Returns after the D2H copy — the device arrays are free to be
        donated into the next step. A snapshot still waiting when the next
        one arrives is superseded (newest wins)."""
        snap = self._buffers.take(state, step=int(step))
        with self._cond:
            if self._closed:
                self._buffers.release(snap)
                raise RuntimeError("AsyncCheckpointEngine is closed")
            if self._pending is not None:
                superseded = self._pending
                self._pending = None
                self._buffers.release(superseded)
                obs.counter(
                    "ckpt_superseded_total",
                    help="queued snapshots replaced by a newer one before "
                         "the writer picked them up",
                ).inc()
                logger.info(
                    "checkpoint snapshot for step %s superseded by step %s",
                    superseded.step, snap.step,
                )
            self._pending = snap
            self._saves_accepted += 1
            self._update_pending_gauge()
            self._cond.notify_all()
        return snap.step

    def maybe_save(self, state, step):
        """The ``save_every_n`` loop hook: save when ``step`` lands on the
        cadence (and the engine has one configured). Returns True when a
        save was queued."""
        if self.save_every_n and step % self.save_every_n == 0:
            self.save(state, step)
            return True
        return False

    def drain(self, timeout=None):
        """Block until the pending and in-flight saves are fully committed
        (or ``timeout`` elapses). Returns True when drained; on timeout the
        warning names this engine (:meth:`pending_desc`)."""
        deadline = resilience.Deadline(timeout)
        with self._cond:
            while self._pending is not None or self._writing:
                if deadline.expired():
                    logger.warning(
                        "checkpoint drain timed out (timeout=%s): %s",
                        timeout, self._pending_desc_locked(),
                    )
                    return False
                self._cond.wait(timeout=deadline.clamp(1.0))
        return True

    def pending_desc(self):
        """``"<model_dir> (pending step N, committing step M)"`` for the
        work still undrained, or None when idle — so drain-timeout messages
        name the engine instead of a bare boolean."""
        with self._cond:
            return self._pending_desc_locked()

    def _pending_desc_locked(self):
        parts = []
        if self._pending is not None:
            parts.append("pending step {}".format(self._pending.step))
        if self._in_flight_path is not None:
            parts.append("committing {}".format(
                os.path.basename(self._in_flight_path)
            ))
        elif self._writing:
            parts.append("committing")
        if not parts:
            return None
        return "{} ({})".format(self.model_dir, ", ".join(parts))

    def close(self, timeout=None):
        """Drain, stop the writer thread, and surface any writer error.
        Idempotent; called by ``with``-exit."""
        drained = self.drain(timeout=timeout)
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=5.0)
        if not drained:
            logger.warning(
                "checkpoint engine %s closed before draining (timeout=%s)",
                self.model_dir, timeout,
            )
        if self._last_error is not None:
            raise self._last_error
        return drained

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None:
            self.close()
        else:
            # error exit: best-effort drain, never mask the original error
            try:
                self.drain(timeout=60)
                with self._cond:
                    self._closed = True
                    self._cond.notify_all()
            except Exception:
                logger.exception("checkpoint drain failed during error exit")
        return False

    # -- introspection --------------------------------------------------------

    @property
    def error(self):
        """The writer's last failure (None = healthy)."""
        with self._cond:
            return self._last_error

    @property
    def saves_accepted(self):
        with self._cond:
            return self._saves_accepted

    def busy_paths(self):
        """Final paths this engine will still write to (pending +
        in-flight) — consumed by :func:`in_flight_paths`."""
        with self._cond:
            paths = set()
            if self._in_flight_path is not None:
                paths.add(self._in_flight_path)
            if self._pending is not None:
                paths.add(self._final_path(self._pending.step))
            return paths

    def _final_path(self, step):
        return os.path.join(self.model_dir, "{}{}".format(self.prefix, step))

    def _update_pending_gauge(self):
        # called under self._cond
        obs.gauge(
            "ckpt_pending",
            help="snapshots accepted but not yet committed (queued + in flight)",
        ).set((1 if self._pending is not None else 0) + (1 if self._writing else 0))

    # -- writer thread --------------------------------------------------------

    def _run(self):
        while True:
            with self._cond:
                while self._pending is None and not self._closed:
                    self._cond.wait()
                if self._pending is None:
                    return  # closed and drained
                snap = self._pending
                self._pending = None
                self._writing = True
                self._in_flight_path = self._final_path(snap.step)
                self._update_pending_gauge()
            try:
                self._write_and_commit(snap)
            except Exception as e:  # storage errors must not kill training
                with self._cond:
                    self._last_error = e
                obs.counter(
                    "ckpt_write_failures_total",
                    help="background checkpoint writes that failed",
                ).inc()
                logger.exception(
                    "background checkpoint write for step %s failed", snap.step
                )
            finally:
                self._buffers.release(snap)
                with self._cond:
                    self._writing = False
                    self._in_flight_path = None
                    self._update_pending_gauge()
                    self._cond.notify_all()

    def _write_and_commit(self, snap):
        from tensorflowonspark_tpu.train import checkpoint as _ckpt

        final = self._final_path(snap.step)
        staging = os.path.join(
            self.model_dir, "{}{}{}".format(TMP_MARKER, self.prefix, snap.step)
        )
        if os.path.isdir(staging):  # leftover of a torn earlier commit
            shutil.rmtree(staging, ignore_errors=True)
        t0 = time.monotonic()
        if chaos.active:
            chaos.delay("ckpt.write_slow")
        ckptr = _ckpt._checkpointer()
        ckptr.save(staging, _ckpt._to_saveable(snap.tree), force=True)
        ckptr.wait_until_finished()
        _manifest.write_manifest(staging, step=snap.step)
        if chaos.active and chaos.fire("checkpoint.corrupt_write"):
            # bitrot AFTER the manifest: verify() must catch the mismatch
            _ckpt._tear_checkpoint(staging)
        if chaos.active:
            spec = chaos.fire("ckpt.commit_tear")
            if spec is not None:
                if spec.get("publish_torn"):
                    self._tear_manifest(staging)
                else:
                    logger.warning(
                        "chaos: commit torn before publish — leaving %s "
                        "unpublished", staging,
                    )
                    return  # the crash-before-rename shape
        if os.path.isdir(final):  # re-save of the same step: replace
            shutil.rmtree(final, ignore_errors=True)
        os.rename(staging, final)
        # restore-after-power-cut must see the publish: the step dir's
        # rename is only durable once the checkpoint root's entry is
        durable.fsync_dir(os.path.dirname(final))
        elapsed = time.monotonic() - t0
        obs.counter(
            "ckpt_write_seconds_total",
            help="seconds the background writer spent serializing + committing",
        ).inc(elapsed)
        obs.counter(
            "ckpt_commits_total", help="checkpoints published (manifest + rename)"
        ).inc()
        logger.info(
            "committed checkpoint %s (%.3fs, %d bytes snapshotted)",
            final, elapsed, snap.nbytes,
        )
        if self.keep:
            _ckpt.prune_checkpoints(self.model_dir, self.keep)

    @staticmethod
    def _tear_manifest(staging):
        """``ckpt.commit_tear`` with ``publish_torn``: the manifest write
        itself is interrupted mid-flush but the rename happens — the shape
        of a crash racing a non-atomic manifest write on a filesystem
        without rename durability. ``verify`` must reject it."""
        mpath = os.path.join(staging, _manifest.MANIFEST_NAME)
        try:
            size = os.path.getsize(mpath)
            with open(mpath, "r+b") as f:
                f.truncate(max(1, size // 2))
            logger.warning("chaos: tore manifest %s mid-commit", mpath)
        except OSError:
            pass
