"""The atomic-commit manifest: per-file sizes + checksums, written last.

A checkpoint directory is *published* in three ordered steps (the engine's
commit protocol, :mod:`tensorflowonspark_tpu.ckpt.engine`):

1. shards land in a staging dir (``tmp.ckpt_<step>``),
2. ``MANIFEST.json`` — every file's size and CRC32 — is written last,
3. one ``os.rename`` moves the staging dir to its final ``ckpt_<step>`` name.

Because the manifest is written after every shard and the rename is atomic
on a POSIX filesystem, a crash at any point leaves either (a) a staging dir
with no manifest (never considered by restore) or (b) a fully-described
published checkpoint. ``verify`` then lets ``restore_latest`` *cheap-check*
integrity — stat + checksum instead of attempting a full orbax restore and
catching whatever it throws (the pre-manifest fallback path, which still
covers legacy manifest-less checkpoints).
"""

import json
import logging
import os
import zlib

from tensorflowonspark_tpu import durable

logger = logging.getLogger(__name__)

#: the commit marker file, written last inside the staging dir
MANIFEST_NAME = "MANIFEST.json"
#: manifest format version (bump on incompatible layout changes)
VERSION = 1
#: checksum read chunk (checkpoint shards can be GBs; never slurp them)
_CHUNK = 1 << 20


def _file_crc32(path):
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _walk_files(root):
    """Relative paths of every regular file under ``root`` except the
    manifest itself, sorted for deterministic manifests."""
    out = []
    for base, _dirs, names in os.walk(root):
        for name in names:
            rel = os.path.relpath(os.path.join(base, name), root)
            if rel != MANIFEST_NAME:
                out.append(rel)
    return sorted(out)


def write_manifest(path, step=None, extra=None):
    """Write ``MANIFEST.json`` describing every file currently under
    ``path``. MUST be the last write before the publishing rename — the
    manifest's presence is the commit marker. The manifest itself is
    written via a same-directory temp file + rename so a torn manifest
    write can never masquerade as a complete one. Returns the manifest
    dict."""
    path = os.path.abspath(os.path.expanduser(path))
    files = {}
    for rel in _walk_files(path):
        sub = os.path.join(path, rel)
        files[rel] = {"size": os.path.getsize(sub), "crc32": _file_crc32(sub)}
    manifest = {"version": VERSION, "step": step, "files": files}
    if extra:
        manifest["extra"] = dict(extra)
    tmp = os.path.join(path, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.rename(tmp, os.path.join(path, MANIFEST_NAME))
    # the rename is only durable once the directory entry is: a power cut
    # after fsync(file) but before fsync(dir) can replay the directory
    # without MANIFEST.json even though its bytes hit the platter
    durable.fsync_dir(path)
    return manifest


def read_manifest(path):
    """Parse ``path``'s manifest; returns the dict, or None when absent
    (legacy checkpoints saved before the async engine)."""
    mpath = os.path.join(os.path.abspath(os.path.expanduser(path)), MANIFEST_NAME)
    if not os.path.isfile(mpath):
        return None
    with open(mpath) as f:
        return json.load(f)


def verify(path):
    """Cheap integrity check of a published checkpoint against its manifest.

    Returns ``(ok, reason)``: ``(True, "verified")`` when every listed file
    exists with the recorded size and CRC32, ``(True, "no manifest")`` for
    legacy checkpoints (caller falls back to attempt-the-restore), and
    ``(False, reason)`` naming the first failure — torn manifest JSON,
    missing file, size mismatch, checksum mismatch — so ``restore_latest``
    can log *why* a candidate was skipped."""
    path = os.path.abspath(os.path.expanduser(path))
    try:
        manifest = read_manifest(path)
    except (ValueError, OSError) as e:
        return False, "torn manifest ({})".format(e)
    if manifest is None:
        return True, "no manifest"
    if not isinstance(manifest.get("files"), dict):
        return False, "torn manifest (no file table)"
    for rel, meta in sorted(manifest["files"].items()):
        sub = os.path.join(path, rel)
        try:
            size = os.path.getsize(sub)
        except OSError:
            return False, "missing file {}".format(rel)
        if size != meta.get("size"):
            return False, "size mismatch on {} ({} != {})".format(
                rel, size, meta.get("size")
            )
        if _file_crc32(sub) != meta.get("crc32"):
            return False, "checksum mismatch on {}".format(rel)
    return True, "verified"
