"""Resharded restore: map a checkpoint onto a *different* mesh.

The elastic-recovery case: a cluster checkpoints on a 1×N mesh, a worker
dies, and ``run_with_recovery`` relaunches with a different device count —
the restored arrays must land on the new mesh under the new partition
specs. Orbax records the shardings a checkpoint was *saved* with; instead
of fighting that metadata, the restore here is deliberately two-phase:

1. restore the checkpoint to plain host numpy arrays (mesh-free), then
2. ``device_put`` every leaf under the placement the **new** strategy
   derives for it (params via ``param_shardings``, optimizer state via the
   structural matcher, step/model_state replicated — exactly the placement
   ``create_state`` would produce).

Host memory bounds this (phase 1 materializes full arrays on the host),
which is the right trade for the recovery path: it is rare, correctness
matters more than peak speed, and it works for any source→target mesh pair
including shape-incompatible ones. Single-controller scope: each process
restores onto its own (local) mesh — the multi-host jax child world
restores per-process like every other placement in this repo.
"""

import logging

logger = logging.getLogger(__name__)


def state_shardings(strategy, state):
    """The NamedSharding pytree ``strategy`` assigns a TrainState (or bare
    pytree) — computable from a restored *host* state: only leaf shapes and
    dtypes are consulted, matching ``create_state``'s placement."""
    from tensorflowonspark_tpu.parallel import replicated
    from tensorflowonspark_tpu.train.strategy import TrainState

    import jax

    rep = replicated(strategy.mesh)
    if isinstance(state, TrainState):
        return TrainState(
            rep,
            strategy.param_shardings(state.params),
            strategy._opt_shardings(state),
            jax.tree.map(lambda _: rep, state.model_state),
        )
    return jax.tree.map(lambda _: rep, state)


def reshard_restore(path, strategy=None, target=None, shardings=None):
    """Restore the checkpoint at ``path`` onto a new mesh / partition spec.

    ``strategy`` (a :class:`~tensorflowonspark_tpu.train.strategy.
    SyncDataParallel` built on the NEW mesh) derives the target placement;
    pass an explicit ``shardings`` pytree instead for custom layouts.
    ``target`` (optional) supplies tree structure for the host restore —
    device-resident targets are host-ified first, so a fresh state created
    on the new mesh can be passed directly.

    Returns the state device-resident under the new placement. Values are
    bit-identical to the saved ones — resharding moves bytes, it never
    recomputes them.
    """
    import jax

    from tensorflowonspark_tpu.train import checkpoint as _ckpt

    if strategy is None and shardings is None:
        raise ValueError("reshard_restore needs a strategy or explicit shardings")
    if target is not None:
        target = jax.device_get(target)
    host = _ckpt.restore_checkpoint(path, target=target)
    if shardings is None:
        shardings = state_shardings(strategy, host)
    placed = jax.tree.map(lambda x, s: jax.device_put(x, s), host, shardings)
    logger.info(
        "resharded checkpoint %s onto mesh %s", path,
        getattr(strategy, "mesh", None),
    )
    return placed
