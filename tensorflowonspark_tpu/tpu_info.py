"""TPU topology discovery and chip-visibility control.

The TPU-native replacement for the reference's ``gpu_info.py`` (nvidia-smi
scraping + ``CUDA_VISIBLE_DEVICES`` pinning,
/root/reference/tensorflowonspark/gpu_info.py:54-116). TPUs need a different
model: a host owns all of its chips through libtpu (one process per host by
default), topology comes from the TPU runtime env / device files rather than a
CLI tool, and visibility is controlled with ``TPU_VISIBLE_CHIPS`` /
``TPU_PROCESS_BOUNDS`` instead of a device list.

Nothing here imports jax — these probes run in the lightweight executor
process before the jax child is forked.
"""

import glob
import logging
import os

logger = logging.getLogger(__name__)

#: env vars consulted for explicit topology overrides
ENV_CHIP_COUNT = "TOS_TPU_CHIPS_PER_HOST"
ENV_ACCEL_TYPE = "TOS_TPU_ACCELERATOR_TYPE"

#: accelerator generation → (what the "-N" suffix counts, cores per chip,
#: max chips per host machine). Cloud TPU naming: core-counted generations
#: (v2..v4, v5p) say "v4-32" = 32 TensorCores = 16 chips; chip-counted
#: generations (v5e/v5litepod, v6e) say "v5e-32" = 32 chips. Rule-based so
#: ANY slice size derives (round-2 review: a fixed table stopped at v5p-16).
_GENERATIONS = {
    "v2": ("cores", 2, 4),
    "v3": ("cores", 2, 4),
    "v4": ("cores", 2, 4),
    "v5p": ("cores", 2, 4),
    "v5e": ("chips", 1, 8),
    "v5litepod": ("chips", 1, 8),
    "v6e": ("chips", 1, 8),
}


def parse_accelerator_type(accel_type):
    """``'v5e-32'`` → ``('v5e', 32)``; None for unparseable strings."""
    if not accel_type or "-" not in accel_type:
        return None
    gen, _, num = accel_type.partition("-")
    gen = gen.lower()
    if gen not in _GENERATIONS or not num.isdigit() or int(num) < 1:
        return None
    return gen, int(num)


def detect_local_chips():
    """Best-effort count of TPU chips attached to this host.

    Order: explicit override env → TPU runtime env hints → accel device files
    (``/dev/accel*`` for PCIe-attached TPU, ``/dev/vfio``) → 0 (no TPU).
    """
    override = os.environ.get(ENV_CHIP_COUNT)
    if override:
        return int(override)
    # Cloud TPU VM runtime exports these
    for var in ("TPU_CHIPS_PER_HOST_BOUNDS", "TPU_CHIPS_PER_PROCESS_BOUNDS"):
        bounds = os.environ.get(var)
        if bounds:
            try:
                dims = [int(x) for x in bounds.split(",")]
                count = 1
                for d in dims:
                    count *= d
                return count
            except ValueError:
                pass
    accels = glob.glob("/dev/accel*")
    if accels:
        return len(accels)
    if os.path.isdir("/dev/vfio"):
        vfio = [p for p in glob.glob("/dev/vfio/*") if os.path.basename(p).isdigit()]
        if vfio:
            return len(vfio)
    return 0


def is_tpu_available():
    """Analogue of gpu_info.is_gpu_available (reference gpu_info.py:45)."""
    return detect_local_chips() > 0


def accelerator_type():
    """Accelerator type string (e.g. 'v5e-32') if known, else None."""
    return os.environ.get(ENV_ACCEL_TYPE) or os.environ.get("TPU_ACCELERATOR_TYPE")


def topology_for(accel_type):
    """(chips_per_host, total_chips) derived from the accelerator type, else
    None. Single-host slices put all chips on one machine; multi-host
    slices use the generation's per-host chip count (4 for core-counted
    generations, and for chip-counted ones past the 8-chip host boundary)."""
    parsed = parse_accelerator_type(accel_type)
    if parsed is None:
        return None
    gen, num = parsed
    unit, cores_per_chip, host_max = _GENERATIONS[gen]
    total_chips = num // cores_per_chip if unit == "cores" else num
    total_chips = max(total_chips, 1)
    if total_chips <= host_max:
        return (total_chips, total_chips)
    # multi-host: v5e/v6e multi-host slices are built from 4-chip hosts
    per_host = 4 if unit == "chips" else min(host_max, total_chips)
    return (per_host, total_chips)


def num_hosts_for(accel_type):
    """Host (worker VM) count for a slice, or None — what the launch tooling
    sizes ``--cluster_size`` with."""
    topo = topology_for(accel_type)
    if topo is None:
        return None
    per_host, total = topo
    return max(1, total // per_host)


def validate_against_runtime(local_device_count):
    """Compare the env/device-file detection against what the runtime
    actually sees (called from the jax child once jax is up). Logs — never
    raises — because detection feeds placement hints, not correctness.

    Core-counted generations (v2/v3) expose 2 devices per chip, so a
    runtime count of exactly 2x the detected chips is also a match."""
    detected = detect_local_chips()
    if not detected or not local_device_count:
        return True
    if local_device_count in (detected, 2 * detected):
        return True
    logger.warning(
        "tpu_info detected %d local chip(s) but the runtime reports %d "
        "local device(s); trusting the runtime (override with %s)",
        detected, local_device_count, ENV_CHIP_COUNT,
    )
    return False


def local_topology():
    """Summary dict of this host's TPU situation, shipped in the reservation
    record so the coordinator sees the whole slice's shape (SURVEY.md §2.8:
    the reservation server's role grows to include TPU topology exchange)."""
    accel = accelerator_type()
    chips = detect_local_chips()
    if chips == 0 and accel:
        derived = topology_for(accel)
        if derived:
            chips = derived[0]
    return {
        "accelerator_type": accel,
        "num_chips": chips,
        "worker_id": os.environ.get("TPU_WORKER_ID"),
        "worker_hostnames": os.environ.get("TPU_WORKER_HOSTNAMES"),
    }


def visibility_env(chip_ids=None, platform=None):
    """Environment to pin a child process to a subset of chips / a platform.

    The CUDA_VISIBLE_DEVICES analogue (reference gpu_info.py:102-113 placed
    workers on GPUs by local index). On TPU the common case is *all* chips to
    *one* process per host; chip subsetting is for megacore-style splits or
    colocated independent replicas (TFParallel).
    """
    env = {}
    if platform:
        env["JAX_PLATFORMS"] = platform
    if chip_ids is not None:
        env["TPU_VISIBLE_CHIPS"] = ",".join(str(c) for c in chip_ids)
        env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = _chip_grid_bounds(len(chip_ids))
        env["TPU_PROCESS_BOUNDS"] = "1,1,1"
    return env


def _chip_grid_bounds(n):
    """x,y,z bounds covering ``n`` chips — the per-process bounds must match
    the visible-chip count or libtpu rejects/ignores the extra chips, and
    must fit inside the host's chip grid (x is the narrow dimension: v5e-8 /
    v6e-8 hosts are a 2x4 grid, so 8 chips is '2,4,1', never '4,2,1')."""
    host = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS")
    if host:
        try:
            hx, hy, hz = (int(v) for v in host.split(","))
            if hx * hy * hz == n:  # all chips: mirror the host grid exactly
                return host
        except ValueError:
            pass
    grids = {1: "1,1,1", 2: "1,2,1", 4: "2,2,1", 8: "2,4,1", 16: "4,4,1"}
    return grids.get(n, "1,{},1".format(n))
