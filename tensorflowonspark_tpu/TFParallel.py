"""Run N independent single-node instances in parallel — no cluster, no
reservation server.

Capability-parity with /root/reference/tensorflowonspark/TFParallel.py
(Spark barrier execution for parallel single-node inference,
TFParallel.py:17-64): each executor gets a synthetic
:class:`~tensorflowonspark_tpu.TFSparkNode.TFNodeContext` (executor id from
the task's partition index, ``num_workers`` = parallelism, no manager/feed
plane) and runs the user function in a spawned jax child so libtpu's
process-owns-chips rule holds and chips free up when the task ends.
"""

import logging
import os
import traceback

from tensorflowonspark_tpu import TFSparkNode, tpu_info, util

logger = logging.getLogger(__name__)


class _ParallelTask:
    def __init__(self, fn, tf_args, num_executors, env=None):
        self.fn = fn
        self.tf_args = tf_args
        self.num_executors = num_executors
        self.env = dict(env or {})

    def __call__(self, iterator):
        executor_id = None
        for i in iterator:
            executor_id = i if not isinstance(i, (list, tuple)) else i[0]
        if executor_id is None:
            return []
        ctx = TFSparkNode.TFNodeContext(
            executor_id=executor_id,
            job_name="worker",
            task_index=executor_id,
            cluster_spec={"worker": ["localhost"] * self.num_executors},
            defaultFS="file://",
            working_dir=os.getcwd(),
        )

        # partition this host's chips across co-resident instances — the
        # reference placed workers on GPUs by local index (gpu_info.py:102);
        # without this, concurrent children would each claim ALL chips and
        # collide on libtpu's process-owns-chips rule
        chip_ids = None
        n_chips = tpu_info.detect_local_chips()
        if n_chips and self.env.get("JAX_PLATFORMS") != "cpu":
            local_rank, num_local = self._local_placement(executor_id)
            if num_local > n_chips:
                raise RuntimeError(
                    "{} TFParallel instances on this host but only {} chips — "
                    "reduce num_executors or instances per host".format(num_local, n_chips)
                )
            per = n_chips // num_local
            start = local_rank * per
            chip_ids = list(range(start, start + per))

        def _entry():
            try:
                os.environ.update(self.env)
                os.environ.update(
                    tpu_info.visibility_env(
                        chip_ids=chip_ids, platform=self.env.get("JAX_PLATFORMS")
                    )
                )
                if self.env.get("JAX_PLATFORMS"):
                    util.force_platform(
                        self.env["JAX_PLATFORMS"], self.env.get("TOS_NUM_CPU_DEVICES")
                    )
                self.fn(self.tf_args, ctx)
            except BaseException:
                logger.error("TFParallel fn failed:\n%s", traceback.format_exc())
                raise SystemExit(1)

        child = util.spawn_process(_entry, name="jax-parallel-{}".format(executor_id))
        child.start()
        child.join()
        if child.exitcode != 0:
            raise RuntimeError(
                "TFParallel instance {} failed (exit {})".format(executor_id, child.exitcode)
            )
        return [executor_id]

    def _local_placement(self, executor_id):
        """(host-local rank, instances on this host). Real Spark barrier mode
        exposes co-located tasks via BarrierTaskContext (the reference's
        placement source, TFParallel.py:42-45); the local backend runs every
        instance on one host, so there the global id IS the local rank."""
        try:
            from pyspark import BarrierTaskContext

            ctx = BarrierTaskContext.get()
            infos = ctx.getTaskInfos()
            import socket

            me = socket.gethostname()
            local = [
                i for i, t in enumerate(infos)
                if t.address.split(":")[0] in (me, "localhost", "127.0.0.1")
            ]
            return local.index(ctx.partitionId()), max(len(local), 1)
        except Exception:
            return executor_id, self.num_executors


def run(sc, map_fn, tf_args, num_executors, env=None):
    """Run ``map_fn(tf_args, ctx)`` as ``num_executors`` independent instances
    (reference TFParallel.run, TFParallel.py:17). Returns the executor ids
    that completed."""
    kwargs = {"pin_to_executors": True} if getattr(sc, "PIN_SUPPORTED", False) else {}
    rdd = sc.parallelize(range(num_executors), num_executors, **kwargs)
    if hasattr(rdd, "barrier"):  # real Spark: barrier execution mode
        rdd = rdd.barrier()
    return rdd.mapPartitions(_ParallelTask(map_fn, tf_args, num_executors, env)).collect()
