"""Driver-hosted reservation / coordination control plane.

TPU-native re-design of the reference's reservation protocol
(/root/reference/tensorflowonspark/reservation.py). Same capability — every
executor registers exactly one reservation, the driver blocks until the cluster
is fully assembled, clients can fetch the final cluster info and request an
early stop — with deliberate differences:

* Wire format is length-prefixed **JSON**, not pickle: executors should not be
  able to execute arbitrary code on the driver via the control socket
  (reference framing: reservation.py:68-97).
* Reservations carry TPU topology (local chip count, process index hints) and
  the assembled cluster info is the input to ``jax.distributed.initialize`` —
  the server is the natural coordinator-election point (SURVEY.md §2.8).
* The store uses a condition variable instead of busy-polling where possible,
  but the driver-side ``await_reservations`` still polls with a timeout so it
  can abort on executor errors reported out-of-band (reference
  reservation.py:113-126).

Environment overrides ``TOS_TPU_SERVER_HOST`` / ``TOS_TPU_SERVER_PORT`` mirror
the reference's ``TFOS_SERVER_HOST/PORT`` (reservation.py:25-26) for NAT'd or
proxied driver setups.
"""

import json
import logging
import os
import selectors
import socket
import struct
import threading
import time

from tensorflowonspark_tpu import chaos, obs, resilience
from tensorflowonspark_tpu.obs import tracing

logger = logging.getLogger(__name__)

#: env var: externally-visible host for the server (NAT / container setups)
ENV_SERVER_HOST = "TOS_TPU_SERVER_HOST"
#: env var: fixed listening port for the server
ENV_SERVER_PORT = "TOS_TPU_SERVER_PORT"

_HEADER = struct.Struct(">I")
_MAX_MSG = 64 * 1024 * 1024


class ReservationError(Exception):
    """Raised when the cluster cannot be assembled (timeout or node error).

    ``missing`` carries the executor ids that never registered (when the
    server was told which ids to expect) — the recovery ladder's attribution
    input (:mod:`~tensorflowonspark_tpu.elastic`).
    """

    def __init__(self, message, missing=None):
        super().__init__(message)
        self.missing = list(missing) if missing else []


class Reservations:
    """Thread-safe store of node reservations (reference reservation.py:31-65).

    ``required`` is the number of reservations that completes the cluster.
    ``expected_ids`` optionally names the executor ids that should arrive, so
    a timeout can report *which* nodes never registered instead of just how
    many.
    """

    def __init__(self, required, expected_ids=None):
        self.required = required
        self.expected_ids = sorted(expected_ids) if expected_ids else None
        self._lock = threading.Condition()
        self._entries = []

    def missing(self):
        """Expected executor ids that have not registered yet (sorted).

        Empty when no ``expected_ids`` were declared — the caller falls back
        to count-based reporting.
        """
        if self.expected_ids is None:
            return []
        with self._lock:
            seen = {
                e.get("executor_id") for e in self._entries if isinstance(e, dict)
            }
        return [eid for eid in self.expected_ids if eid not in seen]

    def add(self, meta):
        """Add (or idempotently replace) one reservation.

        Dedup key: ``executor_id`` when present. Spark retries tasks and the
        client retries lost replies, so REG must be idempotent — the reference
        handled retried tasks by reusing prior reservations
        (TFSparkNode.py:240-249); we dedup at the store instead.
        """
        with self._lock:
            key = meta.get("executor_id") if isinstance(meta, dict) else None
            if key is not None:
                for i, existing in enumerate(self._entries):
                    if isinstance(existing, dict) and existing.get("executor_id") == key:
                        self._entries[i] = meta
                        self._lock.notify_all()
                        return
            self._entries.append(meta)
            if self.done:
                self._lock.notify_all()

    def get(self):
        with self._lock:
            return list(self._entries)

    def remaining(self):
        with self._lock:
            return self.required - len(self._entries)

    @property
    def done(self):
        return len(self._entries) >= self.required

    def wait(self, timeout=None):
        """Block until complete; returns True if complete."""
        deadline = None if timeout is None else time.time() + timeout
        with self._lock:
            while not self.done:
                remaining = None if deadline is None else deadline - time.time()
                if remaining is not None and remaining <= 0:
                    return False
                self._lock.wait(timeout=remaining)
            return True


class MessageSocket:
    """Length-prefixed JSON framing over a stream socket."""

    def __init__(self, sock):
        self.sock = sock

    def send(self, obj):
        payload = json.dumps(obj).encode("utf-8")
        self.sock.sendall(_HEADER.pack(len(payload)) + payload)

    def recv(self):
        header = self._recv_exact(_HEADER.size)
        if header is None:
            return None
        (length,) = _HEADER.unpack(header)
        if length > _MAX_MSG:
            raise ReservationError("control message too large: {} bytes".format(length))
        payload = self._recv_exact(length)
        if payload is None:
            return None
        return json.loads(payload.decode("utf-8"))

    # raw frames (binary payload lanes, e.g. serving tensors) share the same
    # 4-byte BE length framing so one implementation owns the wire format

    def send_raw(self, payload):
        self.sock.sendall(_HEADER.pack(len(payload)) + payload)

    def recv_raw(self, max_bytes=None):
        """One raw frame. Oversize frames are consumed-and-refused (the
        stream stays in sync for the next message) — callers get a
        ValueError they can answer with an error reply."""
        header = self._recv_exact(_HEADER.size)
        if header is None:
            return None
        (length,) = _HEADER.unpack(header)
        if length < 0:
            raise ConnectionError("corrupt raw frame length {}".format(length))
        limit = _MAX_MSG if max_bytes is None else max_bytes
        if length > limit:
            remaining = length
            while remaining:
                chunk = self.sock.recv(min(1 << 20, remaining))
                if not chunk:
                    return None
                remaining -= len(chunk)
            raise ValueError(
                "raw frame too large: {} bytes (limit {})".format(length, limit)
            )
        return self._recv_exact(length)

    def _recv_exact(self, n):
        buf = bytearray()
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                return None
            buf.extend(chunk)
        return bytes(buf)

    def close(self):
        try:
            self.sock.close()
        except OSError:
            pass


class Server:
    """Reservation server hosted on the Spark driver.

    One instance per cluster. ``start()`` spawns a daemon listener thread
    multiplexing all executor clients with a selector (reference ran a
    select()-loop thread, reservation.py:148-188).

    ``expected_ids`` names the executor ids that should register (enables
    per-id timeout attribution via :meth:`Reservations.missing`);
    ``blacklist`` is a set of executor ids whose registrations are refused —
    the recovery ladder excludes known-bad hosts this way, and a refused
    executor fails fast instead of silently joining the wrong cluster.

    ``registry`` is an optional
    :class:`~tensorflowonspark_tpu.registry.MembershipRegistry`: when given,
    it becomes the membership truth — its blacklist is consulted alongside
    (union with) the static ``blacklist`` set, and every accepted REG grants
    the executor a lease via ``registry.join``.
    """

    def __init__(self, count, expected_ids=None, blacklist=None, registry=None):
        if count <= 0:
            raise ValueError("reservation count must be positive")
        self.reservations = Reservations(count, expected_ids=expected_ids)
        self.blacklist = frozenset(blacklist or ())
        self.registry = registry
        self._stop_requested = threading.Event()
        self._shutdown = threading.Event()
        self._sock = None
        self._thread = None

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        """Bind, listen and serve in a daemon thread. Returns (host, port)."""
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        port = int(os.environ.get(ENV_SERVER_PORT, "0"))
        self._sock.bind(("", port))
        self._sock.listen(64)
        self._thread = threading.Thread(
            target=self._serve, name="tos-reservation-server", daemon=True
        )
        self._thread.start()
        host = os.environ.get(ENV_SERVER_HOST)
        if not host:
            from tensorflowonspark_tpu import util

            host = util.get_ip_address()
        addr = (host, self._sock.getsockname()[1])
        logger.info("reservation server listening at %s", addr)
        return addr

    def stop(self):
        self._shutdown.set()
        # connect to ourselves to wake the selector promptly
        try:
            with socket.create_connection(
                ("127.0.0.1", self._sock.getsockname()[1]), timeout=1
            ):
                pass
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    @property
    def stop_requested(self):
        """True once any client sent STOP (early-termination request)."""
        return self._stop_requested.is_set()

    # -- driver-side wait ----------------------------------------------------

    def await_reservations(self, status=None, timeout=600, poll_interval=1.0):
        """Block the driver until all nodes reserved.

        ``status`` is a shared dict the background launch thread writes an
        ``'error'`` key into when an executor fails during startup; we abort
        immediately in that case (reference reservation.py:113-126 +
        TFCluster.py:314-331).
        """
        pending = obs.gauge(
            "reservation_pending_nodes", help="nodes still missing from the cluster"
        )
        deadline = time.time() + timeout
        with obs.span("reservation_roundtrip", required=self.reservations.required):
            while not self.reservations.done:
                pending.set(self.reservations.remaining())
                if status and status.get("error"):
                    obs.counter(
                        "reservation_failures_total",
                        help="await_reservations aborts (node error or timeout)",
                    ).inc()
                    raise ReservationError(
                        "cluster startup aborted by node failure: {}".format(status["error"])
                    )
                if time.time() > deadline:
                    obs.counter("reservation_failures_total").inc()
                    missing = self.reservations.missing()
                    detail = (
                        "; never registered: executors {}".format(missing)
                        if missing
                        else ""
                    )
                    raise ReservationError(
                        "timed out waiting for {} node(s) to register (of {}){}".format(
                            self.reservations.remaining(),
                            self.reservations.required,
                            detail,
                        ),
                        missing=missing,
                    )
                self.reservations.wait(timeout=poll_interval)
        pending.set(0)
        logger.info(
            "all %d node(s) reserved", self.reservations.required
        )
        return self.reservations.get()

    # -- server internals ----------------------------------------------------

    def _serve(self):
        sel = selectors.DefaultSelector()
        sel.register(self._sock, selectors.EVENT_READ, data=None)
        try:
            while not self._shutdown.is_set():
                for key, _ in sel.select(timeout=0.5):
                    if key.data is None:
                        try:
                            conn, _addr = self._sock.accept()
                        except OSError:
                            continue
                        if chaos.active:
                            chaos.delay("reservation.slow_accept")
                        # bounded blocking reads: a stalled client must not
                        # wedge the single-threaded control plane
                        conn.settimeout(10.0)
                        sel.register(conn, selectors.EVENT_READ, data=MessageSocket(conn))
                    else:
                        msock = key.data
                        try:
                            msg = msock.recv()
                        except (OSError, ValueError, ReservationError):
                            msg = None
                        if msg is None:
                            sel.unregister(msock.sock)
                            msock.close()
                            continue
                        try:
                            self._handle(msock, msg)
                        except OSError:
                            sel.unregister(msock.sock)
                            msock.close()
                        except Exception as e:  # malformed-but-valid-JSON input
                            logger.warning("dropping bad control message %r: %s", msg, e)
                            sel.unregister(msock.sock)
                            msock.close()
        finally:
            for key in list(sel.get_map().values()):
                if key.data is not None:
                    key.data.close()
            sel.close()
            try:
                self._sock.close()
            except OSError:
                pass

    def _handle(self, msock, msg):
        """Dispatch one control message (reference reservation.py:130-146)."""
        kind = msg.get("type") if isinstance(msg, dict) else None
        if kind == "REG":
            if chaos.active and chaos.fire("reservation.reg_drop"):
                # drop the connection before replying: the client sees a
                # closed stream and re-registers (REG is idempotent)
                raise OSError("chaos: dropped registration")
            data = msg.get("data", {})
            eid = data.get("executor_id") if isinstance(data, dict) else None
            refused = eid is not None and (
                eid in self.blacklist
                or (self.registry is not None and self.registry.is_blacklisted(eid))
            )
            if refused:
                obs.counter(
                    "reservation_blacklist_rejections_total",
                    help="REG refused because the executor is blacklisted",
                ).inc()
                logger.warning("refusing registration from blacklisted executor %s", eid)
                msock.send(
                    {"type": "ERROR", "data": "executor {} is blacklisted".format(eid)}
                )
                return
            self.reservations.add(data)
            if self.registry is not None and eid is not None:
                try:
                    self.registry.join(
                        eid,
                        job_name=data.get("job_name"),
                        task_index=data.get("task_index"),
                    )
                except Exception as e:
                    # a fenced/failed journal must not take down assembly:
                    # the lease is advisory until the watchdog reads it
                    logger.warning("registry join for executor %s failed: %s", eid, e)
            obs.counter(
                "reservation_registrations_total",
                help="REG messages accepted (retries re-register idempotently)",
            ).inc()
            # the reply carries the driver's wall clock: the client folds the
            # stamped round-trip into its NTP-style clock-offset estimate so
            # the trace merger can align per-host timelines (obs.tracing)
            msock.send({"type": "OK", "ts": time.time()})
        elif kind == "QUERY":
            msock.send({"type": "DONE", "data": self.reservations.done})
        elif kind == "QINFO":
            msock.send({"type": "INFO", "data": self.reservations.get()})
        elif kind == "QSTOP":
            msock.send({"type": "STOPPED", "data": self.stop_requested})
        elif kind == "STOP":
            logger.info("stop requested via control plane")
            self._stop_requested.set()
            msock.send({"type": "OK"})
        else:
            msock.send({"type": "ERROR", "data": "unknown message type {!r}".format(kind)})


#: env var: seconds a restarting driver is given to re-bind its rendezvous
#: socket before connection-refused executors give up
ENV_RESTART_WINDOW = "TOS_DRIVER_RESTART_WINDOW"

#: default driver-restart grace window (seconds)
DEFAULT_RESTART_WINDOW = 15.0


class Client:
    """Executor-side client for the reservation server.

    Opens one connection per request with bounded retries, because executors
    may race the server's startup and Spark may retry tasks (reference kept a
    connection but reconnect-retried ×3, reservation.py:221-246).

    Connection-refused is special-cased: nothing is listening on the
    rendezvous port, which during a driver restart is a *transient* state —
    the new driver re-binds (``TOS_TPU_SERVER_PORT`` pins the port precisely
    so this works) within the restart window. Rather than failing the
    executor on the first refusal, refusals are retried under a dedicated
    deadline-bounded policy (``restart_window`` seconds, env
    ``TOS_DRIVER_RESTART_WINDOW``); the error that finally surfaces names
    the rendezvous address and the elapsed retry budget so the operator can
    tell "driver never came back" from "wrong address".
    """

    RETRIES = 3
    #: retry schedule shared by every request (1s, 2s, ... capped at 5s —
    #: same envelope as the reference's fixed ``2 ** attempt`` sleep, now
    #: jittered so a fleet of racing executors doesn't reconnect in lockstep)
    BACKOFF = resilience.Backoff(base=1.0, factor=2.0, max_delay=5.0, jitter=0.5)

    def __init__(self, server_addr, timeout=30, restart_window=None, backoff=None):
        self.server_addr = (server_addr[0], int(server_addr[1]))
        self.timeout = timeout
        if restart_window is None:
            restart_window = float(
                os.environ.get(ENV_RESTART_WINDOW, str(DEFAULT_RESTART_WINDOW))
            )
        self.restart_window = restart_window
        backoff = backoff if backoff is not None else self.BACKOFF
        self._policy = resilience.RetryPolicy(
            max_attempts=self.RETRIES,
            backoff=backoff,
            retry_on=(OSError, ReservationError),
            on_retry=self._on_retry,
            name="reservation-client",
        )
        # connection-refused during a driver restart: retry until the window
        # closes, not until an attempt count runs out — the deadline is the
        # budget (attempt cap is just a runaway guard)
        self._restart_policy = resilience.RetryPolicy(
            max_attempts=256,
            backoff=backoff,
            retry_on=(ConnectionRefusedError,),
            timeout=self.restart_window,
            on_retry=self._on_restart_retry,
            name="reservation-restart-window",
        )

    @staticmethod
    def _on_retry(attempt, exc, delay):
        obs.counter(
            "reservation_client_retries_total",
            help="control-plane request attempts that failed and retried",
        ).inc()
        logger.debug("reservation request attempt %d failed (%s); retrying in %.1fs",
                     attempt + 1, exc, delay)

    @staticmethod
    def _on_restart_retry(attempt, exc, delay):
        obs.counter(
            "reservation_restart_retries_total",
            help="connection-refused retries inside the driver-restart window",
        ).inc()
        logger.info(
            "rendezvous refused connection (attempt %d) — assuming driver "
            "restart, retrying in %.1fs", attempt + 1, delay,
        )

    def _request_once(self, msg):
        if chaos.active and chaos.fire("reservation.client_reset"):
            raise ConnectionResetError("chaos: injected connection reset")
        with socket.create_connection(self.server_addr, timeout=self.timeout) as sock:
            msock = MessageSocket(sock)
            t0 = time.time()
            msock.send(msg)
            reply = msock.recv()
            t1 = time.time()
            if reply is None:
                raise ReservationError("server closed connection")
            if reply.get("type") == "ERROR":
                raise ReservationError(str(reply.get("data")))
            # driver-stamped replies double as clock-sync samples: per-attempt
            # wall clocks bracket exactly one round-trip (retries would
            # inflate the RTT and poison the NTP-style midpoint estimate)
            if "ts" in reply:
                tracing.observe_clock(float(reply["ts"]), t0, t1)
            return reply

    def _request(self, msg):
        try:
            return self._policy.call(self._request_once, msg)
        except ConnectionRefusedError:
            # nothing listening: plausibly a driver restart in progress.
            # Keep knocking until the restart window closes.
            started = time.monotonic()
            try:
                return self._restart_policy.call(self._request_once, msg)
            except (OSError, ReservationError, resilience.DeadlineExceeded) as e:
                elapsed = time.monotonic() - started
                raise ReservationError(
                    "could not reach reservation server at {}:{} after {:.1f}s of "
                    "connection-refused retries (driver restart window {:.0f}s): {}".format(
                        self.server_addr[0], self.server_addr[1],
                        elapsed, self.restart_window, e,
                    )
                ) from e
        except (OSError, ReservationError) as e:
            raise ReservationError(
                "could not reach reservation server at {}: {}".format(self.server_addr, e)
            ) from e

    # -- API -----------------------------------------------------------------

    def register(self, reservation):
        if chaos.active:
            chaos.delay("reservation.late_register")
        self._request({"type": "REG", "data": reservation})

    def get_reservations(self):
        return self._request({"type": "QINFO"})["data"]

    def await_reservations(self, timeout=600, poll_interval=1.0):
        """Poll until the cluster is complete; returns the full cluster info."""
        poll = resilience.Backoff(
            base=poll_interval, factor=1.0, max_delay=poll_interval, jitter=0.0
        )
        for _ in poll.attempts(deadline=resilience.Deadline(timeout)):
            if self._request({"type": "QUERY"})["data"]:
                return self.get_reservations()
        raise ReservationError("timed out awaiting full cluster")

    def request_stop(self):
        self._request({"type": "STOP"})

    def stop_requested(self):
        return self._request({"type": "QSTOP"})["data"]

    def close(self):  # connections are per-request; kept for API parity
        pass
