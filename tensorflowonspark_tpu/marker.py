"""Sentinel markers used on the feed queues.

Mirrors the roles of the reference's markers
(/root/reference/tensorflowonspark/marker.py:11-16): ``None`` on a feed queue is
the implicit end-of-feed signal, :class:`EndPartition` separates RDD partitions
so an inference task can collect exactly the results for its own partition.
"""


class Marker:
    """Base class for control markers placed on data queues."""

    __slots__ = ()

    def __repr__(self):  # pragma: no cover - cosmetic
        return "<{}>".format(type(self).__name__)


class EndPartition(Marker):
    """Marks the end of one RDD partition within a continuing feed."""

    __slots__ = ()


#: The end-of-feed marker. Kept as ``None`` for wire-compat with the reference
#: semantics (/root/reference/tensorflowonspark/TFNode.py:267).
END_OF_FEED = None
