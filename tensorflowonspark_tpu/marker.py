"""Sentinel markers used on the feed queues.

Mirrors the roles of the reference's markers
(/root/reference/tensorflowonspark/marker.py:11-16): ``None`` on a feed queue is
the implicit end-of-feed signal, :class:`EndPartition` separates RDD partitions
so an inference task can collect exactly the results for its own partition.
"""


class Marker:
    """Base class for control markers placed on data queues."""

    __slots__ = ()

    def __repr__(self):  # pragma: no cover - cosmetic
        return "<{}>".format(type(self).__name__)


class EndPartition(Marker):
    """Marks the end of one RDD partition within a continuing feed."""

    __slots__ = ()


class Chunk(Marker):
    """A block of consecutive feed items shipped as ONE queue message.

    The feed plane's throughput unit: the reference pushed one pickled row
    per Manager proxy call (its hot-loop bottleneck, TFSparkNode.py:430-434);
    chunking amortizes the proxy round trip over ``len(items)`` rows. Fully
    transparent to consumers — :class:`~tensorflowonspark_tpu.TFNode.DataFeed`
    unwraps chunks and plain items alike.
    """

    __slots__ = ("items",)

    def __init__(self, items):
        self.items = list(items)

    def __len__(self):
        return len(self.items)


#: The end-of-feed marker. Kept as ``None`` for wire-compat with the reference
#: semantics (/root/reference/tensorflowonspark/TFNode.py:267).
END_OF_FEED = None
