"""Shared-memory feed chunks: the bulk-data lane of the feed plane.

The reference's feed plane pickled every row through a Manager proxy — its
hot loop (/root/reference/tensorflowonspark/TFSparkNode.py:430-434) put one
row per proxied call. Round 2 amortized the proxy round trip with
:class:`~tensorflowonspark_tpu.marker.Chunk` (100 rows/message) but the row
payload still made two socket hops (feeder → manager process → jax child) as
pickle bytes. This module moves the payload out of band: the feeder lays the
chunk out as columnar numpy arrays in a ``multiprocessing.shared_memory``
segment and ships only a tiny descriptor through the Manager; the consumer
copies the columns out at memcpy speed and unlinks the segment.

Columnar layout is what the consumer wants anyway: ``DataFeed.next_batch``
(as_numpy=True) hands the arrays to ``jax.device_put`` without a Python-loop
transpose.

Only rows with a uniform numeric shape ride this lane (tuples/lists of
numeric fields, or bare numeric rows); anything else falls back to the
pickled :class:`Chunk` transparently — ``ShmChunk.from_rows`` returns None
and the caller keeps the old path.
"""

import logging
import secrets

from tensorflowonspark_tpu.marker import Marker

logger = logging.getLogger(__name__)

#: /dev/shm name prefix for feed segments (diagnosable leaks: a crashed
#: consumer leaves ``tosfeed_*`` files behind; see ``unlink_leaked``)
NAME_PREFIX = "tosfeed_"

#: /dev/shm name prefix for decode-plane batch slabs (long-lived pooled
#: segments owned by the creating pipeline, unlike the one-shot ``tosfeed_``
#: chunks that die at materialize)
SLAB_PREFIX = "tosslab_"


def _unregister_from_tracker(name):
    """The creating process hands the segment's lifetime to the consumer;
    without this, the creator's resource_tracker unlinks it at process exit
    (racing the consumer) and spams leak warnings."""
    try:
        from multiprocessing import resource_tracker

        resource_tracker.unregister("/" + name, "shared_memory")
    except Exception:
        pass


class ShmChunk(Marker):
    """Descriptor for one columnar chunk living in a shared-memory segment.

    Wire-side it is a tiny picklable object: segment ``name``, row ``count``,
    and per-column ``(dtype, shape, offset)``. ``single`` distinguishes bare
    rows (one column) from tuple rows (one column per field). ``py_cols``
    records, per column, whether the source values were Python objects
    (lists/ints/floats) rather than numpy — consumers use it to hand back
    the SAME types the feeder saw (a numpy-array row must come back numpy,
    a list row as a list)."""

    __slots__ = ("name", "count", "columns", "single", "py_cols")

    def __init__(self, name, count, columns, single, py_cols=None):
        self.name = name
        self.count = count
        self.columns = columns
        self.single = single
        self.py_cols = tuple(py_cols) if py_cols is not None else (True,) * len(columns)

    def __len__(self):
        return self.count

    # -- producer --------------------------------------------------------------

    @staticmethod
    def from_rows(rows):
        """Build a segment from a list of rows; None if the rows don't have a
        uniform numeric columnar shape (caller falls back to pickled Chunk)."""
        import numpy as np

        if not rows:
            return None
        first = rows[0]
        # Field-tuple rows ((features, label), sorted-input-cols tuples)
        # split one column per field; a bare numeric vector row (784 floats)
        # is ONE logical field. Nested fields or a small width mark a field
        # tuple; a wide all-scalar row stays multi only when its fields mix
        # dtype kinds (one unified column would silently upcast, e.g. an int
        # label among float features).
        def _mixed_kinds(row):
            kinds = set()
            for f in row:
                try:
                    kinds.add(np.asarray(f).dtype.kind)
                except Exception:
                    return False
            return len(kinds) > 1

        multi = (
            isinstance(first, (tuple, list))
            and not any(isinstance(f, (str, bytes)) for f in first)
            and (
                len(first) <= 16
                or any(isinstance(f, (list, tuple, np.ndarray)) for f in first)
                or _mixed_kinds(first)
            )
        )
        single = not multi

        def _is_py(value):
            return not isinstance(value, (np.ndarray, np.generic))

        try:
            if single:
                cols = [np.asarray(rows)]
                py_cols = [_is_py(first)]
            else:
                width = len(first)
                if any(len(r) != width for r in rows):
                    return None
                cols = [np.asarray([r[i] for r in rows]) for i in range(width)]
                py_cols = [_is_py(first[i]) for i in range(width)]
        except (ValueError, TypeError):
            return None
        for c in cols:
            if c.dtype == object or c.dtype.kind in "US":
                return None

        from multiprocessing import shared_memory

        total = sum(int(c.nbytes) for c in cols)
        name = NAME_PREFIX + secrets.token_hex(8)
        try:
            seg = shared_memory.SharedMemory(create=True, size=max(total, 1), name=name)
        except Exception:
            logger.warning("shared memory unavailable; feed falls back to pickle", exc_info=True)
            return None
        columns = []
        offset = 0
        for c in cols:
            c = np.ascontiguousarray(c)
            view = np.ndarray(c.shape, dtype=c.dtype, buffer=seg.buf, offset=offset)
            view[...] = c
            columns.append((c.dtype.str, c.shape, offset))
            offset += int(c.nbytes)
        seg.close()
        _unregister_from_tracker(name)
        return ShmChunk(name, len(rows), columns, single, py_cols)

    # -- consumer --------------------------------------------------------------

    def materialize(self):
        """Copy the columns out and unlink the segment; returns a list of
        numpy arrays (one per column)."""
        import numpy as np
        from multiprocessing import shared_memory

        seg = shared_memory.SharedMemory(name=self.name)
        try:
            out = [
                np.array(
                    np.ndarray(shape, dtype=np.dtype(dtype), buffer=seg.buf, offset=offset),
                    copy=True,
                )
                for dtype, shape, offset in self.columns
            ]
        finally:
            seg.close()
            # attach registered the segment with this process's tracker
            # (CPython pre-3.13 registers on attach too) and unlink()
            # UNREGISTERS it again — sending our own extra unregister after
            # that made the tracker's cache.remove() raise the KeyError
            # tracebacks seen in every dryrun log (MULTICHIP_r04 tail).
            # Only the unlink-already-gone path still needs the manual
            # unregister, to balance the attach-side registration.
            try:
                seg.unlink()
            except FileNotFoundError:
                _unregister_from_tracker(self.name)
        return out

    def rows(self):
        """Materialize as row objects: bare column entries for single-column
        chunks, tuples of per-field values otherwise (each a zero-copy view
        of the materialized column)."""
        cols = self.materialize()
        if self.single:
            return list(cols[0])
        return list(zip(*cols))

    def py_rows(self):
        """Materialize as TYPE-FAITHFUL rows: each field comes back as the
        kind of object the feeder saw — ``tolist`` for Python-sourced
        columns (lists/ints/floats, exact numeric round trip), numpy arrays
        kept numpy. The path for consumers iterating rows without
        ``as_numpy``."""
        raw = self.materialize()
        cols = [
            c.tolist() if py else list(c)
            for c, py in zip(raw, self.py_cols)
        ]
        if self.single:
            return cols[0]
        return list(zip(*cols))

    def discard(self):
        """Unlink without reading (drain paths). unlink() already
        unregisters from this process's tracker — see materialize()."""
        from multiprocessing import shared_memory

        try:
            seg = shared_memory.SharedMemory(name=self.name)
        except FileNotFoundError:
            return
        except Exception:
            logger.warning("failed to discard shm chunk %s", self.name, exc_info=True)
            return
        seg.close()
        try:
            seg.unlink()
        except FileNotFoundError:
            # lost an unlink race: balance the attach-side registration
            _unregister_from_tracker(self.name)
        except Exception:
            logger.warning("failed to discard shm chunk %s", self.name, exc_info=True)


class SlabSegment:
    """One pooled shared-memory slab: a named segment sized for a batch
    buffer, written in place by decode-plane worker processes and viewed
    zero-copy by the producer thread.

    Unlike :class:`ShmChunk` (one-shot: created by the feeder, unlinked by
    the consumer at materialize), a slab lives for the whole pipeline
    iteration and circulates through a free list — the creating process
    owns its lifetime end to end. Attachers (worker processes) call
    :meth:`attach`/:meth:`close`; only the creator calls :meth:`unlink`.
    """

    __slots__ = ("name", "nbytes", "_seg", "_creator")

    def __init__(self, name, nbytes, seg, creator):
        self.name = name
        self.nbytes = nbytes
        self._seg = seg
        self._creator = creator

    @classmethod
    def create(cls, nbytes):
        """Allocate a fresh ``tosslab_`` segment of ``nbytes`` (creator
        side). Raises whatever ``shared_memory`` raises when the platform
        has no usable shm — callers fall back to in-process buffers."""
        from multiprocessing import shared_memory

        name = SLAB_PREFIX + secrets.token_hex(8)
        seg = shared_memory.SharedMemory(create=True, size=max(int(nbytes), 1), name=name)
        return cls(name, seg.size, seg, creator=True)

    @classmethod
    def attach(cls, name):
        """Map an existing slab by name (worker side), with the attach-side
        resource_tracker registration suppressed (pre-3.13 ``SharedMemory``
        registers on attach unconditionally). Two reasons a worker must not
        register: a worker forked before the parent's tracker started would
        spawn its OWN tracker, which unlinks the slab when the worker is
        chaos-killed; and an unregister-after-register dance is not safe
        either — forked workers share one tracker whose cache is a set, so
        N workers' balanced pairs leave N-1 KeyError tracebacks in the
        tracker when the creator's unlink sends the final unregister."""
        from multiprocessing import resource_tracker, shared_memory

        orig_register = resource_tracker.register
        resource_tracker.register = lambda *a, **k: None
        try:
            seg = shared_memory.SharedMemory(name=name)
        finally:
            resource_tracker.register = orig_register
        return cls(name, seg.size, seg, creator=False)

    def ndarray(self, shape, dtype, offset=0):
        """Zero-copy numpy view over the slab (valid until :meth:`close`)."""
        import numpy as np

        return np.ndarray(shape, dtype=np.dtype(dtype), buffer=self._seg.buf, offset=offset)

    def close(self):
        """Drop this process's mapping — which UNMAPS it, dangling any live
        :meth:`ndarray` view (``mmap.close()`` does not honor numpy's base
        reference; observed as a segfault, not an error). Only for
        processes about to exit (decode workers at loop end); the creator
        tears down with :meth:`release` instead."""
        try:
            self._seg.close()
        except BufferError:
            pass

    def release(self):
        """Creator-side teardown: unlink the name and hand the mapping's
        lifetime to the outstanding numpy views. Closing here would unmap
        under any batch view the consumer still holds (see :meth:`close`),
        so the SharedMemory finalizer is disarmed instead — the mmap object
        then lives exactly as long as the last view's base reference and
        unmaps on its own deallocation. No leak, no dangling view."""
        self.unlink()
        self._seg._buf = None
        self._seg._mmap = None

    def unlink(self):
        """Remove the segment name (creator side). unlink() already
        unregisters from this process's tracker; the FileNotFoundError
        branch balances a lost race the same way ShmChunk.discard does."""
        try:
            self._seg.unlink()
        except FileNotFoundError:
            _unregister_from_tracker(self.name)
        except Exception:
            logger.warning("failed to unlink slab %s", self.name, exc_info=True)


def unlink_leaked(max_age_secs=86400):
    """Best-effort cleanup of ``tosfeed_*`` / ``tosslab_*`` segments left by
    crashed consumers (called from executor shutdown). Only touches segments
    older than ``max_age_secs`` to avoid racing in-flight chunks — the
    default is deliberately a full day (in-flight backlogs are bounded by
    feed timeouts, default 600 s); pass 0 only in tests that own every
    segment."""
    import os
    import time

    shm_dir = "/dev/shm"
    if not os.path.isdir(shm_dir):
        return 0
    removed = 0
    now = time.time()
    for fname in os.listdir(shm_dir):
        if not fname.startswith((NAME_PREFIX, SLAB_PREFIX)):
            continue
        path = os.path.join(shm_dir, fname)
        try:
            if now - os.stat(path).st_mtime >= max_age_secs:
                os.unlink(path)
                removed += 1
        except OSError:
            continue
    if removed:
        logger.info("unlinked %d leaked feed segments", removed)
    return removed
