"""Helper API available to user ``main_fun(args, ctx)`` code on each node.

Capability-parity with /root/reference/tensorflowonspark/TFNode.py: filesystem
path normalization, cluster bootstrap, model export, and — the heart of
``InputMode.SPARK`` — the :class:`DataFeed` consumer that turns the executor's
IPC queue into batches ready for ``jax.device_put`` / host infeed.

TPU-native differences:
* ``start_cluster_server`` (TF1 grpc bootstrap, reference TFNode.py:67-129) is
  replaced by ``ctx``-driven ``jax.distributed`` initialization performed by the
  node runtime before ``main_fun`` runs; a stub remains for API familiarity.
* ``DataFeed.next_batch`` can return columnar numpy arrays (``as_numpy=True``)
  so a batch can go straight onto the chips without a Python-loop transpose.
"""

import collections
import getpass
import logging

from tensorflowonspark_tpu import chaos
from tensorflowonspark_tpu.marker import Chunk, EndPartition

logger = logging.getLogger(__name__)


def _is_shm_chunk(item):
    """Type check without importing numpy/shm on the common path."""
    from tensorflowonspark_tpu.shm import ShmChunk

    return isinstance(item, ShmChunk)


class _Block:
    """Marks a multi-row columnar slice inside a per-tensor accumulator (the
    as_numpy+mapping fast lane appends these instead of scalars)."""

    __slots__ = ("arr",)

    def __init__(self, arr):
        self.arr = arr


def _merge_column(entries):
    """Assemble one output column from a mix of per-row values and
    :class:`_Block` slices, preserving order."""
    import numpy as np

    if not any(isinstance(e, _Block) for e in entries):
        return np.asarray(entries)
    parts, scalars = [], []
    for e in entries:
        if isinstance(e, _Block):
            if scalars:
                parts.append(np.asarray(scalars))
                scalars = []
            parts.append(np.asarray(e.arr))
        else:
            scalars.append(e)
    if scalars:
        parts.append(np.asarray(scalars))
    return parts[0] if len(parts) == 1 else np.concatenate(parts)


def _all_numpy(rows):
    """True when every row (and every field of tuple rows) is a numpy value —
    the precondition for type-faithful shared-memory results."""
    import numpy as np

    def _np(v):
        return isinstance(v, (np.ndarray, np.generic))

    return bool(rows) and all(
        all(_np(f) for f in r) if isinstance(r, (tuple, list)) else _np(r)
        for r in rows
    )

#: URI schemes recognized as absolute filesystem locations
#: (reference TFNode.py:40-49, plus ``gs`` as a first-class TPU-era scheme).
_FS_SCHEMES = (
    "file",
    "hdfs",
    "viewfs",
    "gs",
    "s3",
    "s3a",
    "s3n",
    "wasb",
    "wasbs",
    "adl",
    "abfs",
    "abfss",
)


def hdfs_path(ctx, path):
    """Normalize a path relative to the cluster's default filesystem.

    Mirrors reference TFNode.py:29-64: absolute URIs pass through, absolute
    paths are anchored at the default FS, relative paths land under the user's
    home directory on the default FS.
    """
    if any(path.startswith(scheme + "://") for scheme in _FS_SCHEMES):
        return path
    defaultFS = getattr(ctx, "defaultFS", None) or "file://"
    # normalize: keep the '://' but drop any trailing path slash so joins are clean
    base = defaultFS[:-1] if defaultFS.endswith("/") and not defaultFS.endswith("://") else defaultFS
    if path.startswith("/"):
        return base + path
    if base.startswith("file://"):
        # local FS: resolve relative to the working dir like the reference
        import os

        working = getattr(ctx, "working_dir", None) or os.getcwd()
        return "{}{}/{}".format(base, working, path)
    return "{}/user/{}/{}".format(base, getpass.getuser(), path)


def start_cluster_server(ctx, num_gpus=1, rdma=False):
    """Deprecated TF1-era bootstrap (reference TFNode.py:67-129).

    On TPU the distributed runtime is initialized by the node runtime itself
    (jax.distributed over the reservation-elected coordinator) before user code
    runs; there is no per-node server object to start.
    """
    raise NotImplementedError(
        "start_cluster_server is a TF1 grpc concept; the jax.distributed "
        "runtime is already initialized before main_fun runs — use ctx.mesh() "
        "or tensorflowonspark_tpu.parallel directly."
    )


def export_saved_model(*args, **kwargs):
    """Reference TFNode.py:159 exported a TF1 SavedModel; the TPU-native
    equivalent is :mod:`tensorflowonspark_tpu.train.checkpoint` (orbax)."""
    from tensorflowonspark_tpu.train import checkpoint

    return checkpoint.export_saved_model(*args, **kwargs)


class DataFeed:
    """Consumer side of ``InputMode.SPARK`` feeding, running inside the jax
    process; reads items the Spark feed tasks pushed through the executor IPC
    channel (reference TFNode.py:221-329).

    Semantics pinned by the reference and its tests:

    * ``None`` on the queue ⇒ end of feed; ``next_batch`` returns the partial
      batch and ``should_stop()`` becomes True (TFNode.py:267-272).
    * :class:`EndPartition` ⇒ end the current batch early without ending the
      feed (TFNode.py:273-278) — inference uses this to align results with
      partitions.
    * With ``input_mapping``, batches are dicts keyed by tensor/feature name,
      one list (or numpy array) per column, with columns matched to the sorted
      input column order (TFNode.py:261,281-286).
    """

    def __init__(self, mgr, train_mode=True, qname_in="input", qname_out="output", input_mapping=None, use_shm=None):
        import os

        self.mgr = mgr
        self.train_mode = train_mode
        self.qname_in = qname_in
        self.qname_out = qname_out
        self.done_feeding = False
        #: output-lane shared-memory gate: the driver's choice arrives via
        #: ctx.get_data_feed (cluster_meta["feed_shm"]); standalone DataFeeds
        #: fall back to this process's env
        self.use_shm = (
            os.environ.get("TOS_FEED_SHM", "1") == "1" if use_shm is None else bool(use_shm)
        )
        self.input_tensors = (
            [input_mapping[col] for col in sorted(input_mapping)] if input_mapping else None
        )
        #: rows unwrapped from a partially-consumed Chunk, served before the
        #: next proxied queue get (the consumer half of feed-plane chunking)
        self._pending = collections.deque()
        #: a partially-consumed ShmChunk kept COLUMNAR: (columns, single,
        #: cursor, total) — the fast lane for as_numpy+mapping consumers
        self._cols = None
        #: a dequeued Chunk whose task_done is deferred until every row is
        #: consumed — keeps the feeder's unfinished()==0 wait meaning "all
        #: rows trained", not "all messages dequeued"
        self._chunk_open = False

    def next_batch(self, batch_size, as_numpy=False):
        """Get up to ``batch_size`` items from the feed queue.

        Returns a list of items, or — when ``input_mapping`` was supplied — a
        dict of columns keyed by tensor name. ``as_numpy=True`` stacks columns
        into numpy arrays (device-put ready). One proxied queue get fetches a
        whole :class:`~tensorflowonspark_tpu.marker.Chunk` of rows (vs the
        reference's one-round-trip-per-row loop, TFNode.py:243-288); a
        shared-memory chunk consumed by an ``as_numpy`` + ``input_mapping``
        consumer moves COLUMN SLICES, never Python rows — the near-zero-copy
        path from feeder numpy straight to ``jax.device_put``.
        """
        logger.debug("next_batch(%d)", batch_size)
        if chaos.active:
            chaos.delay("feed.slow_consumer")
        queue_in = self.mgr.get_queue(self.qname_in)
        tensors = [] if self.input_tensors is None else {t: [] for t in self.input_tensors}
        count = 0
        columnar_ok = as_numpy and self.input_tensors is not None

        def _consume(row):
            if self.input_tensors is None:
                tensors.append(row)
            else:
                for i, t in enumerate(self.input_tensors):
                    tensors[t].append(row[i])

        def _segment_done():
            self._cols = None
            if self._chunk_open:
                queue_in.task_done()
                self._chunk_open = False

        def _take_columnar(need):
            cols, single, py_cols, cursor, total = self._cols
            n = min(need, total - cursor)
            if columnar_ok and not single and len(cols) == len(self.input_tensors):
                # fast lane: one slice per tensor per chunk (no row objects)
                for i, t in enumerate(self.input_tensors):
                    tensors[t].append(_Block(cols[i][cursor : cursor + n]))
            else:
                # type-faithful rows: Python-sourced columns come back as
                # lists/scalars (tolist), numpy-sourced ones stay numpy —
                # the shm lane must hand user code the SAME kinds of
                # objects the pickled path would
                slices = [
                    c[cursor : cursor + n].tolist()
                    if (py and not as_numpy)
                    else c[cursor : cursor + n]
                    for c, py in zip(cols, py_cols)
                ]
                rows = list(slices[0]) if single else list(zip(*slices))
                for row in rows:
                    _consume(row)
            cursor += n
            if cursor >= total:
                _segment_done()
            else:
                self._cols = (cols, single, py_cols, cursor, total)
            return n

        while count < batch_size:
            if self._cols is not None:
                count += _take_columnar(batch_size - count)
                continue
            if self._pending:
                _consume(self._pending.popleft())
                count += 1
                if not self._pending and self._chunk_open:
                    queue_in.task_done()  # whole chunk now consumed
                    self._chunk_open = False
                continue
            item = queue_in.get(block=True)
            if item is None:
                # end-of-feed marker from shutdown (TFSparkNode.py:560-569)
                logger.info("next_batch: end of feed")
                queue_in.task_done()
                self.done_feeding = True
                break
            elif isinstance(item, EndPartition):
                # end current batch at a partition boundary
                logger.debug("next_batch: end of partition")
                queue_in.task_done()
                if count > 0:
                    break
            elif isinstance(item, Chunk):
                # pickled chunk: rows as the feeder sent them; task_done
                # deferred until the last row is consumed
                self._pending.extend(item.items)
                self._chunk_open = bool(self._pending)
                if not self._pending:  # defensive: empty chunk
                    queue_in.task_done()
            elif _is_shm_chunk(item):
                # shared-memory descriptor: payload never crossed the
                # Manager socket; keep it columnar and slice batches out
                cols = item.materialize()
                if item.count:
                    self._cols = (cols, item.single, item.py_cols, 0, item.count)
                    self._chunk_open = True
                else:
                    queue_in.task_done()
            else:
                _consume(item)
                count += 1
                queue_in.task_done()
        logger.debug("next_batch: returning %d items", count)
        if as_numpy:
            import numpy as np

            if self.input_tensors is None:
                return np.asarray(tensors)
            return {t: _merge_column(col) for t, col in tensors.items()}
        return tensors

    def should_stop(self):
        """True once the end-of-feed marker was consumed."""
        return self.done_feeding

    def batch_results(self, results):
        """Push a batch of inference results to the output queue — one
        chunked message per call; the contract stays 1:1 row-for-row with
        consumed inputs (reference TFNode.py:294-305). Uniform numeric
        results ride the shared-memory lane like the input feed."""
        results = list(results)
        if self.use_shm and _all_numpy(results):
            # numpy-only gate: shm materialization yields numpy values, so
            # only rows that are ALREADY numpy keep their exact types across
            # the lane; Python ints/floats/lists take the pickled path
            # (collectors would otherwise see np types, breaking e.g.
            # json.dumps of collected rows)
            from tensorflowonspark_tpu.shm import ShmChunk

            chunk = ShmChunk.from_rows(results)
            if chunk is not None:
                self.mgr.get_queue(self.qname_out).put(chunk, block=True)
                return
        self.mgr.get_queue(self.qname_out).put(Chunk(results), block=True)

    def terminate(self):
        """Request feeder termination: flips the executor state machine to
        ``'terminating'`` and drains the input queue so blocked feed tasks can
        finish (reference TFNode.py:307-329)."""
        logger.info("DataFeed.terminate: requesting stop of data feed")
        self.mgr.set("state", "terminating")
        queue_in = self.mgr.get_queue(self.qname_in)
        # drain with a short patience window: feed tasks may still be pushing,
        # so the blocking get doubles as the inter-poll pacing
        empty_checks = 0
        while empty_checks < 3:
            try:
                item = queue_in.get(timeout=0.1)
                if _is_shm_chunk(item):
                    item.discard()  # unlink the unread segment
                queue_in.task_done()
                empty_checks = 0
            except Exception:
                empty_checks += 1
