"""Transformer LM training on a cluster — the beyond-parity flagship.

The reference's model zoo stopped at CNNs (ResNet/U-Net/MNIST — SURVEY.md §5
"Long-context / sequence parallelism: absent"); this driver exercises the
TPU-native capabilities the framework adds on top of reference parity:

* flash attention (pallas, `ops/flash_attention.py`) via ``attention=auto``;
* sequence parallelism (`--mesh sp=2 ...` → ring attention over the ``sp``
  axis) for long context;
* tensor parallelism (``--mesh tp=...``, `_TP_RULES` param placement);
* mixture of experts (``--moe_experts N`` over an ``ep`` axis);
* rematerialization (``--remat``) trading FLOPs for HBM.

Data is real: TFRecord text shards stream through the sequence-packing
:class:`~tensorflowonspark_tpu.data.TextPipeline` (per-worker file shards,
FFD packing into ``[B, seq_len+1]`` with segment-id/position columns, the
packed-slab cache with ``--slab_cache_dir``). Without ``--data_dir`` a
deterministic synthetic corpus is materialized on the driver first — same
plumbing, zero setup.

Usage (single host):
    python examples/transformer/transformer_spark.py --train_steps 50 \
        --d_model 512 --n_layers 4 --seq_len 1024
    # 8-way CPU test: --platform cpu --mesh dp=2,tp=2,sp=2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

#: word list for the synthetic corpus — varied lengths so FFD has real work
_WORDS = (
    "the spark cluster streams tokenized text through shared memory slabs "
    "while accelerator meshes consume packed sequences of variable length "
    "records a distributed pipeline keeps every chip busy with deterministic "
    "batches and observability counters tracking efficiency"
).split()


def make_text_corpus(data_dir, num_shards=4, records_per_shard=512, seed=0):
    """Materialize a deterministic synthetic text corpus as TFRecord shards
    (raw UTF-8 records, the ``Tokenizer(field=None)`` shape). Record lengths
    are lognormal-ish so sequence packing has a realistic distribution to
    chew on. Idempotent: existing shards are reused."""
    import numpy as np

    from tensorflowonspark_tpu import tfrecord as tfr

    existing = tfr.list_shards(data_dir) if os.path.isdir(data_dir) else []
    if len(existing) >= num_shards:
        return existing
    os.makedirs(data_dir, exist_ok=True)
    rng = np.random.default_rng(seed)
    for s in range(num_shards):
        path = os.path.join(data_dir, "part-{:05d}".format(s))
        with tfr.TFRecordWriter(path) as w:
            for _ in range(records_per_shard):
                n = max(3, int(rng.lognormal(mean=3.0, sigma=0.6)))
                text = " ".join(rng.choice(_WORDS, size=n))
                w.write(text.encode("utf-8"))
    return tfr.list_shards(data_dir)


def parse_mesh(spec):
    """'dp=2,tp=2,sp=2' → {'dp': 2, 'tp': 2, 'sp': 2} (None: all-dp)."""
    if not spec:
        return None
    axes = {}
    for part in spec.split(","):
        name, _, size = part.partition("=")
        axes[name.strip()] = int(size)
    return axes


def main_fun(args, ctx):
    import time

    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.models import transformer
    from tensorflowonspark_tpu.train import SyncDataParallel, checkpoint

    ctx.initialize_distributed()
    axes = parse_mesh(args.mesh) or {"dp": -1}
    mesh = parallel.local_mesh(axes) if ctx.num_processes == 1 else ctx.mesh(axes)
    model = transformer.create_model(
        mesh=mesh,
        vocab_size=args.vocab_size, d_model=args.d_model,
        n_layers=args.n_layers, n_heads=args.n_heads, d_ff=args.d_ff,
        max_seq_len=args.seq_len, dtype=args.dtype, remat=args.remat,
        moe_experts=args.moe_experts,
    )
    strategy = SyncDataParallel(
        mesh, param_spec_fn=transformer.param_specs if "tp" in mesh.axis_names else None
    )
    optimizer = optax.adamw(args.learning_rate)
    state = strategy.create_state(
        transformer.make_init_fn(model, sample_len=8), optimizer, jax.random.PRNGKey(0)
    )
    loss_fn = transformer.make_loss_fn(model)
    start_step = 0
    if args.model_dir:
        # resume contract (run_with_recovery / job resubmission): continue
        # from the newest checkpoint; sharded target = shard-direct restore
        latest = checkpoint.latest_checkpoint(args.model_dir)
        if latest:
            state = checkpoint.restore_checkpoint(latest, target=state)
            start_step = int(jax.device_get(state.step))
            print("resuming from {} at step {}".format(latest, start_step))
    steps_per_loop = max(args.steps_per_loop, 1)
    if steps_per_loop > 1:
        run = strategy.compile_train_loop(
            loss_fn, optimizer, steps_per_loop, has_aux=True, donate="state"
        )
    else:
        run = strategy.compile_train_step(loss_fn, optimizer, has_aux=True)

    # real corpus: per-worker TFRecord text shards → tokenize → FFD-pack
    # into [B, seq_len+1] (the +1 feeds the shift-by-one LM loss), with
    # segment_ids/positions fencing packed sequences in the attention mask
    from tensorflowonspark_tpu import obs
    from tensorflowonspark_tpu import tfrecord as tfr
    from tensorflowonspark_tpu.data import TextPipeline, Tokenizer, shard_files

    all_files = tfr.list_shards(args.data_dir)
    files = shard_files(all_files, ctx.num_workers, ctx.executor_id)
    if not files:
        # fail loudly NOW: a worker with no data would sit out the
        # collective train steps and hang the whole world at step 1
        raise RuntimeError(
            "worker {} got 0 of {} shard files in {} — distributed training "
            "needs at least num_workers ({}) shard files".format(
                ctx.executor_id, len(all_files), args.data_dir, ctx.num_workers
            )
        )
    tokenizer = Tokenizer(
        kind=args.tokenizer,
        vocab_size=args.vocab_size if args.tokenizer == "word" else None,
    )
    if tokenizer.vocab_size > args.vocab_size:
        raise ValueError(
            "model vocab_size {} smaller than tokenizer vocab {}".format(
                args.vocab_size, tokenizer.vocab_size
            )
        )
    pipe = TextPipeline(
        files, tokenizer, seq_len=args.seq_len + 1, batch_size=args.batch_size,
        seed=ctx.executor_id, epochs=None, max_bad_records=args.max_bad_records,
        pack_workers=args.pack_workers, slab_cache_dir=args.slab_cache_dir,
    )
    stream = iter(pipe)

    def packed_batches():
        for batch in stream:
            yield strategy.shard_batch(batch)

    batches = packed_batches()
    t0, metrics = time.perf_counter(), {}
    i = start_step
    while i < args.train_steps:
        if steps_per_loop > 1 and i + steps_per_loop <= args.train_steps:
            state, metrics = run(state, [next(batches) for _ in range(steps_per_loop)])
            i += steps_per_loop
        else:
            state, metrics = run(state, next(batches))
            i += 1
        if i % args.log_steps == 0 or i >= args.train_steps:
            jax.block_until_ready(metrics["loss"])
            dt = time.perf_counter() - t0
            tps = args.batch_size * args.seq_len * (i - start_step) / dt
            print("step {}: loss {:.3f} ({:.0f} tokens/s)".format(
                i, float(metrics["loss"]), tps))
    stream.close()  # stop the producer (and the pack plane) before teardown
    if args.model_dir and (ctx.distributed or ctx.executor_id == 0):
        checkpoint.save_checkpoint(
            os.path.join(args.model_dir, "ckpt_{}".format(args.train_steps)),
            jax.device_get(state),
        )
    print(
        "transformer training complete: mesh={} packing_efficiency={:.3f}".format(
            dict(zip(mesh.axis_names, mesh.devices.shape)),
            obs.gauge("text_pack_efficiency").value,
        )
    )


def main(argv=None, sc=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--cluster_size", type=int, default=None,
                        help="explicit cluster size (default: from the Spark conf/parallelism under Spark; 1 on the local backend)")
    parser.add_argument("--d_ff", type=int, default=1024)
    parser.add_argument("--d_model", type=int, default=256)
    parser.add_argument("--data_dir", default=None,
                        help="TFRecord text shards (raw UTF-8 records); default: a deterministic synthetic corpus materialized on the driver")
    parser.add_argument("--dtype", default="bfloat16")
    parser.add_argument("--learning_rate", type=float, default=3e-4)
    parser.add_argument("--log_steps", type=int, default=10)
    parser.add_argument("--max_bad_records", type=int, default=0)
    parser.add_argument("--mesh", default=None,
                        help="e.g. dp=2,tp=2,sp=2 (default: all-dp)")
    parser.add_argument("--model_dir", default=None)
    parser.add_argument("--moe_experts", type=int, default=0)
    parser.add_argument("--n_heads", type=int, default=8)
    parser.add_argument("--n_layers", type=int, default=2)
    parser.add_argument("--pack_workers", type=int, default=0,
                        help="0 = in-process thread packing, N = forked pack-plane workers")
    parser.add_argument("--platform", default=None)
    parser.add_argument("--remat", action="store_true")
    parser.add_argument("--seq_len", type=int, default=256)
    parser.add_argument("--slab_cache_dir", default=None,
                        help="packed-slab cache root (epoch >= 2 serves token rows from a memory map)")
    parser.add_argument("--steps_per_loop", type=int, default=1)
    parser.add_argument("--tokenizer", default="byte", choices=("byte", "word"))
    parser.add_argument("--train_steps", type=int, default=20)
    parser.add_argument("--vocab_size", type=int, default=1024)
    args = parser.parse_args(argv)

    if not args.data_dir:
        args.data_dir = os.path.join("/tmp", "tos_transformer_corpus")
        shards = make_text_corpus(args.data_dir)
        print("synthetic text corpus: {} shards in {}".format(len(shards), args.data_dir))

    from tensorflowonspark_tpu import TFCluster

    from tensorflowonspark_tpu.backends import get_spark_context

    # spark-submit / pyspark when present, local backend otherwise;
    # a caller-supplied sc is passed through with owned=False
    sc, args.cluster_size, owned = get_spark_context("transformer_spark", args.cluster_size, sc=sc, local_default=1)
    env = {"JAX_PLATFORMS": args.platform} if args.platform else None
    if args.platform == "cpu" and args.mesh:
        # expose enough virtual devices for the requested mesh
        n = 1
        for v in parse_mesh(args.mesh).values():
            n *= max(v, 1)
        env["TOS_NUM_CPU_DEVICES"] = str(n)
    try:
        cluster = TFCluster.run(
            sc, main_fun, args, args.cluster_size,
            input_mode=TFCluster.InputMode.TENSORFLOW, master_node="chief", env=env,
        )
        cluster.shutdown()
        print("transformer run complete")
    finally:
        if owned:
            sc.stop()


if __name__ == "__main__":
    from tensorflowonspark_tpu import util

    util.setup_logging()
    main()
