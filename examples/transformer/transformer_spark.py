"""Transformer LM training on a cluster — the beyond-parity flagship.

The reference's model zoo stopped at CNNs (ResNet/U-Net/MNIST — SURVEY.md §5
"Long-context / sequence parallelism: absent"); this driver exercises the
TPU-native capabilities the framework adds on top of reference parity:

* flash attention (pallas, `ops/flash_attention.py`) via ``attention=auto``;
* sequence parallelism (`--mesh sp=2 ...` → ring attention over the ``sp``
  axis) for long context;
* tensor parallelism (``--mesh tp=...``, `_TP_RULES` param placement);
* mixture of experts (``--moe_experts N`` over an ``ep`` axis);
* rematerialization (``--remat``) trading FLOPs for HBM.

Data is a synthetic LM stream (seeded per worker) — the point here is the
compute/parallelism path; plug a real corpus by replacing ``token_batches``.

Usage (single host):
    python examples/transformer/transformer_spark.py --train_steps 50 \
        --d_model 512 --n_layers 4 --seq_len 1024
    # 8-way CPU test: --platform cpu --mesh dp=2,tp=2,sp=2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def parse_mesh(spec):
    """'dp=2,tp=2,sp=2' → {'dp': 2, 'tp': 2, 'sp': 2} (None: all-dp)."""
    if not spec:
        return None
    axes = {}
    for part in spec.split(","):
        name, _, size = part.partition("=")
        axes[name.strip()] = int(size)
    return axes


def main_fun(args, ctx):
    import time

    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.models import transformer
    from tensorflowonspark_tpu.train import SyncDataParallel, checkpoint

    ctx.initialize_distributed()
    axes = parse_mesh(args.mesh) or {"dp": -1}
    mesh = parallel.local_mesh(axes) if ctx.num_processes == 1 else ctx.mesh(axes)
    model = transformer.create_model(
        mesh=mesh,
        vocab_size=args.vocab_size, d_model=args.d_model,
        n_layers=args.n_layers, n_heads=args.n_heads, d_ff=args.d_ff,
        max_seq_len=args.seq_len, dtype=args.dtype, remat=args.remat,
        moe_experts=args.moe_experts,
    )
    strategy = SyncDataParallel(
        mesh, param_spec_fn=transformer.param_specs if "tp" in mesh.axis_names else None
    )
    optimizer = optax.adamw(args.learning_rate)
    state = strategy.create_state(
        transformer.make_init_fn(model, sample_len=8), optimizer, jax.random.PRNGKey(0)
    )
    loss_fn = transformer.make_loss_fn(model)
    start_step = 0
    if args.model_dir:
        # resume contract (run_with_recovery / job resubmission): continue
        # from the newest checkpoint; sharded target = shard-direct restore
        latest = checkpoint.latest_checkpoint(args.model_dir)
        if latest:
            state = checkpoint.restore_checkpoint(latest, target=state)
            start_step = int(jax.device_get(state.step))
            print("resuming from {} at step {}".format(latest, start_step))
    steps_per_loop = max(args.steps_per_loop, 1)
    if steps_per_loop > 1:
        run = strategy.compile_train_loop(
            loss_fn, optimizer, steps_per_loop, has_aux=True, donate="state"
        )
    else:
        run = strategy.compile_train_step(loss_fn, optimizer, has_aux=True)

    def token_batches():
        # synthetic LM stream: fixed per-worker seed; replace with a real
        # corpus reader (e.g. data pipeline over tokenized TFRecords)
        rng = np.random.default_rng(ctx.executor_id)
        while True:
            tokens = rng.integers(
                0, args.vocab_size, (args.batch_size, args.seq_len + 1)
            )
            yield strategy.shard_batch({"tokens": tokens})

    batches = token_batches()
    t0, metrics = time.perf_counter(), {}
    i = start_step
    while i < args.train_steps:
        if steps_per_loop > 1 and i + steps_per_loop <= args.train_steps:
            state, metrics = run(state, [next(batches) for _ in range(steps_per_loop)])
            i += steps_per_loop
        else:
            state, metrics = run(state, next(batches))
            i += 1
        if i % args.log_steps == 0 or i >= args.train_steps:
            jax.block_until_ready(metrics["loss"])
            dt = time.perf_counter() - t0
            tps = args.batch_size * args.seq_len * (i - start_step) / dt
            print("step {}: loss {:.3f} ({:.0f} tokens/s)".format(
                i, float(metrics["loss"]), tps))
    if args.model_dir and (ctx.distributed or ctx.executor_id == 0):
        checkpoint.save_checkpoint(
            os.path.join(args.model_dir, "ckpt_{}".format(args.train_steps)),
            jax.device_get(state),
        )
    print("transformer training complete: mesh={}".format(dict(zip(mesh.axis_names, mesh.devices.shape))))


def main(argv=None, sc=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--cluster_size", type=int, default=None,
                        help="explicit cluster size (default: from the Spark conf/parallelism under Spark; 1 on the local backend)")
    parser.add_argument("--d_ff", type=int, default=1024)
    parser.add_argument("--d_model", type=int, default=256)
    parser.add_argument("--dtype", default="bfloat16")
    parser.add_argument("--learning_rate", type=float, default=3e-4)
    parser.add_argument("--log_steps", type=int, default=10)
    parser.add_argument("--mesh", default=None,
                        help="e.g. dp=2,tp=2,sp=2 (default: all-dp)")
    parser.add_argument("--model_dir", default=None)
    parser.add_argument("--moe_experts", type=int, default=0)
    parser.add_argument("--n_heads", type=int, default=8)
    parser.add_argument("--n_layers", type=int, default=2)
    parser.add_argument("--platform", default=None)
    parser.add_argument("--remat", action="store_true")
    parser.add_argument("--seq_len", type=int, default=256)
    parser.add_argument("--steps_per_loop", type=int, default=1)
    parser.add_argument("--train_steps", type=int, default=20)
    parser.add_argument("--vocab_size", type=int, default=1024)
    args = parser.parse_args(argv)

    from tensorflowonspark_tpu import TFCluster

    from tensorflowonspark_tpu.backends import get_spark_context

    # spark-submit / pyspark when present, local backend otherwise;
    # a caller-supplied sc is passed through with owned=False
    sc, args.cluster_size, owned = get_spark_context("transformer_spark", args.cluster_size, sc=sc, local_default=1)
    env = {"JAX_PLATFORMS": args.platform} if args.platform else None
    if args.platform == "cpu" and args.mesh:
        # expose enough virtual devices for the requested mesh
        n = 1
        for v in parse_mesh(args.mesh).values():
            n *= max(v, 1)
        env["TOS_NUM_CPU_DEVICES"] = str(n)
    try:
        cluster = TFCluster.run(
            sc, main_fun, args, args.cluster_size,
            input_mode=TFCluster.InputMode.TENSORFLOW, master_node="chief", env=env,
        )
        cluster.shutdown()
        print("transformer run complete")
    finally:
        if owned:
            sc.stop()


if __name__ == "__main__":
    from tensorflowonspark_tpu import util

    util.setup_logging()
    main()
