"""MNIST training with InputMode.TENSORFLOW — each node reads its own shard
of TFRecords directly from the filesystem (the perf path: no feed queues).

Parity with /root/reference/examples/mnist/keras/mnist_tf_ds.py (TFRecords
read directly per worker with ``ds.shard(num_workers, index)``).

Usage:
    python examples/mnist/mnist_data_setup.py --output /tmp/mnist_tfr
    python examples/mnist/mnist_tf.py --data_dir /tmp/mnist_tfr --cluster_size 2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main_fun(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import tfrecord, parallel
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import SyncDataParallel

    ctx.initialize_distributed()
    mesh = parallel.local_mesh({"dp": -1}) if ctx.num_processes == 1 else ctx.mesh({"dp": -1})
    strategy = SyncDataParallel(mesh)
    model = mnist.create_model("mlp")
    optimizer = optax.adam(args.learning_rate)
    state = strategy.create_state(mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0))
    step = strategy.compile_train_step(mnist.make_loss_fn(model), optimizer, has_aux=True)

    # this worker's shard of the files (reference: ds.shard(num_workers, i))
    shards = tfrecord.list_shards(args.data_dir)
    my_rank = ctx.executor_id
    my_files = [s for i, s in enumerate(shards) if i % ctx.num_workers == my_rank % ctx.num_workers]

    def batches():
        images, labels = [], []
        for _ in range(args.epochs):
            for path in my_files:
                for ex in tfrecord.read_examples(path):
                    images.append(np.asarray(ex["image"][1], np.float32).reshape(28, 28))
                    labels.append(int(ex["label"][1][0]))
                    if len(images) == args.batch_size:
                        yield {"image": np.stack(images), "label": np.asarray(labels)}
                        images, labels = [], []

    metrics = {}
    for i, batch in enumerate(batches()):
        state, metrics = step(state, strategy.shard_batch(batch))
        if (i + 1) % 100 == 0:
            print("step {} loss {:.4f} acc {:.3f}".format(
                i + 1, float(metrics["loss"]), float(metrics["accuracy"])))
    if metrics:
        print("final: loss {:.4f} acc {:.3f}".format(
            float(metrics["loss"]), float(metrics["accuracy"])))


def main(argv=None, sc=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--data_dir", required=True)
    parser.add_argument("--batch_size", type=int, default=64)
    parser.add_argument("--cluster_size", type=int, default=None,
                        help="explicit cluster size (default: from the Spark conf/parallelism under Spark; 2 on the local backend)")
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--learning_rate", type=float, default=1e-3)
    parser.add_argument("--platform", default=None)
    args = parser.parse_args(argv)

    from tensorflowonspark_tpu import TFCluster

    from tensorflowonspark_tpu.backends import get_spark_context

    # spark-submit / pyspark when present, local backend otherwise;
    # a caller-supplied sc is passed through with owned=False
    sc, args.cluster_size, owned = get_spark_context("mnist_tf", args.cluster_size, sc=sc, local_default=2)
    env = {"JAX_PLATFORMS": args.platform} if args.platform else None
    try:
        cluster = TFCluster.run(
            sc, main_fun, args, args.cluster_size,
            input_mode=TFCluster.InputMode.TENSORFLOW, master_node="chief", env=env,
        )
        cluster.shutdown()
        print("training complete")
    finally:
        if owned:
            sc.stop()


if __name__ == "__main__":
    from tensorflowonspark_tpu import util

    util.setup_logging()
    main()
