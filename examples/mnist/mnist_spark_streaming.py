"""MNIST training from a STREAM of micro-batches (InputMode.SPARK).

Parity with /root/reference/examples/mnist/estimator/mnist_spark_streaming.py
(DStream feed :84-144): the reference used Spark Streaming +
``ParameterServerStrategy`` for async training; on TPU there is no PS — the
same capability is micro-batches flowing into the sync feed plane, with the
training loop simply blocking in ``next_batch`` between waves. Stop either
from the driver (``--num_waves`` exhausted → ``cluster.shutdown(ssc)``) or
externally with ``examples/utils/stop_cluster.py <host> <port>`` (the
reference's utils/stop_streaming.py analogue; the server address is printed
at startup).

This example drives the bundled local backend's streaming context (its
``feed()`` API pushes waves incrementally). On real pyspark the same
``cluster.train(dstream)`` path takes an actual DStream — exercised against
a real ``queueStream`` on ``local-cluster`` in
``tests/test_real_pyspark.py::test_streaming_foreachrdd_single_arg``
(pyspark<4: Spark 4 removed DStreams).

Usage:
    python examples/mnist/mnist_spark_streaming.py --cluster_size 2 \
        --num_waves 5 --wave_rows 512 --platform cpu
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main_fun(args, ctx):
    """Runs inside the jax child; trains for as long as micro-batches flow."""
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import SyncDataParallel

    mesh = parallel.local_mesh({"dp": -1}) if ctx.num_processes == 1 else ctx.mesh({"dp": -1})
    strategy = SyncDataParallel(mesh)
    model = mnist.create_model("mlp")
    optimizer = optax.adam(args.learning_rate)
    state = strategy.create_state(mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0))
    step = strategy.compile_train_step(mnist.make_loss_fn(model), optimizer, has_aux=True)

    feed = ctx.get_data_feed(train_mode=True)
    steps = 0
    while not feed.should_stop():
        # blocks while the stream is idle; returns when a batch fills or the
        # shutdown end-of-feed marker arrives
        batch = feed.next_batch(args.batch_size)
        if not batch:
            break
        images = np.asarray([b[0] for b in batch], np.float32).reshape(-1, 28, 28)
        labels = np.asarray([b[1] for b in batch])
        state, metrics = step(state, strategy.shard_batch({"image": images, "label": labels}))
        steps += 1
        if steps % args.log_steps == 0:
            print("streamed step {} loss {:.4f}".format(steps, float(metrics["loss"])))
    print("stream ended after {} steps".format(steps))


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch_size", type=int, default=64)
    parser.add_argument("--batch_interval", type=float, default=0.5)
    parser.add_argument("--cluster_size", type=int, default=2)
    parser.add_argument("--learning_rate", type=float, default=1e-3)
    parser.add_argument("--log_steps", type=int, default=10)
    parser.add_argument("--num_waves", type=int, default=5)
    parser.add_argument("--wave_rows", type=int, default=512)
    parser.add_argument("--platform", default=None)
    args = parser.parse_args(argv)

    from tensorflowonspark_tpu import TFCluster
    from tensorflowonspark_tpu.backends.local import LocalSparkContext, LocalStreamingContext

    sys.path.insert(0, os.path.dirname(__file__))
    from mnist_data_setup import synthetic_mnist

    sc = LocalSparkContext(num_executors=args.cluster_size)
    ssc = LocalStreamingContext(sc, batch_interval=args.batch_interval)
    env = {"JAX_PLATFORMS": args.platform} if args.platform else None
    try:
        cluster = TFCluster.run(
            sc, main_fun, args, args.cluster_size,
            input_mode=TFCluster.InputMode.SPARK, master_node="chief", env=env,
        )
        print("control plane at {}:{} (stop with examples/utils/stop_cluster.py)".format(
            *cluster.cluster_meta["server_addr"]))
        stream = ssc.queueStream()
        cluster.train(stream)  # registers the micro-batch feed
        ssc.start()

        images, labels = synthetic_mnist(args.num_waves * args.wave_rows)
        for wave in range(args.num_waves):
            if cluster.stop_requested:
                print("external stop request — ending stream")
                break
            lo = wave * args.wave_rows
            rows = [
                (images[i].ravel().tolist(), int(labels[i]))
                for i in range(lo, lo + args.wave_rows)
            ]
            ssc.feed(sc.parallelize(rows, 2))
            print("fed wave {}/{}".format(wave + 1, args.num_waves))
            time.sleep(args.batch_interval)

        cluster.shutdown(ssc=ssc, grace_secs=5)
        print("streaming training complete")
    finally:
        sc.stop()


if __name__ == "__main__":
    from tensorflowonspark_tpu import util

    util.setup_logging()
    main()
