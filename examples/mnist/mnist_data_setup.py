"""Prepare MNIST data as CSV-style RDD rows or TFRecords.

Parity with /root/reference/examples/mnist/mnist_data_setup.py (tfds → RDD
CSV :41-42 and → TFRecords via the Hadoop OutputFormat :58-65). This
environment has no network egress, so ``--source synthetic`` (default)
generates a deterministic MNIST-shaped dataset; ``--source tfds`` uses
tensorflow_datasets when available.

Usage (local backend):
    python examples/mnist/mnist_data_setup.py --output /tmp/mnist --format tfrecords
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def synthetic_mnist(num_examples=10000, seed=0):
    """Deterministic MNIST-shaped data: class-dependent blob patterns so
    models can actually learn (test accuracy is meaningful, not 10%)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, num_examples)
    images = rng.normal(0.1, 0.05, (num_examples, 28, 28)).astype(np.float32)
    for digit in range(10):
        mask = labels == digit
        r, c = 4 + 2 * (digit % 5), 6 + 3 * (digit // 5)
        images[mask, r : r + 6, c : c + 6] += 0.8
    return np.clip(images, 0, 1), labels.astype(np.int64)


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--output", required=True, help="output directory")
    parser.add_argument("--format", choices=["tfrecords", "csv"], default="tfrecords")
    parser.add_argument("--source", choices=["synthetic", "tfds"], default="synthetic")
    parser.add_argument("--num_examples", type=int, default=10000)
    parser.add_argument("--num_partitions", type=int, default=4)
    args = parser.parse_args(argv)

    import numpy as np

    if args.source == "tfds":
        import tensorflow_datasets as tfds

        ds = tfds.as_numpy(tfds.load("mnist", split="train", batch_size=-1))
        images = ds["image"].reshape(-1, 28, 28).astype(np.float32) / 255.0
        labels = ds["label"].astype(np.int64)
    else:
        images, labels = synthetic_mnist(args.num_examples)

    from tensorflowonspark_tpu import dfutil
    from tensorflowonspark_tpu.backends import create_dataframe, get_spark_context

    sc, _n, owned = get_spark_context("mnist_data_setup", 2)
    try:
        rows = [
            (images[i].ravel().tolist(), int(labels[i])) for i in range(len(labels))
        ]
        if args.format == "tfrecords":
            df = create_dataframe(sc, rows, ["image", "label"], args.num_partitions)
            dfutil.saveAsTFRecords(df, args.output)
        else:
            os.makedirs(args.output, exist_ok=True)
            with open(os.path.join(args.output, "mnist.csv"), "w") as f:
                for img, lbl in rows:
                    f.write(",".join(str(x) for x in img) + "|" + str(lbl) + "\n")
        print("wrote {} examples to {}".format(len(rows), args.output))
    finally:
        if owned:
            sc.stop()


if __name__ == "__main__":
    from tensorflowonspark_tpu import util

    util.setup_logging()
    main()
