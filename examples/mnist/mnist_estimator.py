"""MNIST train-and-evaluate with a dedicated evaluator node.

Parity with the reference's estimator example
(/root/reference/examples/mnist/estimator/mnist_tf.py:109 — the only
reference workload that sets ``eval_node=True``): workers train and
checkpoint; the evaluator node continuously evaluates the newest checkpoint
and writes eval records next to the model, until the driver shuts the
cluster down (TF's train_and_evaluate loop, reborn as explicit roles).

Usage:
    python examples/mnist/mnist_estimator.py --cluster_size 3 \
        --model_dir /tmp/mnist_est --platform cpu
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main_fun(args, ctx):
    if ctx.job_name == "evaluator":
        _evaluate_forever(args, ctx)
    else:
        _train(args, ctx)


def _train(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import SyncDataParallel, checkpoint, steps_per_worker

    mesh = parallel.local_mesh({"dp": -1}) if ctx.num_processes == 1 else ctx.mesh({"dp": -1})
    strategy = SyncDataParallel(mesh)
    model = mnist.create_model("mlp")
    optimizer = optax.adam(args.learning_rate)
    state = strategy.create_state(mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0))
    step = strategy.compile_train_step(mnist.make_loss_fn(model), optimizer, has_aux=True)

    # LOCKSTEP INVARIANT (multi-process worlds): every training process must
    # execute the same number of collective steps — and therefore the same
    # checkpoint saves — or the world deadlocks at the first divergence. The
    # 0.9 safety factor in steps_per_worker is what guarantees every worker's
    # feed can fill max_steps batches despite uneven partitions (the
    # reference's 90%-of-steps trick, mnist_spark.py:58-64).
    max_steps = steps_per_worker(args.num_examples * args.epochs, args.batch_size, ctx.num_workers)
    feed = ctx.get_data_feed(train_mode=True)
    steps = 0
    is_saver = ctx.distributed or ctx.job_name in ("chief", "master") or ctx.num_workers <= 1
    while not feed.should_stop() and steps < max_steps:
        batch = feed.next_batch(args.batch_size)
        if not batch:
            break
        images = np.asarray([b[0] for b in batch], np.float32).reshape(-1, 28, 28)
        labels = np.asarray([b[1] for b in batch])
        state, metrics = step(state, strategy.shard_batch({"image": images, "label": labels}))
        steps += 1
        if steps % args.checkpoint_steps == 0 and is_saver:
            checkpoint.save_checkpoint(
                os.path.join(args.model_dir, "ckpt_{}".format(steps)), jax.device_get(state))
            print("saved ckpt_{} (loss {:.4f})".format(steps, float(metrics["loss"])))
    if is_saver and steps % args.checkpoint_steps != 0:
        # final model state — the checkpoint the evaluator's last record
        # must come from (train_and_evaluate parity)
        checkpoint.save_checkpoint(
            os.path.join(args.model_dir, "ckpt_{}".format(steps)), jax.device_get(state))
        print("saved final ckpt_{}".format(steps))
    if not feed.should_stop():
        feed.terminate()


def _evaluate_forever(args, ctx):
    """The evaluator role: eval every new checkpoint until shutdown
    (reference estimator continuous-eval loop)."""
    import numpy as np

    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import checkpoint

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from mnist_data_setup import synthetic_mnist

    model = mnist.create_model("mlp")
    images, labels = synthetic_mnist(args.eval_examples, seed=99)
    seen = set()
    while True:  # terminated by driver shutdown
        latest = checkpoint.latest_checkpoint(args.model_dir)
        if latest and latest not in seen:
            seen.add(latest)
            state = checkpoint.restore_checkpoint(latest)
            logits = model.apply({"params": state.params}, np.asarray(images, np.float32))
            acc = float(np.mean(np.argmax(np.asarray(logits), -1) == labels))
            record = {"checkpoint": os.path.basename(latest), "accuracy": acc}
            with open(os.path.join(args.model_dir, "eval_results.jsonl"), "a") as f:
                f.write(json.dumps(record) + "\n")
            print("evaluated {}: accuracy {:.3f}".format(record["checkpoint"], acc))
        time.sleep(0.5)


def main(argv=None, sc=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch_size", type=int, default=64)
    parser.add_argument("--checkpoint_steps", type=int, default=10)
    parser.add_argument("--cluster_size", type=int, default=None,
                        help="explicit cluster size (default: from the Spark conf/parallelism under Spark; 3 on the local backend)")
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--eval_examples", type=int, default=256)
    parser.add_argument("--learning_rate", type=float, default=1e-3)
    parser.add_argument("--model_dir", required=True)
    parser.add_argument("--num_examples", type=int, default=2048)
    parser.add_argument("--platform", default=None)
    args = parser.parse_args(argv)

    from tensorflowonspark_tpu import TFCluster

    sys.path.insert(0, os.path.dirname(__file__))
    from mnist_data_setup import synthetic_mnist

    images, labels = synthetic_mnist(args.num_examples)
    data = [(images[i].ravel().tolist(), int(labels[i])) for i in range(len(labels))]

    from tensorflowonspark_tpu.backends import get_spark_context

    # spark-submit / pyspark when present, local backend otherwise;
    # a caller-supplied sc is passed through with owned=False
    sc, args.cluster_size, owned = get_spark_context("mnist_estimator", args.cluster_size, sc=sc, local_default=3)
    env = {"JAX_PLATFORMS": args.platform} if args.platform else None
    try:
        cluster = TFCluster.run(
            sc, main_fun, args, args.cluster_size,
            input_mode=TFCluster.InputMode.SPARK, master_node="chief",
            eval_node=True, env=env,
        )
        cluster.train(sc.parallelize(data, 4), num_epochs=args.epochs)
        # wait until the NEWEST checkpoint has an eval record (not merely the
        # first one) before tearing the evaluator down
        from tensorflowonspark_tpu.train import checkpoint as ckpt_lib

        deadline = time.time() + 60
        results = os.path.join(args.model_dir, "eval_results.jsonl")
        while time.time() < deadline:
            latest = ckpt_lib.latest_checkpoint(args.model_dir)
            if latest and os.path.exists(results) and os.path.basename(latest) in open(results).read():
                break
            time.sleep(0.5)
        cluster.shutdown(grace_secs=5)
        if os.path.exists(results):
            with open(results) as f:
                print("eval records:\n" + f.read().strip())
        print("estimator training complete")
    finally:
        if owned:
            sc.stop()


if __name__ == "__main__":
    from tensorflowonspark_tpu import util

    util.setup_logging()
    main()
