"""Parallel single-node inference from an exported bundle via TFParallel.

Parity with /root/reference/examples/mnist/keras/mnist_inference.py
(TFParallel + saved_model + per-worker ``ds.shard``, :42).

Usage:
    python examples/mnist/mnist_inference.py --export_dir /tmp/mnist_bundle \
        --output /tmp/mnist_preds --cluster_size 2
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def inference_fun(args, ctx):
    import jax
    import numpy as np

    from tensorflowonspark_tpu.train import export

    predict_fn, params, model_state = export.load_model(args.export_dir)

    sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__))))
    from mnist_data_setup import synthetic_mnist

    images, labels = synthetic_mnist(args.num_examples, seed=99)
    # each instance handles its shard (reference ds.shard(num_workers, i))
    idx = np.arange(ctx.executor_id, len(labels), ctx.num_workers)

    os.makedirs(args.output, exist_ok=True)
    correct = total = 0
    with open(os.path.join(args.output, "part-{:05d}".format(ctx.executor_id)), "w") as f:
        for start in range(0, len(idx), args.batch_size):
            chunk = idx[start : start + args.batch_size]
            out = predict_fn(params, model_state, {"image": images[chunk].reshape(len(chunk), -1)})
            preds = np.asarray(out["prediction"] if isinstance(out, dict) else out)[: len(chunk)]
            for i, p in zip(chunk, preds):
                f.write("{} {}\n".format(labels[i], int(p)))
                correct += int(labels[i] == p)
                total += 1
    print("instance {}: {}/{} correct".format(ctx.executor_id, correct, total))


def main(argv=None, sc=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch_size", type=int, default=256)
    parser.add_argument("--cluster_size", type=int, default=None,
                        help="explicit cluster size (default: from the Spark conf/parallelism under Spark; 2 on the local backend)")
    parser.add_argument("--export_dir", required=True)
    parser.add_argument("--num_examples", type=int, default=2048)
    parser.add_argument("--output", required=True)
    parser.add_argument("--platform", default=None)
    args = parser.parse_args(argv)

    from tensorflowonspark_tpu import TFParallel

    from tensorflowonspark_tpu.backends import get_spark_context

    # spark-submit / pyspark when present, local backend otherwise;
    # a caller-supplied sc is passed through with owned=False
    sc, args.cluster_size, owned = get_spark_context("mnist_inference", args.cluster_size, sc=sc, local_default=2)
    env = {"JAX_PLATFORMS": args.platform} if args.platform else None
    try:
        TFParallel.run(sc, inference_fun, args, args.cluster_size, env=env)
        print("inference shards in", args.output)
    finally:
        if owned:
            sc.stop()


if __name__ == "__main__":
    from tensorflowonspark_tpu import util

    util.setup_logging()
    main()
