"""MNIST through the Spark-ML pipeline API: TFEstimator.fit → TFModel.transform.

Parity with /root/reference/examples/mnist/keras/mnist_pipeline.py (TFEstimator
+ TFModel + dfutil TFRecords, :107-148).

Usage:
    python examples/mnist/mnist_pipeline.py --export_dir /tmp/mnist_bundle
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def train_fun(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import SyncDataParallel, export

    strategy = SyncDataParallel(parallel.local_mesh({"dp": -1}))
    model = mnist.create_model("mlp")
    optimizer = optax.adam(1e-3)
    state = strategy.create_state(mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0))
    step = strategy.compile_train_step(mnist.make_loss_fn(model), optimizer, has_aux=True)

    feed = ctx.get_data_feed(train_mode=True)
    while not feed.should_stop():
        batch = feed.next_batch(args.batch_size)
        if not batch:
            break
        images = np.asarray([b[0] for b in batch], np.float32).reshape(-1, 28, 28)
        labels = np.asarray([b[1] for b in batch])
        state, metrics = step(state, strategy.shard_batch({"image": images, "label": labels}))

    if ctx.job_name in ("chief", "master"):
        params = jax.device_get(state.params)

        def predict_builder():
            import jax as _jax
            import numpy as _np

            from tensorflowonspark_tpu.models import mnist as _mnist

            _model = _mnist.create_model("mlp")
            _predict = _jax.jit(_mnist.make_predict_fn(_model))

            def predict(p, ms, arrays):
                images = _np.asarray(arrays["image"], _np.float32).reshape(-1, 28, 28)
                return {"prediction": _predict(p, {"image": images})}

            return predict

        export.export_model(args.export_dir, predict_builder, params)


def main(argv=None, sc=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch_size", type=int, default=64)
    parser.add_argument("--cluster_size", type=int, default=None,
                        help="explicit cluster size (default: from the Spark conf/parallelism under Spark; 2 on the local backend)")
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--export_dir", required=True)
    parser.add_argument("--num_examples", type=int, default=4096)
    parser.add_argument("--platform", default=None)
    args = parser.parse_args(argv)

    from tensorflowonspark_tpu import pipeline

    sys.path.insert(0, os.path.dirname(__file__))
    from mnist_data_setup import synthetic_mnist

    images, labels = synthetic_mnist(args.num_examples)
    rows = [(images[i].ravel().tolist(), int(labels[i])) for i in range(len(labels))]

    from tensorflowonspark_tpu.backends import create_dataframe, get_spark_context

    # spark-submit / pyspark when present, local backend otherwise;
    # a caller-supplied sc is passed through with owned=False
    sc, args.cluster_size, owned = get_spark_context("mnist_pipeline", args.cluster_size, sc=sc, local_default=2)
    env = {"JAX_PLATFORMS": args.platform} if args.platform else None
    try:
        df = create_dataframe(sc, rows, ["image", "label"], 8)
        est = (
            pipeline.TFEstimator(train_fun, vars(args), env=env)
            .setInputMapping({"image": "image", "label": "label"})
            .setBatchSize(args.batch_size)
            .setEpochs(args.epochs)
            .setClusterSize(args.cluster_size)
            .setExportDir(args.export_dir)
            .setGraceSecs(5)
        )
        model = est.fit(df)

        model.setInputMapping({"image": "image"}).setOutputMapping(
            {"prediction": "prediction"}
        ).setExportDir(args.export_dir)
        test_df = create_dataframe(sc, [(r[0],) for r in rows[:256]], ["image"], 4)
        preds = [r[0] for r in model.transform(test_df).collect()]
        acc = sum(int(p == labels[i]) for i, p in enumerate(preds)) / len(preds)
        print("pipeline inference accuracy on {} rows: {:.3f}".format(len(preds), acc))
    finally:
        if owned:
            sc.stop()


if __name__ == "__main__":
    from tensorflowonspark_tpu import util

    util.setup_logging()
    main()
