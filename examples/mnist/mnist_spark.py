"""MNIST training with InputMode.SPARK — RDD partitions stream into the
cluster's feed queues and each node trains a data-parallel model over its
local chips.

Parity with /root/reference/examples/mnist/keras/mnist_spark.py: same flow
(DataFeed → batches → train → chief exports), with the reference's
90%-of-steps safeguard for uneven partitions surfaced via
``steps_per_worker`` (reference buried it at mnist_spark.py:58-64).

Usage:
    python examples/mnist/mnist_spark.py --cluster_size 2 --epochs 3 \
        --model_dir /tmp/mnist_model --export_dir /tmp/mnist_export

Under spark-submit the same script runs on a real cluster unchanged
(context + executor count resolve via backends.get_spark_context):

    spark-submit --master $MASTER --conf spark.executor.instances=N \
        examples/mnist/mnist_spark.py [args...]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main_fun(args, ctx):
    """Runs inside the jax child process on every cluster node."""
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import SyncDataParallel, checkpoint, export, steps_per_worker

    ctx.initialize_distributed()  # no-op single-host
    mesh = parallel.local_mesh({"dp": -1}) if ctx.num_processes == 1 else ctx.mesh({"dp": -1})
    strategy = SyncDataParallel(mesh)
    model = mnist.create_model("mlp")
    optimizer = optax.adam(args.learning_rate)
    state = strategy.create_state(mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0))
    step = strategy.compile_train_step(mnist.make_loss_fn(model), optimizer, has_aux=True)
    start_step = 0
    if args.model_dir:
        # resume contract (run_with_recovery / job resubmission): continue
        # from the newest checkpoint; sharded target = shard-direct restore
        latest = checkpoint.latest_checkpoint(args.model_dir)
        if latest:
            state = checkpoint.restore_checkpoint(latest, target=state)
            start_step = int(jax.device_get(state.step))
            print("resuming from {} at step {}".format(latest, start_step))

    max_steps = steps_per_worker(args.num_examples * args.epochs, args.batch_size, ctx.num_workers)
    feed = ctx.get_data_feed(train_mode=True)
    steps = start_step
    while not feed.should_stop() and steps < max_steps:
        batch = feed.next_batch(args.batch_size)
        if not batch:
            break
        images = np.asarray([b[0] for b in batch], np.float32).reshape(-1, 28, 28)
        labels = np.asarray([b[1] for b in batch])
        state, metrics = step(state, strategy.shard_batch({"image": images, "label": labels}))
        steps += 1
        if steps % 100 == 0:
            print("step {} loss {:.4f} acc {:.3f}".format(
                steps, float(metrics["loss"]), float(metrics["accuracy"])))
        # in a multi-process world orbax saves are collective — EVERY process
        # must call save (gating on process 0 hangs the barrier); with
        # independent single-process nodes only the chief saves, or the
        # workers would race on the same checkpoint directory
        is_saver = ctx.distributed or ctx.job_name in ("chief", "master") or ctx.num_workers <= 1
        if args.model_dir and steps % args.checkpoint_steps == 0 and is_saver:
            checkpoint.save_checkpoint(
                os.path.join(args.model_dir, "ckpt_{}".format(steps)), jax.device_get(state))
    if not feed.should_stop():
        feed.terminate()

    if args.export_dir and ctx.job_name in ("chief", "master"):
        params = jax.device_get(state.params)

        def predict_builder():
            import jax as _jax

            from tensorflowonspark_tpu.models import mnist as _mnist

            _model = _mnist.create_model("mlp")
            _predict = _mnist.make_predict_fn(_model)
            return _jax.jit(lambda p, ms, a: {"prediction": _predict(p, a)})

        export.export_model(args.export_dir, predict_builder, params)
        print("exported model bundle to", args.export_dir)


def main(argv=None, sc=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch_size", type=int, default=64)
    parser.add_argument("--checkpoint_steps", type=int, default=100)
    parser.add_argument("--cluster_size", type=int, default=None,
                        help="explicit cluster size (default: from the Spark conf/parallelism under Spark; 2 on the local backend)")
    parser.add_argument("--epochs", type=int, default=3)
    parser.add_argument("--learning_rate", type=float, default=1e-3)
    parser.add_argument("--model_dir", default=None)
    parser.add_argument("--export_dir", default=None)
    parser.add_argument("--num_examples", type=int, default=4096)
    parser.add_argument("--num_partitions", type=int, default=8)
    parser.add_argument("--tensorboard", action="store_true")
    parser.add_argument("--platform", default=None, help="force JAX_PLATFORMS in nodes (e.g. cpu)")
    parser.add_argument(
        "--auto_recover", type=int, default=0, metavar="N",
        help="relaunch budget on node failure: run_with_recovery(feed_fn=...) "
             "re-feeds the RDD against the relaunched cluster and nodes resume "
             "from --model_dir's newest checkpoint (requires --model_dir)")
    parser.add_argument(
        "--jax_distributed", choices=["auto", "0", "1"], default="auto",
        help="force the cross-process jax.distributed world on/off "
             "(auto = the framework's default: on when >1 training node)")
    args = parser.parse_args(argv)
    jax_distributed = None if args.jax_distributed == "auto" else args.jax_distributed == "1"
    if args.auto_recover and not args.model_dir:
        parser.error("--auto_recover needs --model_dir (the resume point)")

    from tensorflowonspark_tpu import TFCluster

    sys.path.insert(0, os.path.join(os.path.dirname(__file__)))
    from mnist_data_setup import synthetic_mnist

    images, labels = synthetic_mnist(args.num_examples)
    data = [(images[i].ravel().tolist(), int(labels[i])) for i in range(len(labels))]

    from tensorflowonspark_tpu.backends import get_spark_context

    # spark-submit / pyspark when present, local backend otherwise;
    # a caller-supplied sc is passed through with owned=False
    sc, args.cluster_size, owned = get_spark_context("mnist_spark", args.cluster_size, sc=sc, local_default=2)
    env = {"JAX_PLATFORMS": args.platform} if args.platform else None
    try:
        if args.auto_recover:
            # SPARK-mode recovery: the caller owns the feed, so recovery
            # means re-invoking this feed loop against the relaunched
            # cluster; main_fun resumes from the newest checkpoint
            def feed_fn(cluster):
                cluster.train(
                    sc.parallelize(data, args.num_partitions), num_epochs=args.epochs
                )

            relaunches = TFCluster.run_with_recovery(
                sc, main_fun, args, args.cluster_size,
                max_relaunches=args.auto_recover,
                input_mode=TFCluster.InputMode.SPARK, master_node="chief",
                tensorboard=args.tensorboard, env=env, feed_fn=feed_fn,
                jax_distributed=jax_distributed,
            )
            print("training complete ({} relaunch(es))".format(relaunches))
        else:
            cluster = TFCluster.run(
                sc, main_fun, args, args.cluster_size,
                input_mode=TFCluster.InputMode.SPARK, master_node="chief",
                tensorboard=args.tensorboard, env=env,
                jax_distributed=jax_distributed,
            )
            cluster.train(sc.parallelize(data, args.num_partitions), num_epochs=args.epochs)
            cluster.shutdown(grace_secs=5)
            print("training complete")
    finally:
        if owned:
            sc.stop()


if __name__ == "__main__":
    from tensorflowonspark_tpu import util

    util.setup_logging()
    main()
