"""Request early stop of a live cluster from outside the driver.

Parity with /root/reference/examples/utils/stop_streaming.py (drives
``reservation.Client.request_stop`` against a running cluster, :12-18).

Usage:
    python examples/utils/stop_cluster.py <host> <port>
"""

import sys

sys.path.insert(0, __file__.rsplit("/", 3)[0])

from tensorflowonspark_tpu import reservation


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 2:
        print(__doc__)
        raise SystemExit(2)
    host, port = argv[0], int(argv[1])
    client = reservation.Client((host, port))
    client.request_stop()
    print("requested stop of cluster at {}:{}".format(host, port))


if __name__ == "__main__":
    from tensorflowonspark_tpu import util

    util.setup_logging()
    main()
