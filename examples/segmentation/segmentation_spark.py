"""Image segmentation (U-Net) on a cluster, with distributed inference.

Parity with /root/reference/examples/segmentation/segmentation_spark.py
(U-Net on 128x128x3 → 3 classes, :70-122, converted to TFoS :173-196).
Synthetic shapes dataset replaces oxford_iiit_pet (no egress here): images
contain a bright square whose mask is the prediction target, so pixel
accuracy is meaningful.

Usage:
    python examples/segmentation/segmentation_spark.py --train_steps 20 \
        --cluster_size 2 --platform cpu
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def synthetic_shapes(n, size=128, seed=0):
    import numpy as np

    rng = np.random.default_rng(seed)
    images = rng.normal(0.2, 0.05, (n, size, size, 3)).astype(np.float32)
    masks = np.zeros((n, size, size), np.int64)
    lo, hi = max(size // 8, 2), max(size // 4, 4)
    for i in range(n):
        h, w = rng.integers(lo, hi, 2)
        r, c = rng.integers(0, size - h), rng.integers(0, size - w)
        images[i, r : r + h, c : c + w] += 0.7
        masks[i, r : r + h, c : c + w] = 1
        # second class: a dimmer box
        h2 = w2 = lo
        r2, c2 = rng.integers(0, size - h2), rng.integers(0, size - w2)
        images[i, r2 : r2 + h2, c2 : c2 + w2] += 0.35
        masks[i, r2 : r2 + h2, c2 : c2 + w2] = 2
    return images, masks


def main_fun(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.models import segmentation
    from tensorflowonspark_tpu.train import SyncDataParallel, export

    ctx.initialize_distributed()
    mesh = parallel.local_mesh({"dp": -1}) if ctx.num_processes == 1 else ctx.mesh({"dp": -1})
    strategy = SyncDataParallel(mesh)
    model = segmentation.create_model(
        num_classes=3, base_filters=args.base_filters, depth=args.depth
    )
    optimizer = optax.adam(1e-3)
    state = strategy.create_state(
        segmentation.make_init_fn(model, image_size=args.image_size), optimizer,
        jax.random.PRNGKey(0),
    )
    step = strategy.compile_train_step(
        segmentation.make_loss_fn(model), optimizer, has_aux=True
    )

    images, masks = synthetic_shapes(args.batch_size * 4, args.image_size, seed=ctx.executor_id)
    metrics = {}
    for i in range(args.train_steps):
        sel = np.arange(i * args.batch_size, (i + 1) * args.batch_size) % len(images)
        state, metrics = step(
            state, strategy.shard_batch({"image": images[sel], "mask": masks[sel]})
        )
        if (i + 1) % 10 == 0:
            print("step {}: loss {:.3f} pixel_acc {:.3f}".format(
                i + 1, float(metrics["loss"]), float(metrics["pixel_accuracy"])))
    if metrics:
        print("final pixel accuracy: {:.3f}".format(float(metrics["pixel_accuracy"])))

    if args.export_dir and ctx.job_name in ("chief", "master"):
        params = jax.device_get(state.params)
        cfg = dict(num_classes=3, base_filters=args.base_filters, depth=args.depth)

        def predict_builder():
            import jax as _jax

            from tensorflowonspark_tpu.models import segmentation as _seg

            _model = _seg.create_model(**cfg)
            _predict = _jax.jit(_seg.make_predict_fn(_model))
            return lambda p, ms, a: {"mask": _predict(p, {"image": a["image"]})}

        export.export_model(args.export_dir, predict_builder, params)
        print("exported segmentation bundle to", args.export_dir)


def inference_fun(args, ctx):
    """Independent-instance inference from the exported bundle: each
    TFParallel worker segments its own shard of images (the multi-worker
    inference leg of BASELINE config 5; reference pattern:
    mnist/keras/mnist_inference.py ds.shard per worker)."""
    import numpy as np

    from tensorflowonspark_tpu.train import export

    predict_fn, params, model_state = export.load_model(args.export_dir)
    images, masks = synthetic_shapes(
        args.inference_count, args.image_size, seed=1000 + ctx.executor_id
    )
    # shard: this worker's slice of the global workload
    sel = np.arange(ctx.executor_id, len(images), max(ctx.num_workers, 1))
    out = predict_fn(params, model_state, {"image": images[sel]})
    pred = np.asarray(out["mask"])
    acc = float(np.mean(pred == masks[sel]))
    path = os.path.join(args.export_dir, "inference-{}.txt".format(ctx.executor_id))
    with open(path, "w") as f:
        f.write("{} {} {:.4f}".format(len(sel), pred.shape[1], acc))
    print("worker {} segmented {} images (pixel acc {:.3f})".format(
        ctx.executor_id, len(sel), acc))


def main(argv=None, sc=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--base_filters", type=int, default=16)
    parser.add_argument("--batch_size", type=int, default=8)
    parser.add_argument("--cluster_size", type=int, default=None,
                        help="explicit cluster size (default: from the Spark conf/parallelism under Spark; 1 on the local backend)")
    parser.add_argument("--depth", type=int, default=3)
    parser.add_argument("--export_dir", default=None)
    parser.add_argument("--image_size", type=int, default=128)
    parser.add_argument("--inference_count", type=int, default=16)
    parser.add_argument("--train_steps", type=int, default=20)
    parser.add_argument("--platform", default=None)
    args = parser.parse_args(argv)

    from tensorflowonspark_tpu import TFCluster, TFParallel

    from tensorflowonspark_tpu.backends import get_spark_context

    # spark-submit / pyspark when present, local backend otherwise;
    # a caller-supplied sc is passed through with owned=False
    sc, args.cluster_size, owned = get_spark_context("segmentation_spark", args.cluster_size, sc=sc, local_default=1)
    env = {"JAX_PLATFORMS": args.platform} if args.platform else None
    try:
        cluster = TFCluster.run(
            sc, main_fun, args, args.cluster_size,
            input_mode=TFCluster.InputMode.TENSORFLOW, master_node="chief", env=env,
        )
        cluster.shutdown()
        print("segmentation training complete")
        if args.export_dir:
            # multi-worker inference: N independent instances over the bundle
            TFParallel.run(sc, inference_fun, args, args.cluster_size, env=env)
            print("segmentation inference complete")
    finally:
        if owned:
            sc.stop()


if __name__ == "__main__":
    from tensorflowonspark_tpu import util

    util.setup_logging()
    main()
