"""Write CIFAR/ImageNet-schema TFRecord shards for resnet_spark.py.

The reference assumed pre-existing TFRecords (imagenet_preprocessing.py:144
get_filenames over train-xxxxx-of-01024) and shipped a separate download
pipeline; this environment has no dataset downloads, so this tool writes
shards in the SAME schema from synthetic images (or from numpy .npz files
via --from_npz with arrays ``images`` uint8 NHWC and ``labels``), exercising
the identical read path.

Usage:
    python examples/resnet/resnet_data_setup.py --output /tmp/cifar_tfr \
        --dataset cifar --num_examples 1024 --num_shards 4
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--dataset", choices=["cifar", "imagenet"], default="cifar")
    parser.add_argument("--from_npz", default=None)
    parser.add_argument("--image_size", type=int, default=None)
    parser.add_argument("--num_examples", type=int, default=1024)
    parser.add_argument("--num_shards", type=int, default=4)
    parser.add_argument("--output", required=True)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)

    import numpy as np

    from tensorflowonspark_tpu import tfrecord
    from tensorflowonspark_tpu.data import cifar, imagenet

    if args.dataset == "cifar":
        encode, classes = cifar.encode_example, cifar.NUM_CLASSES
        size = args.image_size or cifar.HEIGHT
    else:
        encode, classes = imagenet.encode_example, imagenet.NUM_CLASSES
        size = args.image_size or imagenet.IMAGE_SIZE

    if args.from_npz:
        data = np.load(args.from_npz)
        images, labels = data["images"], data["labels"]
    else:
        rng = np.random.default_rng(args.seed)
        images = rng.integers(0, 256, (args.num_examples, size, size, 3), dtype=np.uint8)
        labels = rng.integers(0, classes, args.num_examples)

    os.makedirs(args.output, exist_ok=True)
    per = (len(images) + args.num_shards - 1) // args.num_shards
    total = 0
    for s in range(args.num_shards):
        lo, hi = s * per, min((s + 1) * per, len(images))
        path = os.path.join(args.output, "part-{:05d}".format(s))
        with tfrecord.TFRecordWriter(path) as w:
            for i in range(lo, hi):
                w.write(encode(images[i], int(labels[i])))
                total += 1
    print("wrote {} examples in {} shards to {}".format(total, args.num_shards, args.output))


if __name__ == "__main__":
    from tensorflowonspark_tpu import util

    util.setup_logging()
    main()
